"""paddle.static compatibility surface (reference: python/paddle/static/).

The reference's static graph (Program/Executor/feed-fetch) is subsumed by
the jit compile path here — `to_static` traces to one XLA program and the
"executor" is the compiled function cache (SURVEY.md §7: PIR+interpreter →
jaxpr+XLA). This module keeps the reference's entry points importable and
maps them onto that path; InputSpec is the shared shape/dtype declaration.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..base import dtype as dtype_mod


class InputSpec:
    """Shape/dtype/name declaration (reference static/input.py::InputSpec).
    None/-1 dims mark dynamic axes (bucketing boundary under XLA)."""

    def __init__(self, shape: Sequence[Optional[int]], dtype="float32", name=None,
                 stop_gradient=True):
        self.shape = [None if (s is None or s == -1) else int(s) for s in shape]
        self.dtype = dtype_mod.convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name or tensor.name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(list(ndarray.shape), str(ndarray.dtype), name)

    def batch(self, batch_size):
        return InputSpec([batch_size] + self.shape, self.dtype, self.name)

    def unbatch(self):
        if not self.shape:
            raise ValueError("cannot unbatch a 0-D InputSpec")
        return InputSpec(self.shape[1:], self.dtype, self.name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"

    def __eq__(self, other):
        return (isinstance(other, InputSpec) and self.shape == other.shape
                and self.dtype == other.dtype and self.name == other.name)

    def __hash__(self):
        return hash((tuple(self.shape), str(self.dtype), self.name))


from . import nn  # noqa: E402,F401
from .program import (  # noqa: E402,F401
    Executor,
    Program,
    append_backward,
    data,
    default_main_program,
    default_startup_program,
    disable_static,
    enable_static,
    in_static_mode,
    program_guard,
)


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Serialize the recorded program's feed→fetch computation + referenced
    parameters (reference static/io.py::save_inference_model)."""
    import pickle

    import os

    prog = program or default_main_program()
    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) else [fetch_vars]
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    # the replay closure is fully picklable only via its recorded graph: we
    # persist (program nodes are closures) by baking the computation into a
    # StableHLO module through jax.export
    import jax
    import numpy as np

    fetch_ids = [id(t) for t in fetch_vars]
    names = sorted(prog.feeds)

    def fn(*vals):
        return prog._replay(dict(zip(names, vals)), fetch_ids)

    # None/-1 dims in the declared feed shapes export as symbolic dims so
    # the loaded program accepts any batch (jax.export shape polymorphism)
    feed_avals = []
    for i, n in enumerate(names):
        shape, np_dtype = prog.feed_specs[n]
        dims = ",".join(
            f"b{i}_{j}" if (s is None or int(s) < 0) else str(int(s))
            for j, s in enumerate(shape))
        sym = jax.export.symbolic_shape(f"({dims})") if dims else ()
        feed_avals.append(jax.ShapeDtypeStruct(sym, np.dtype(np_dtype)))
    exported = jax.export.export(jax.jit(fn))(*feed_avals)
    payload = {
        "stablehlo": exported.serialize(),
        "feed_names": names,
        "feed_specs": [(tuple(prog.feed_specs[n][0]), str(prog.feed_specs[n][1]))
                       for n in names],
    }
    with open(path_prefix + ".pdmodel", "wb") as f:
        pickle.dump(payload, f)
    return path_prefix


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Returns (program-like callable, feed_names, fetch_count-opaque) in the
    reference's (program, feed_target_names, fetch_targets) shape."""
    import pickle

    with open(path_prefix + ".pdmodel", "rb") as f:
        payload = pickle.load(f)
    import jax

    rebuilt = jax.export.deserialize(payload["stablehlo"])

    class _LoadedProgram:
        feed_names = payload["feed_names"]
        feed_specs = payload["feed_specs"]

        def __call__(self, feed):
            import numpy as np

            vals = [np.asarray(feed[n]) for n in self.feed_names]
            return [np.asarray(o) for o in rebuilt.call(*vals)]

    prog = _LoadedProgram()
    return prog, payload["feed_names"], None


class name_scope:
    def __init__(self, *a, **k):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
