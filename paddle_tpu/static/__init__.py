"""paddle.static compatibility surface (reference: python/paddle/static/).

The reference's static graph (Program/Executor/feed-fetch) is subsumed by
the jit compile path here — `to_static` traces to one XLA program and the
"executor" is the compiled function cache (SURVEY.md §7: PIR+interpreter →
jaxpr+XLA). This module keeps the reference's entry points importable and
maps them onto that path; InputSpec is the shared shape/dtype declaration.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..base import dtype as dtype_mod


class InputSpec:
    """Shape/dtype/name declaration (reference static/input.py::InputSpec).
    None/-1 dims mark dynamic axes (bucketing boundary under XLA)."""

    def __init__(self, shape: Sequence[Optional[int]], dtype="float32", name=None,
                 stop_gradient=True):
        self.shape = [None if (s is None or s == -1) else int(s) for s in shape]
        self.dtype = dtype_mod.convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name or tensor.name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(list(ndarray.shape), str(ndarray.dtype), name)

    def batch(self, batch_size):
        return InputSpec([batch_size] + self.shape, self.dtype, self.name)

    def unbatch(self):
        if not self.shape:
            raise ValueError("cannot unbatch a 0-D InputSpec")
        return InputSpec(self.shape[1:], self.dtype, self.name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"

    def __eq__(self, other):
        return (isinstance(other, InputSpec) and self.shape == other.shape
                and self.dtype == other.dtype and self.name == other.name)

    def __hash__(self):
        return hash((tuple(self.shape), str(self.dtype), self.name))


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None, **kwargs):
    """Maps to jit.save (reference static/io.py::save_inference_model — the
    program+params export path)."""
    program = kwargs.get("program")
    layer = program if program is not None else fetch_vars
    from ..jit.serialization import save as jit_save

    jit_save(layer, path_prefix)


def load_inference_model(path_prefix, executor=None, **kwargs):
    from ..jit.serialization import load as jit_load

    return jit_load(path_prefix)


# no-op graph-mode toggles: eager tracing is always live and to_static
# compiles whole steps, so program guards are identity context managers
class _NullGuard:
    def __init__(self, *a, **k):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


program_guard = _NullGuard
name_scope = _NullGuard


def default_main_program():
    return None


def default_startup_program():
    return None
