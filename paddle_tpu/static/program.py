"""Real static-graph Program + Executor (VERDICT r3 #5).

Reference: python/paddle/static/ — Program/Block over protobuf, Executor
(python/paddle/base/executor.py:1234) driving the C++ StandaloneExecutor.

TPU-native design: static mode records ops AS THEY EXECUTE eagerly on
placeholder tensors (the dispatch layer's static_capture hook appends a
replayable node per op), so the Program is an op list with feed/fetch
bindings instead of a protobuf graph, and shape inference is just eager
execution. Executor.run REPLAYS the recorded ops inside one jax.jit with
the feeds substituted — the whole program compiles to a single XLA
executable per feed signature (the reference's PirInterpreter → one
compiled program; SURVEY §7 maps the interpreter stack to XLA).

Parameters (tensors created outside the program's ops, e.g. by
static.nn.fc) replay by reference: the node reads their CURRENT value at
run time, so weight updates between runs are visible, matching the
reference's scope/variable semantics.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

import jax

from ..core import hooks
from ..core.tensor import Tensor, unwrap


class _Node:
    """One replayable op: the dispatch-level fn + arg bindings.

    Arg bindings: ('v', tensor_id) for values flowing through the program,
    ('t', Tensor) for by-reference constants (parameters), ('lt', [...])
    for lists of tensors, ('c', value) for plain python args.
    """

    __slots__ = ("name", "fn", "attrs", "arg_specs", "out_ids", "out_refs")

    def __init__(self, name, fn, attrs, arg_specs, out_ids, out_refs):
        self.name = name
        self.fn = fn
        self.attrs = attrs
        self.arg_specs = arg_specs
        self.out_ids = out_ids
        # keep the build-time output Tensors alive: ids key the replay env,
        # and a gc'd tensor would let CPython reuse its id for a new one
        self.out_refs = out_refs


class Program:
    """Recorded op graph (reference base/framework.py::Program analog)."""

    def __init__(self):
        self.ops: List[_Node] = []
        self.feeds: Dict[str, int] = {}        # feed name -> placeholder id
        self.feed_specs: Dict[str, tuple] = {} # feed name -> (shape, dtype)
        self._version = 0
        self._lock = threading.Lock()  # noqa: CX1003 — static-graph bootstrap: imported before observability exists

    # -- recording (installed as hooks.static_capture) ----------------------
    def record(self, name, fn, tensor_args, attrs, outs):
        def bind(a):
            if isinstance(a, Tensor):
                return ("v", id(a), a)  # resolved to 't' if never produced
            if isinstance(a, (list, tuple)) and any(
                    isinstance(x, Tensor) for x in a):
                return ("lt", [bind(x) for x in a])
            return ("c", a)

        out_list = outs if isinstance(outs, tuple) else (outs,)
        with self._lock:
            self.ops.append(_Node(
                name, fn, dict(attrs),
                [bind(a) for a in tensor_args],
                [id(o) for o in out_list],
                list(out_list),
            ))
            self._version += 1

    def add_feed(self, name, placeholder, shape, dtype):
        self.feeds[name] = id(placeholder)
        self.feed_specs[name] = (tuple(shape), str(dtype))
        self._placeholders = getattr(self, "_placeholders", [])
        self._placeholders.append(placeholder)
        self._version += 1

    # -- introspection -------------------------------------------------------
    def op_types(self) -> List[str]:
        return [n.name for n in self.ops]

    def __repr__(self):
        return (f"Program(feeds={list(self.feeds)}, "
                f"ops={len(self.ops)}: {self.op_types()[:8]}...)")

    def clone(self, for_test: bool = False) -> "Program":
        p = Program()
        p.ops = list(self.ops)
        p.feeds = dict(self.feeds)
        p.feed_specs = dict(self.feed_specs)
        # the placeholder Tensors whose ids key `feeds` must stay alive via
        # the clone too: a clone that outlives the original would otherwise
        # replay against reused ids (analysis PV009 clone invariant)
        p._placeholders = list(getattr(self, "_placeholders", []))
        return p

    # -- verification (paddle_tpu.analysis.program_verify) -------------------
    def verify(self, fetch_list=None, raise_on_error: bool = True):
        """Well-formedness pass over the recorded IR (the reference's PIR
        verify analog): SSA/def-before-use, feed/fetch resolution, recorded
        shape/dtype vs producer, signature arity vs ops/op_defs.py, dead
        nodes. Returns the findings list; raises ``EnforceError`` on any
        error-severity finding unless ``raise_on_error=False``."""
        from ..analysis import errors as _errors
        from ..analysis.program_verify import verify_program

        fetch_ids = None
        if fetch_list is not None:
            fetch_ids = [t if isinstance(t, int) else id(t) for t in fetch_list]
        findings = verify_program(self, fetch_ids=fetch_ids)
        errors = _errors(findings)
        if errors and raise_on_error:
            from ..base.enforce import PreconditionNotMetError

            raise PreconditionNotMetError(
                "Program.verify failed:\n  " + "\n  ".join(str(f) for f in errors))
        return findings

    def constants(self) -> Dict[int, Tensor]:
        """By-reference constant tensors (parameters): 'v' bindings never
        produced by an op nor declared as feeds. Their CURRENT values enter
        the compiled replay as arguments, so set_value between runs is
        visible (reference scope semantics) without recompiling."""
        produced = set()
        for node in self.ops:
            produced.update(node.out_ids)
        feed_ids = set(self.feeds.values())
        out: Dict[int, Tensor] = {}

        def scan(spec):
            if spec[0] == "v":
                _, tid, tensor = spec
                if tid not in produced and tid not in feed_ids:
                    out.setdefault(tid, tensor)
            elif spec[0] == "lt":
                for s in spec[1]:
                    scan(s)

        for node in self.ops:
            for spec in node.arg_specs:
                scan(spec)
        return out

    # -- replay --------------------------------------------------------------
    def _replay(self, feed_values: Dict[str, object], fetch_ids: Sequence[int],
                const_values: Optional[Dict[int, object]] = None):
        env: Dict[int, object] = dict(const_values or {})
        for name, fid in self.feeds.items():
            env[fid] = feed_values[name]

        def resolve(spec):
            kind = spec[0]
            if kind == "v":
                _, tid, tensor = spec
                if tid in env:
                    return env[tid]
                # not a program value: a by-reference constant (parameter)
                return unwrap(tensor)
            if kind == "lt":
                return [resolve(s) for s in spec[1]]
            return spec[1]

        for node in self.ops:
            out = node.fn(*[resolve(s) for s in node.arg_specs], **node.attrs)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            for tid, val in zip(node.out_ids, outs):
                env[tid] = val
        missing = [i for i in fetch_ids if i not in env]
        if missing:
            raise KeyError(
                "fetch targets were not produced by this program (fetch a "
                "Tensor created inside program_guard / static mode)")
        return [env[i] for i in fetch_ids]


_default_main = Program()
_default_startup = Program()
_static_mode = False


def default_main_program() -> Program:
    return _default_main


def default_startup_program() -> Program:
    return _default_startup


def enable_static():
    """paddle.enable_static analog: ops start recording into the default
    main program (they still execute eagerly on placeholder values, which
    is what performs shape/dtype inference)."""
    global _static_mode
    _static_mode = True
    hooks.static_capture = _default_main


def disable_static():
    global _static_mode
    _static_mode = False
    hooks.static_capture = None


def in_static_mode() -> bool:
    return _static_mode


class program_guard:
    """Record into specific programs within the block (reference
    static/program_guard)."""

    def __init__(self, main_program: Program, startup_program: Optional[Program] = None):
        self.main = main_program
        self.startup = startup_program
        self._prev = None

    def __enter__(self):
        self._prev = hooks.static_capture
        hooks.static_capture = self.main
        return self

    def __exit__(self, *exc):
        hooks.static_capture = self._prev
        return False


def data(name: str, shape: Sequence[Optional[int]], dtype="float32",
         lod_level=0) -> Tensor:
    """Declare a feed variable (reference static/input.py::data): returns a
    placeholder Tensor (None/-1 dims become 1 for build-time inference) and
    registers it with the recording program."""
    from ..base import dtype as dtype_mod

    concrete = [1 if (s is None or int(s) < 0) else int(s) for s in shape]
    np_dtype = dtype_mod.convert_dtype(dtype).np_dtype
    placeholder = Tensor(np.zeros(concrete, np_dtype), name=name,
                         stop_gradient=True)
    prog = hooks.static_capture or _default_main
    if isinstance(prog, Program):
        prog.add_feed(name, placeholder, shape, np_dtype)
    return placeholder


class Executor:
    """Replay-and-compile executor (reference base/executor.py::Executor →
    StandaloneExecutor; here: one jax.jit per (program version, feed
    signature))."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program: Optional[Program] = None, feed=None, fetch_list=None,
            return_numpy=True):
        program = program or _default_main
        feed = feed or {}
        fetch_list = fetch_list or []
        if not isinstance(program, Program):
            if callable(program) and hasattr(program, "feed_names"):
                return program(feed)  # loaded inference program
            raise TypeError(f"Executor.run expects a Program, got {type(program)}")
        if not program.ops:
            return []  # startup program: parameters already initialized eagerly
        fetch_ids = [id(t) for t in fetch_list]

        from ..base.flags import get_flag

        if get_flag("static_verify_program"):
            # debug gate (FLAGS_static_verify_program): run the analysis
            # verify pass once per program version before compiling it.
            # The marker lives ON the program so a reused id of a collected
            # program can never skip verification of a new one.
            key = (program._version, tuple(fetch_ids))
            done = getattr(program, "_verified_keys", None)
            if done is None:
                done = program._verified_keys = set()
            if key not in done:
                program.verify(fetch_list=fetch_ids)
                done.add(key)

        feed_vals = {}
        for name in program.feeds:
            if name not in feed:
                raise KeyError(f"missing feed '{name}'")
            feed_vals[name] = np.asarray(feed[name])
        sig = (program._version, tuple(sorted(
            (n, v.shape, str(v.dtype)) for n, v in feed_vals.items())),
            tuple(fetch_ids))
        consts = program.constants()
        const_ids = sorted(consts)
        compiled = self._cache.get(sig)
        if compiled is None:
            names = sorted(feed_vals)

            def fn(feed_list, const_list):
                return program._replay(dict(zip(names, feed_list)), fetch_ids,
                                       dict(zip(const_ids, const_list)))

            compiled = (names, jax.jit(fn))
            self._cache[sig] = compiled
        names, jitted = compiled
        outs = jitted([feed_vals[n] for n in names],
                      [unwrap(consts[i]) for i in const_ids])
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]

    def close(self):
        self._cache.clear()


def append_backward(loss, parameter_list=None, no_grad_set=None):
    """Static-mode autodiff (reference base/backward.py::append_backward):
    in the replay design gradients come from jax.grad over the replayed
    program — expose the standard API returning (param, grad placeholder)
    pairs; Executor resolves them through the same replay."""
    raise NotImplementedError(
        "append_backward: train static programs through paddle.jit / "
        "TrainStep (the compiled-train-step path); Executor covers the "
        "feed/fetch inference contract")
