"""paddle.static.nn layer builders (reference python/paddle/static/nn/).

Each builder creates its parameters EAGERLY (outside program recording, so
they are by-reference constants that persist across Executor.run calls) and
then applies the compute ops, which record into the active Program.
"""
from __future__ import annotations

import contextlib

import numpy as np

from ..core import hooks
from ..core.tensor import Tensor, unwrap


@contextlib.contextmanager
def _no_capture():
    prev, hooks.static_capture = hooks.static_capture, None
    try:
        yield
    finally:
        hooks.static_capture = prev


def _param(shape, dtype, scale=None):
    from ..base import global_state

    with _no_capture():
        import jax

        key = global_state.default_generator.split()
        if scale is None:
            scale = float(np.sqrt(2.0 / max(int(shape[0]), 1)))
        val = jax.random.normal(key, tuple(shape), np.dtype(dtype)) * scale
        p = Tensor(val, stop_gradient=False)
    return p


def fc(x, size, num_flatten_dims=1, activation=None, name=None,
       weight_attr=None, bias_attr=None):
    """Fully-connected layer (reference static/nn/common.py::fc)."""
    from ..ops import math as om

    in_dim = 1
    for s in unwrap(x).shape[num_flatten_dims:]:
        in_dim *= int(s)
    w = _param((in_dim, size), unwrap(x).dtype)
    b = _param((size,), unwrap(x).dtype, scale=0.0)
    from ..ops import manipulation

    flat = x
    if unwrap(x).ndim > num_flatten_dims + 1:
        lead = list(unwrap(x).shape[:num_flatten_dims])
        flat = manipulation.reshape(x, lead + [in_dim])
    out = om.add(om.matmul(flat, w), b)
    return _maybe_act(out, activation)


def embedding(input, size, is_sparse=False, padding_idx=None, dtype="float32",
              name=None, param_attr=None):
    """reference static/nn/common.py::embedding."""
    table = _param(size, np.dtype(dtype), scale=0.02)
    from ..nn import functional as F

    return F.embedding(input, table)


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, name=None,
               **kwargs):
    """Inference-style batch norm over recorded stats (reference
    static/nn/common.py::batch_norm, is_test path)."""
    c = int(unwrap(input).shape[1])
    gamma = _param((c,), unwrap(input).dtype, scale=0.0)
    with _no_capture():
        gamma.set_value(np.ones((c,), np.dtype(str(unwrap(input).dtype))))
    beta = _param((c,), unwrap(input).dtype, scale=0.0)
    mean = _param((c,), unwrap(input).dtype, scale=0.0)
    var = _param((c,), unwrap(input).dtype, scale=0.0)
    with _no_capture():
        var.set_value(np.ones((c,), np.dtype(str(unwrap(input).dtype))))
    from ..nn import functional as F

    out = F.batch_norm(input, mean, var, weight=gamma, bias=beta,
                       training=False, momentum=momentum, epsilon=epsilon)
    return _maybe_act(out, act)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, act=None, name=None, **kwargs):
    """reference static/nn/common.py::conv2d."""
    c_in = int(unwrap(input).shape[1])
    ks = filter_size if isinstance(filter_size, (list, tuple)) else (
        filter_size, filter_size)
    w = _param((num_filters, c_in // groups, ks[0], ks[1]), unwrap(input).dtype)
    from ..nn import functional as F

    out = F.conv2d(input, w, stride=stride, padding=padding,
                   dilation=dilation, groups=groups)
    return _maybe_act(out, act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, act=None, name=None, **kwargs):
    """reference static/nn/common.py::layer_norm (normalizes over dims
    [begin_norm_axis:])."""
    from ..nn import functional as F

    shape = [int(s) for s in unwrap(input).shape[begin_norm_axis:]]
    g = _param(shape, unwrap(input).dtype, scale=0.0) if scale else None
    if g is not None:
        with _no_capture():
            g.set_value(np.ones(shape, np.dtype(str(unwrap(input).dtype))))
    b = _param(shape, unwrap(input).dtype, scale=0.0) if shift else None
    out = F.layer_norm(input, shape, weight=g, bias=b, epsilon=epsilon)
    return _maybe_act(out, act)


def group_norm(input, groups, epsilon=1e-5, act=None, name=None, **kwargs):
    """reference static/nn/common.py::group_norm."""
    from ..nn import functional as F

    c = int(unwrap(input).shape[1])
    g = _param((c,), unwrap(input).dtype, scale=0.0)
    with _no_capture():
        g.set_value(np.ones((c,), np.dtype(str(unwrap(input).dtype))))
    b = _param((c,), unwrap(input).dtype, scale=0.0)
    out = F.group_norm(input, groups, epsilon=epsilon, weight=g, bias=b)
    return _maybe_act(out, act)


def instance_norm(input, epsilon=1e-5, name=None, **kwargs):
    """reference static/nn/common.py::instance_norm."""
    from ..nn import functional as F

    c = int(unwrap(input).shape[1])
    g = _param((c,), unwrap(input).dtype, scale=0.0)
    with _no_capture():
        g.set_value(np.ones((c,), np.dtype(str(unwrap(input).dtype))))
    b = _param((c,), unwrap(input).dtype, scale=0.0)
    return F.instance_norm(input, weight=g, bias=b, eps=epsilon)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, act=None, name=None, **kwargs):
    """reference static/nn/common.py::conv3d."""
    from ..nn import functional as F

    c_in = int(unwrap(input).shape[1])
    ks = filter_size if isinstance(filter_size, (list, tuple)) else (
        filter_size,) * 3
    w = _param((num_filters, c_in // groups, *ks), unwrap(input).dtype)
    out = F.conv3d(input, w, stride=stride, padding=padding,
                   dilation=dilation, groups=groups)
    return _maybe_act(out, act)



def _transpose_ks(v_shape, filter_size, output_size, stride, padding, nd,
                  dilation=1):
    """filter_size, or derived from output_size (reference conv*d_transpose:
    out = (in-1)*stride - 2*pad + dilation*(ks-1) + 1 per spatial dim)."""
    if filter_size is not None:
        return (tuple(filter_size) if isinstance(filter_size, (list, tuple))
                else (filter_size,) * nd)
    if output_size is None:
        raise ValueError("one of filter_size / output_size is required")

    def tup(x):
        return tuple(x) if isinstance(x, (list, tuple)) else (x,) * nd

    outs, strides, pads, dils = (tup(output_size), tup(stride), tup(padding),
                                 tup(dilation))
    ins = v_shape[2:2 + nd]
    ks = []
    for o, i, s, p, d in zip(outs, ins, strides, pads, dils):
        span = int(o) - (int(i) - 1) * int(s) + 2 * int(p) - 1
        if span < 0 or span % int(d):
            raise ValueError(
                f"output_size {outs} unreachable from input {tuple(ins)} "
                f"with stride {strides} / padding {pads} / dilation {dils}")
        ks.append(span // int(d) + 1)
    return tuple(ks)


def conv2d_transpose(input, num_filters, filter_size=None, output_size=None,
                     stride=1, padding=0, dilation=1, groups=1, act=None,
                     name=None, **kwargs):
    """reference static/nn/common.py::conv2d_transpose."""
    from ..nn import functional as F

    c_in = int(unwrap(input).shape[1])
    ks = _transpose_ks(unwrap(input).shape, filter_size, output_size,
                       stride, padding, 2, dilation)
    w = _param((c_in, num_filters // groups, ks[0], ks[1]),
               unwrap(input).dtype)
    out = F.conv2d_transpose(input, w, stride=stride, padding=padding,
                             dilation=dilation, groups=groups,
                             output_size=output_size)
    return _maybe_act(out, act)


def conv3d_transpose(input, num_filters, filter_size=None, output_size=None,
                     stride=1, padding=0, dilation=1, groups=1, act=None,
                     name=None, **kwargs):
    """reference static/nn/common.py::conv3d_transpose."""
    from ..nn import functional as F

    c_in = int(unwrap(input).shape[1])
    ks = _transpose_ks(unwrap(input).shape, filter_size, output_size,
                       stride, padding, 3, dilation)
    w = _param((c_in, num_filters // groups, *ks), unwrap(input).dtype)
    out = F.conv3d_transpose(input, w, stride=stride, padding=padding,
                             dilation=dilation, groups=groups,
                             output_size=output_size)
    return _maybe_act(out, act)


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    """reference static/nn/common.py::prelu; mode in {'all','channel',
    'element'} sizes the slope parameter."""
    from ..ops import activation as act_mod

    v = unwrap(x)
    if mode == "all":
        shape = (1,)
    elif mode == "channel":
        shape = (int(v.shape[1]),)
    elif mode == "element":
        shape = tuple(int(s) for s in v.shape[1:])
    else:
        raise ValueError(f"prelu mode {mode!r}")
    w = _param(shape, v.dtype, scale=0.0)
    with _no_capture():
        w.set_value(np.full(shape, 0.25, np.dtype(str(v.dtype))))
    return act_mod.prelu(x, w, data_format=data_format)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """reference static/nn/common.py::spectral_norm — weight normalized by
    its largest singular value (power iteration over persistent u/v)."""
    from ..ops import misc_ops

    v = unwrap(weight)
    h = int(v.shape[dim])
    w = 1
    for i, s in enumerate(v.shape):
        if i != dim:
            w *= int(s)
    u_vec = _param((h,), v.dtype, scale=1.0)
    v_vec = _param((w,), v.dtype, scale=1.0)
    return misc_ops.spectral_norm(weight, u_vec, v_vec, dim=dim,
                                  power_iters=power_iters, eps=eps)


def _maybe_act(out, act):
    if act:
        from ..ops import activation as act_mod

        return getattr(act_mod, act)(out)
    return out


# static-mode structured control flow (reference static/nn/control_flow.py)
from .control_flow import case, cond, switch_case, while_loop  # noqa: E402,F401
