"""paddle.static.nn layer builders (reference python/paddle/static/nn/).

Each builder creates its parameters EAGERLY (outside program recording, so
they are by-reference constants that persist across Executor.run calls) and
then applies the compute ops, which record into the active Program.
"""
from __future__ import annotations

import contextlib

import numpy as np

from ..core import hooks
from ..core.tensor import Tensor, unwrap


@contextlib.contextmanager
def _no_capture():
    prev, hooks.static_capture = hooks.static_capture, None
    try:
        yield
    finally:
        hooks.static_capture = prev


def _param(shape, dtype, scale=None):
    from ..base import global_state

    with _no_capture():
        import jax

        key = global_state.default_generator.split()
        if scale is None:
            scale = float(np.sqrt(2.0 / max(int(shape[0]), 1)))
        val = jax.random.normal(key, tuple(shape), np.dtype(dtype)) * scale
        p = Tensor(val, stop_gradient=False)
    return p


def fc(x, size, num_flatten_dims=1, activation=None, name=None,
       weight_attr=None, bias_attr=None):
    """Fully-connected layer (reference static/nn/common.py::fc)."""
    from ..ops import math as om

    in_dim = 1
    for s in unwrap(x).shape[num_flatten_dims:]:
        in_dim *= int(s)
    w = _param((in_dim, size), unwrap(x).dtype)
    b = _param((size,), unwrap(x).dtype, scale=0.0)
    from ..ops import manipulation

    flat = x
    if unwrap(x).ndim > num_flatten_dims + 1:
        lead = list(unwrap(x).shape[:num_flatten_dims])
        flat = manipulation.reshape(x, lead + [in_dim])
    out = om.add(om.matmul(flat, w), b)
    if activation:
        from ..ops import activation as act_mod

        out = getattr(act_mod, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None, dtype="float32",
              name=None, param_attr=None):
    """reference static/nn/common.py::embedding."""
    table = _param(size, np.dtype(dtype), scale=0.02)
    from ..nn import functional as F

    return F.embedding(input, table)


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, name=None,
               **kwargs):
    """Inference-style batch norm over recorded stats (reference
    static/nn/common.py::batch_norm, is_test path)."""
    c = int(unwrap(input).shape[1])
    gamma = _param((c,), unwrap(input).dtype, scale=0.0)
    with _no_capture():
        gamma.set_value(np.ones((c,), np.dtype(str(unwrap(input).dtype))))
    beta = _param((c,), unwrap(input).dtype, scale=0.0)
    mean = _param((c,), unwrap(input).dtype, scale=0.0)
    var = _param((c,), unwrap(input).dtype, scale=0.0)
    with _no_capture():
        var.set_value(np.ones((c,), np.dtype(str(unwrap(input).dtype))))
    from ..nn import functional as F

    out = F.batch_norm(input, mean, var, weight=gamma, bias=beta,
                       training=False, momentum=momentum, epsilon=epsilon)
    if act:
        from ..ops import activation as act_mod

        out = getattr(act_mod, act)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, act=None, name=None, **kwargs):
    """reference static/nn/common.py::conv2d."""
    c_in = int(unwrap(input).shape[1])
    ks = filter_size if isinstance(filter_size, (list, tuple)) else (
        filter_size, filter_size)
    w = _param((num_filters, c_in // groups, ks[0], ks[1]), unwrap(input).dtype)
    from ..nn import functional as F

    out = F.conv2d(input, w, stride=stride, padding=padding,
                   dilation=dilation, groups=groups)
    if act:
        from ..ops import activation as act_mod

        out = getattr(act_mod, act)(out)
    return out
