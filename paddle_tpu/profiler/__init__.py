from .pipeline import PipelineStats, pipeline_stats  # noqa: F401
from .profiler import (  # noqa: F401
    Profiler,
    ProfilerState,
    ProfilerTarget,
    RecordEvent,
    SortedKeys,
    export_chrome_tracing,
    make_scheduler,
)
