"""Per-step input-pipeline breakdown: where does a train step's wall time go?

The async XLA dispatch model hides host→device transfer and host-side
dispatch behind device compute — but only when the loop around the compiled
step actually lets it (no per-step ``.numpy()``, batches staged ahead of
consumption). This module is the observability half of that contract: the
``DeviceLoader`` (io/device_prefetch.py), ``MetricBuffer``
(hapi/metric_buffer.py) and the hapi/bench train loops report their waits
into one process-global :class:`PipelineStats`, and ``bench.py`` publishes
the summary under ``extras.pipeline``:

- ``h2d_wait_us``   — time the consumer blocked waiting for the next
  device-resident batch (0 when prefetch keeps up: the H2D overlapped the
  previous step's compute);
- ``h2d_issue_us``  — time the prefetch worker spent issuing
  ``jax.device_put`` (the transfer cost that is being hidden);
- ``dispatch_us``   — time inside the compiled step call (enqueue + for
  synchronous backends the compute itself);
- ``host_sync_us``  / ``host_syncs_per_step`` — time and count of blocking
  device→host reads (metric materialization). The steady-state target is
  **zero per step**: syncs belong at log/epoch boundaries.
- ``overlap_ratio`` — fraction of issued H2D time the consumer never
  waited for (1.0 = transfers fully hidden).

Recording costs two ``perf_counter`` calls per event — cheap enough to
leave on; ``reset()`` starts a fresh window.
"""
from __future__ import annotations

import time

from ..observability.locks import named_lock


class PipelineStats:
    """Thread-safe accumulator for the per-step pipeline breakdown."""

    def __init__(self):
        self._lock = named_lock("profiler.pipeline_stats")
        self.reset()

    def reset(self):
        with self._lock:
            self.steps = 0
            self.h2d_wait_s = 0.0
            self.h2d_issue_s = 0.0
            self.dispatch_s = 0.0
            self.host_sync_s = 0.0
            self.host_syncs = 0

    # ------------------------------------------------------------ recording
    def add_h2d_wait(self, seconds: float):
        with self._lock:
            self.h2d_wait_s += seconds

    def add_h2d_issue(self, seconds: float):
        with self._lock:
            self.h2d_issue_s += seconds

    def add_dispatch(self, seconds: float):
        with self._lock:
            self.dispatch_s += seconds

    def add_host_sync(self, seconds: float, count: int = 1):
        with self._lock:
            self.host_sync_s += seconds
            self.host_syncs += count

    def step(self, n: int = 1):
        with self._lock:
            self.steps += n

    # ------------------------------------------------------------ reporting
    def summary(self) -> dict:
        with self._lock:
            steps = max(self.steps, 1)
            if self.h2d_issue_s > 0:
                overlap = 1.0 - min(self.h2d_wait_s / self.h2d_issue_s, 1.0)
            else:
                overlap = None
            return {
                "steps": self.steps,
                "h2d_wait_us": round(self.h2d_wait_s / steps * 1e6, 1),
                "h2d_issue_us": round(self.h2d_issue_s / steps * 1e6, 1),
                "dispatch_us": round(self.dispatch_s / steps * 1e6, 1),
                "host_sync_us": round(self.host_sync_s / steps * 1e6, 1),
                "host_syncs_per_step": round(self.host_syncs / steps, 4),
                "overlap_ratio": (round(overlap, 4)
                                  if overlap is not None else None),
            }


pipeline_stats = PipelineStats()


class ServingStats:
    """Request-phase accounting for the serving tier (paddle_tpu/serving):
    every completed request reports its enqueue→admit→dispatch→complete
    timestamps, every scheduler pass samples the queue depth, and every
    dispatched batch reports its fill. The summary is the bench's
    ``extras.serving`` payload: p50/p99 end-to-end latency, requests/sec,
    and requests/sec *within the SLO* (FLAGS_serving_slo_ms) — the
    EQuARX-style accounting discipline: a serving tier is measured in
    admitted work per second at a latency bound, not raw throughput.
    Requests recorded with a ``tenant`` additionally land in that
    tenant's own ring, and ``summary()["tenants"]`` breaks the same
    numbers down per tenant (p50/p99, queue-wait, rps, rejected) — the
    multi-tenant fairness read.

    Latency samples are kept in a bounded ring (last ``max_samples``
    requests, globally and per tenant) so percentile math never grows
    with uptime.
    """

    def __init__(self, max_samples: int = 8192):
        self._lock = named_lock("profiler.serving_stats")
        self._max_samples = int(max_samples)
        self.reset()

    def reset(self):
        with self._lock:
            self.requests = 0
            self.samples = 0
            self.rejected = 0
            self.expired = 0
            self.batches = 0
            self.padded_slots = 0
            self.batch_slots = 0
            self.queue_depth_sum = 0
            self.queue_depth_peak = 0
            self.depth_samples = 0
            self._lat = []        # (total, queue_wait, exec) seconds, ring
            self._tenants = {}    # tenant -> {"requests","samples","rejected",
            #                                  "lat": bounded ring like _lat}
            self._t_first = None
            self._t_last = None
            # decode tier (serving/decode.py): per-step prefill-vs-decode
            # latency split, emitted-token throughput, slot occupancy
            self._decode = {
                "prefill_steps": 0, "decode_steps": 0,
                "prefill_s": 0.0, "decode_s": 0.0,
                "prefill_ms": [], "decode_ms": [],   # bounded rings
                # self-speculation split (ISSUE 20): draft/verify program
                # calls keyed like the other step kinds, plus per-round
                # acceptance accounting
                "draft_steps": 0, "verify_steps": 0,
                "draft_s": 0.0, "verify_s": 0.0,
                "draft_ms": [], "verify_ms": [],     # bounded rings
                "spec_rounds": 0, "spec_proposed": 0,
                "spec_accepted": 0, "spec_committed": 0,
                "tokens": 0, "t_first": None, "t_last": None,
                "occ_sum": 0, "occ_samples": 0, "occ_peak": 0,
                "slots": 0,
            }

    def _tenant_cell(self, tenant) -> dict:
        # caller holds the lock
        cell = self._tenants.get(tenant)
        if cell is None:
            cell = self._tenants[tenant] = {
                "requests": 0, "samples": 0, "rejected": 0, "lat": []}
        return cell

    # ------------------------------------------------------------ recording
    def record_request(self, t_enqueue: float, t_admit: float,
                       t_dispatch: float, t_complete: float, n: int = 1,
                       tenant: str = None):
        """One completed request's phase timestamps (perf_counter space);
        ``tenant`` additionally lands the sample in that tenant's own
        bounded ring for the per-tenant summary breakdown."""
        with self._lock:
            self.requests += 1
            self.samples += int(n)
            lat = (t_complete - t_enqueue, t_dispatch - t_admit,
                   t_complete - t_dispatch)
            self._lat.append(lat)
            if len(self._lat) > self._max_samples:
                del self._lat[: len(self._lat) - self._max_samples]
            if tenant is not None:
                cell = self._tenant_cell(tenant)
                cell["requests"] += 1
                cell["samples"] += int(n)
                ring = cell["lat"]
                ring.append(lat)
                if len(ring) > self._max_samples:
                    del ring[: len(ring) - self._max_samples]
            if self._t_first is None:
                self._t_first = t_enqueue
            self._t_last = max(self._t_last or t_complete, t_complete)

    def record_rejected(self, n: int = 1, tenant: str = None):
        with self._lock:
            self.rejected += int(n)
            if tenant is not None:
                self._tenant_cell(tenant)["rejected"] += int(n)

    def record_expired(self, n: int = 1, tenant: str = None):
        """Requests whose queue wait outlived FLAGS_serving_request_ttl_ms
        (failed with AdmissionError reason='ttl', never executed)."""
        with self._lock:
            self.expired += int(n)
            if tenant is not None:
                cell = self._tenant_cell(tenant)
                cell["expired"] = cell.get("expired", 0) + int(n)

    def retire_tenant(self, tenant: str) -> bool:
        """Drop a tenant's stats lane (mid-traffic tenant churn): its
        ring and counters leave ``summary()["tenants"]``; the global
        aggregates keep everything it already contributed."""
        with self._lock:
            return self._tenants.pop(tenant, None) is not None

    def record_decode_step(self, kind: str, seconds: float, n_lanes: int,
                           n_tokens: int):
        """One decode-tier program call: ``kind`` is ``"prefill"``,
        ``"decode"``, ``"draft"`` or ``"verify"``; ``n_tokens`` real
        tokens were emitted by ``n_lanes`` real lanes (pad lanes
        excluded — a draft call emits 0, its round's committed tokens
        land on the verify call). Feeds the per-kind latency split and
        tokens/sec."""
        now = time.perf_counter()
        with self._lock:
            cell = self._decode
            cell[f"{kind}_steps"] += 1
            cell[f"{kind}_s"] += float(seconds)
            ring = cell[f"{kind}_ms"]
            ring.append(float(seconds) * 1e3)
            if len(ring) > self._max_samples:
                del ring[: len(ring) - self._max_samples]
            cell["tokens"] += int(n_tokens)
            if cell["t_first"] is None:
                cell["t_first"] = now - seconds
            cell["t_last"] = now

    def record_spec_round(self, proposed: int, accepted: int,
                          committed: int):
        """One self-speculation round's acceptance accounting across its
        lanes: ``proposed`` draft tokens, ``accepted`` of them matched
        the full-model verify pass, ``committed`` tokens entered streams
        (accepted + the verify-pass bonus token per lane, clipped by
        eos/max_new). Feeds ``spec_accept_rate`` and
        ``spec_net_tokens_per_full_pass`` in the summary."""
        with self._lock:
            cell = self._decode
            cell["spec_rounds"] += 1
            cell["spec_proposed"] += int(proposed)
            cell["spec_accepted"] += int(accepted)
            cell["spec_committed"] += int(committed)

    def record_slot_occupancy(self, in_use: int, capacity: int):
        """KV slot occupancy at a step boundary (peak proves slot reuse:
        under oversubscribed traffic it reaches ``capacity`` while pool
        bytes stay constant)."""
        with self._lock:
            cell = self._decode
            cell["occ_sum"] += int(in_use)
            cell["occ_samples"] += 1
            cell["occ_peak"] = max(cell["occ_peak"], int(in_use))
            cell["slots"] = max(cell["slots"], int(capacity))

    def record_batch(self, n_samples: int, bucket: int):
        """One dispatched batch: ``n_samples`` real rows padded to
        ``bucket`` slots (fill ratio = batching efficiency)."""
        with self._lock:
            self.batches += 1
            self.batch_slots += int(bucket)
            self.padded_slots += int(bucket) - int(n_samples)

    def record_queue_depth(self, depth: int):
        with self._lock:
            self.depth_samples += 1
            self.queue_depth_sum += int(depth)
            if depth > self.queue_depth_peak:
                self.queue_depth_peak = int(depth)

    # ------------------------------------------------------------ reporting
    @staticmethod
    def _pct(sorted_vals, q):
        if not sorted_vals:
            return None
        idx = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
        return sorted_vals[idx]

    def summary(self, slo_ms: float = None) -> dict:
        if slo_ms is None:
            from ..base.flags import get_flag

            slo_ms = float(get_flag("serving_slo_ms"))
        with self._lock:
            total = sorted(t for t, _, _ in self._lat)
            queue_w = sorted(q for _, q, _ in self._lat)
            window = ((self._t_last - self._t_first)
                      if self._t_first is not None and self._t_last else 0.0)
            in_slo = sum(1 for t in total if t * 1e3 <= slo_ms)
            out = {
                "requests": self.requests,
                "samples": self.samples,
                "rejected": self.rejected,
                "expired": self.expired,
                "batches": self.batches,
                "slo_ms": slo_ms,
                "p50_ms": (round(self._pct(total, 0.50) * 1e3, 3)
                           if total else None),
                "p99_ms": (round(self._pct(total, 0.99) * 1e3, 3)
                           if total else None),
                "queue_wait_p50_ms": (round(self._pct(queue_w, 0.50) * 1e3, 3)
                                      if queue_w else None),
                "requests_per_sec": (round(self.requests / window, 1)
                                     if window > 0 else None),
                "samples_per_sec": (round(self.samples / window, 1)
                                    if window > 0 else None),
                "in_slo_fraction": (round(in_slo / len(total), 4)
                                    if total else None),
                "requests_per_sec_in_slo": (
                    round(self.requests * (in_slo / len(total)) / window, 1)
                    if total and window > 0 else None),
                "batch_fill": (round(1.0 - self.padded_slots
                                     / max(self.batch_slots, 1), 4)
                               if self.batches else None),
                "queue_depth_mean": (round(self.queue_depth_sum
                                           / self.depth_samples, 2)
                                     if self.depth_samples else None),
                "queue_depth_peak": self.queue_depth_peak,
                "tenants": {
                    name: self._tenant_summary(cell, window)
                    for name, cell in sorted(self._tenants.items())},
                "decode": self._decode_summary(),
            }
        return out

    def _decode_summary(self):
        """The decode tier's split (caller holds the lock): prefill vs
        decode step latency percentiles, emitted-token throughput, slot
        occupancy. None when no decode steps ran (batch-only engines)."""
        cell = self._decode
        if not cell["prefill_steps"] and not cell["decode_steps"]:
            return None
        window = ((cell["t_last"] - cell["t_first"])
                  if cell["t_first"] is not None else 0.0)
        prefill = sorted(cell["prefill_ms"])
        decode = sorted(cell["decode_ms"])

        def pct(vals, q):
            v = self._pct(vals, q)
            return round(v, 3) if v is not None else None

        out = {
            "prefill_steps": cell["prefill_steps"],
            "decode_steps": cell["decode_steps"],
            "prefill_p50_ms": pct(prefill, 0.50),
            "prefill_p99_ms": pct(prefill, 0.99),
            "decode_p50_ms": pct(decode, 0.50),
            "decode_p99_ms": pct(decode, 0.99),
            "tokens": cell["tokens"],
            "tokens_per_sec": (round(cell["tokens"] / window, 1)
                               if window > 0 else None),
            "slot_occupancy_mean": (round(cell["occ_sum"]
                                          / cell["occ_samples"], 2)
                                    if cell["occ_samples"] else None),
            "slot_occupancy_peak": cell["occ_peak"],
            "slots": cell["slots"],
        }
        if cell["spec_rounds"]:
            draft = sorted(cell["draft_ms"])
            verify = sorted(cell["verify_ms"])
            out.update(
                spec_rounds=cell["spec_rounds"],
                spec_tokens_proposed=cell["spec_proposed"],
                spec_tokens_accepted=cell["spec_accepted"],
                spec_tokens_committed=cell["spec_committed"],
                spec_accept_rate=(
                    round(cell["spec_accepted"]
                          / max(cell["spec_proposed"], 1), 4)),
                # >1.0 is the whole point: tokens committed per FULL-model
                # program call (verify) vs the 1.0 a plain decode step gets
                spec_net_tokens_per_full_pass=(
                    round(cell["spec_committed"]
                          / max(cell["spec_rounds"], 1), 3)),
                draft_steps=cell["draft_steps"],
                verify_steps=cell["verify_steps"],
                draft_p50_ms=pct(draft, 0.50),
                verify_p50_ms=pct(verify, 0.50),
            )
        return out

    def _tenant_summary(self, cell: dict, window: float) -> dict:
        """Per-tenant breakdown (caller holds the lock): latency
        percentiles, queue wait and request rate over the SAME window as
        the global summary — the multi-tenant fairness read: is one
        tenant's p99 paying for another's burst?"""
        total = sorted(t for t, _, _ in cell["lat"])
        queue_w = sorted(q for _, q, _ in cell["lat"])
        return {
            "requests": cell["requests"],
            "samples": cell["samples"],
            "rejected": cell["rejected"],
            "expired": cell.get("expired", 0),
            "p50_ms": (round(self._pct(total, 0.50) * 1e3, 3)
                       if total else None),
            "p99_ms": (round(self._pct(total, 0.99) * 1e3, 3)
                       if total else None),
            "queue_wait_p50_ms": (round(self._pct(queue_w, 0.50) * 1e3, 3)
                                  if queue_w else None),
            "requests_per_sec": (round(cell["requests"] / window, 1)
                                 if window > 0 else None),
        }


serving_stats = ServingStats()


class timed:
    """``with timed(stats.add_dispatch): step(batch)`` — records the span."""

    __slots__ = ("_sink", "_t0")

    def __init__(self, sink):
        self._sink = sink

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._sink(time.perf_counter() - self._t0)
