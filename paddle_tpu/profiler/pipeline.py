"""Per-step input-pipeline breakdown: where does a train step's wall time go?

The async XLA dispatch model hides host→device transfer and host-side
dispatch behind device compute — but only when the loop around the compiled
step actually lets it (no per-step ``.numpy()``, batches staged ahead of
consumption). This module is the observability half of that contract: the
``DeviceLoader`` (io/device_prefetch.py), ``MetricBuffer``
(hapi/metric_buffer.py) and the hapi/bench train loops report their waits
into one process-global :class:`PipelineStats`, and ``bench.py`` publishes
the summary under ``extras.pipeline``:

- ``h2d_wait_us``   — time the consumer blocked waiting for the next
  device-resident batch (0 when prefetch keeps up: the H2D overlapped the
  previous step's compute);
- ``h2d_issue_us``  — time the prefetch worker spent issuing
  ``jax.device_put`` (the transfer cost that is being hidden);
- ``dispatch_us``   — time inside the compiled step call (enqueue + for
  synchronous backends the compute itself);
- ``host_sync_us``  / ``host_syncs_per_step`` — time and count of blocking
  device→host reads (metric materialization). The steady-state target is
  **zero per step**: syncs belong at log/epoch boundaries.
- ``overlap_ratio`` — fraction of issued H2D time the consumer never
  waited for (1.0 = transfers fully hidden).

Recording costs two ``perf_counter`` calls per event — cheap enough to
leave on; ``reset()`` starts a fresh window.
"""
from __future__ import annotations

import threading
import time


class PipelineStats:
    """Thread-safe accumulator for the per-step pipeline breakdown."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self):
        with self._lock:
            self.steps = 0
            self.h2d_wait_s = 0.0
            self.h2d_issue_s = 0.0
            self.dispatch_s = 0.0
            self.host_sync_s = 0.0
            self.host_syncs = 0

    # ------------------------------------------------------------ recording
    def add_h2d_wait(self, seconds: float):
        with self._lock:
            self.h2d_wait_s += seconds

    def add_h2d_issue(self, seconds: float):
        with self._lock:
            self.h2d_issue_s += seconds

    def add_dispatch(self, seconds: float):
        with self._lock:
            self.dispatch_s += seconds

    def add_host_sync(self, seconds: float, count: int = 1):
        with self._lock:
            self.host_sync_s += seconds
            self.host_syncs += count

    def step(self, n: int = 1):
        with self._lock:
            self.steps += n

    # ------------------------------------------------------------ reporting
    def summary(self) -> dict:
        with self._lock:
            steps = max(self.steps, 1)
            if self.h2d_issue_s > 0:
                overlap = 1.0 - min(self.h2d_wait_s / self.h2d_issue_s, 1.0)
            else:
                overlap = None
            return {
                "steps": self.steps,
                "h2d_wait_us": round(self.h2d_wait_s / steps * 1e6, 1),
                "h2d_issue_us": round(self.h2d_issue_s / steps * 1e6, 1),
                "dispatch_us": round(self.dispatch_s / steps * 1e6, 1),
                "host_sync_us": round(self.host_sync_s / steps * 1e6, 1),
                "host_syncs_per_step": round(self.host_syncs / steps, 4),
                "overlap_ratio": (round(overlap, 4)
                                  if overlap is not None else None),
            }


pipeline_stats = PipelineStats()


class timed:
    """``with timed(stats.add_dispatch): step(batch)`` — records the span."""

    __slots__ = ("_sink", "_t0")

    def __init__(self, sink):
        self._sink = sink

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._sink(time.perf_counter() - self._t0)
