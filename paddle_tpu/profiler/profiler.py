"""Profiler (reference: python/paddle/profiler/profiler.py:358 — Profiler
state machine CLOSED/READY/RECORD/RECORD_AND_RETURN driven by a per-step
scheduler; host events via RecordEvent; chrome://tracing export via
chrometracing_logger.cc; summaries in profiler_statistic.py).

TPU-native: host-side events are recorded in-process (RecordEvent context
manager / dispatcher hook); device-side timelines come from `jax.profiler`
(XLA's own tracer) when `ProfilerTarget.TPU` is requested — the
jax.profiler trace dir can be opened in TensorBoard/XProf, while the host
events export to chrome://tracing JSON directly.
"""
from __future__ import annotations

import json
import os
import threading
import time
from enum import Enum
from typing import Callable, Iterable, Optional

from ..base.log import get_logger
from ..observability.locks import named_lock


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


class SortedKeys(Enum):
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3


class _EventStore(threading.local):
    def __init__(self):
        self.events = []
        self.active = False


_store = _EventStore()
_global_events = []
_global_lock = named_lock("profiler.global")


class RecordEvent:
    """Host event span (reference RecordEvent): context manager or begin/end."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._t0 = None

    def begin(self):
        self._t0 = time.perf_counter_ns()

    def end(self):
        if self._t0 is None:
            return
        t1 = time.perf_counter_ns()
        from ..ops import registry

        with _global_lock:
            _global_events.append(
                {"name": self.name, "ts": self._t0 / 1e3, "dur": (t1 - self._t0) / 1e3,
                 "tid": threading.get_ident() % 100000,
                 "cat": registry.profiler_tag(self.name)}
            )
        # same span, unified timeline: host op events land on the shared
        # observability tracer too (same perf_counter clock), so one
        # export correlates them with dispatch/train-loop/serving tracks
        from ..observability.tracing import tracer

        if tracer.enabled:
            tracer.emit(self.name, self._t0 / 1e9, (t1 - self._t0) / 1e9,
                        track="host", cat=registry.profiler_tag(self.name))
        self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """Per-step state schedule (reference make_scheduler)."""
    period = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def _default_scheduler(step: int) -> ProfilerState:
    return ProfilerState.RECORD


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """on_trace_ready callback writing chrome://tracing JSON (reference
    export_chrome_tracing)."""

    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}_time_{int(time.time())}.json")
        events = [
            {"name": e["name"], "ph": "X", "ts": e["ts"], "dur": e["dur"],
             "pid": os.getpid(), "tid": e["tid"], "cat": "host"}
            for e in prof._events
        ]
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)
        get_logger().info("chrome trace exported to %s", path)
        prof._last_export = path

    return handler


class Profiler:
    def __init__(self, *, targets: Optional[Iterable[ProfilerTarget]] = None,
                 scheduler=None, on_trace_ready=None, record_shapes=False,
                 profile_memory=False, timer_only=False, emit_nvtx=False):
        if scheduler is None:
            self._scheduler = _default_scheduler
        elif callable(scheduler):
            self._scheduler = scheduler
        else:  # (start, end) tuple like the reference
            start, end = scheduler
            self._scheduler = make_scheduler(
                closed=max(start - 1, 0), ready=1 if start > 0 else 0,
                record=end - start, repeat=1)
        self.targets = list(targets or [ProfilerTarget.CPU])
        self.on_trace_ready = on_trace_ready
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self._events = []
        self._device_trace_dir = None
        self._device_active = False
        self._last_export = None
        self.timer_only = timer_only
        self._step_times = []
        self._step_t0 = None

    # ------------------------------------------------------------- device
    def _start_device_trace(self):
        if ProfilerTarget.TPU in self.targets and not self._device_active:
            import jax

            self._device_trace_dir = self._device_trace_dir or os.path.join(
                os.getcwd(), "profiler_log", f"xla_{int(time.time())}")
            # capture-boundary stamp for the unified-timeline fusion below
            self._device_t0_us = time.perf_counter() * 1e6
            try:
                jax.profiler.start_trace(self._device_trace_dir)
                self._device_active = True
            except Exception as e:  # already-active tracer etc.
                get_logger().warning("jax trace not started: %s", e)

    def _stop_device_trace(self):
        if self._device_active:
            import jax

            try:
                jax.profiler.stop_trace()
            finally:
                self._device_active = False
            # device-trace fusion (ISSUE 8 / ROADMAP telemetry leftover):
            # with the unified tracer recording, XLA's window lands in the
            # SAME chrome-trace export as the host spans instead of only
            # a separate TensorBoard dir (which is still kept on disk)
            from ..observability.tracing import tracer

            if tracer.enabled:
                tracer.ingest_device_trace_dir(
                    self._device_trace_dir,
                    getattr(self, "_device_t0_us", 0.0))

    # -------------------------------------------------------------- state
    def _sync_op_hook(self):
        """Expose per-op host events through the dispatcher while recording."""
        from ..core import hooks

        recording = self.current_state in (
            ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN
        )
        hooks.op_profiler = RecordEvent if (recording and not self.timer_only) else None

    def start(self):
        with _global_lock:
            _global_events.clear()
        self.current_state = self._scheduler(self.step_num)
        if self.current_state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            self._start_device_trace()
        self._sync_op_hook()
        self._step_t0 = time.perf_counter()

    def stop(self):
        self._collect()
        self._stop_device_trace()
        if self.on_trace_ready is not None and self._events:
            self.on_trace_ready(self)
        self.current_state = ProfilerState.CLOSED
        self._sync_op_hook()

    def step(self, num_samples: Optional[int] = None):
        now = time.perf_counter()
        if self._step_t0 is not None:
            self._step_times.append(now - self._step_t0)
        self._step_t0 = now
        prev = self.current_state
        self.step_num += 1
        self.current_state = self._scheduler(self.step_num)
        if prev == ProfilerState.RECORD_AND_RETURN or (
            prev in (ProfilerState.RECORD,) and self.current_state == ProfilerState.CLOSED
        ):
            self._collect()
            self._stop_device_trace()
            if self.on_trace_ready is not None:
                self.on_trace_ready(self)
        if self.current_state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            self._start_device_trace()
        self._sync_op_hook()

    def _collect(self):
        with _global_lock:
            self._events = list(_global_events)
            _global_events.clear()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    # ------------------------------------------------------------ summary
    def summary(self, sorted_by=SortedKeys.CPUTotal, op_detail=True,
                thread_sep=False, time_unit="ms"):
        """Aggregate host events by name (reference profiler_statistic)."""
        agg = {}
        for e in self._events:
            st = agg.setdefault(e["name"], {"calls": 0, "total": 0.0, "max": 0.0,
                                            "min": float("inf")})
            st["calls"] += 1
            st["total"] += e["dur"]
            st["max"] = max(st["max"], e["dur"])
            st["min"] = min(st["min"], e["dur"])
        unit = {"ms": 1e3, "us": 1.0, "s": 1e6}[time_unit]
        rows = sorted(agg.items(), key=lambda kv: -kv[1]["total"])
        lines = [f"{'Name':<40}{'Calls':>8}{'Total(' + time_unit + ')':>14}"
                 f"{'Avg':>10}{'Max':>10}{'Min':>10}"]
        for name, st in rows:
            lines.append(
                f"{name[:39]:<40}{st['calls']:>8}{st['total'] / unit:>14.3f}"
                f"{st['total'] / st['calls'] / unit:>10.3f}{st['max'] / unit:>10.3f}"
                f"{st['min'] / unit:>10.3f}"
            )
        text = "\n".join(lines)
        print(text)
        return agg

    def benchmark(self):
        """Step-time stats (reference profiler/timer.py benchmark surface)."""
        if not self._step_times:
            return {}
        import numpy as np

        ts = np.asarray(self._step_times)
        return {"steps": len(ts), "avg_s": float(ts.mean()),
                "p50_s": float(np.percentile(ts, 50)), "max_s": float(ts.max())}
