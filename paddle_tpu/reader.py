"""Legacy reader decorators (reference python/paddle/reader/decorator.py):
generator-composition utilities still used by older recipes — shuffle,
batch, buffered, chain, map_readers, xmap_readers (thread pool)."""
from __future__ import annotations

import itertools
import queue
import random as _random
import threading


def shuffle(reader, buf_size):
    def impl():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return impl


def batch(reader, batch_size, drop_last=False):
    def impl():
        chunk = []
        for item in reader():
            chunk.append(item)
            if len(chunk) == batch_size:
                yield chunk
                chunk = []
        if chunk and not drop_last:
            yield chunk

    return impl


def buffered(reader, size):
    """Decouple producer/consumer through a bounded background queue."""
    END = object()

    def impl():
        q = queue.Queue(maxsize=size)

        def fill():
            try:
                for item in reader():
                    q.put(item)
            finally:
                q.put(END)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is END:
                t.join()
                return
            yield item

    return impl


def chain(*readers):
    def impl():
        return itertools.chain(*[r() for r in readers])

    return impl


def compose(*readers):
    def impl():
        for items in zip(*[r() for r in readers]):
            out = []
            for it in items:
                out.extend(it if isinstance(it, (list, tuple)) else [it])
            yield tuple(out)

    return impl


def map_readers(func, *readers):
    def impl():
        for items in zip(*[r() for r in readers]):
            yield func(*items)

    return impl


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Thread-pool mapper (reference xmap_readers; threads, not processes —
    mappers here are numpy-level and the GIL releases in numpy)."""
    from concurrent.futures import ThreadPoolExecutor

    def impl():
        with ThreadPoolExecutor(process_num) as pool:
            pending = []
            it = reader()
            for item in it:
                pending.append(pool.submit(mapper, item))
                if len(pending) >= buffer_size:
                    yield pending.pop(0).result()
            for f in pending:
                yield f.result()

    return impl


def firstn(reader, n):
    def impl():
        return itertools.islice(reader(), n)

    return impl
