"""Legacy reader decorators (reference python/paddle/reader/decorator.py):
generator-composition utilities still used by older recipes — shuffle,
batch, buffered, chain, map_readers, xmap_readers (thread pool)."""
from __future__ import annotations

import itertools
import queue
import random as _random
import threading


def shuffle(reader, buf_size):
    def impl():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return impl


def batch(reader, batch_size, drop_last=False):
    def impl():
        chunk = []
        for item in reader():
            chunk.append(item)
            if len(chunk) == batch_size:
                yield chunk
                chunk = []
        if chunk and not drop_last:
            yield chunk

    return impl


def buffered(reader, size):
    """Decouple producer/consumer through a bounded background queue.
    Producer exceptions propagate to the consumer (a crash must not read
    as a clean short epoch), and an early-abandoned generator unblocks and
    joins the fill thread instead of leaking it."""
    END = object()

    def impl():
        q = queue.Queue(maxsize=size)
        stop = threading.Event()
        err = []

        def put(item):
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def fill():
            try:
                for item in reader():
                    if not put(item):
                        return
            except BaseException as e:
                err.append(e)
            finally:
                put(END)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is END:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            stop.set()
            t.join()

    return impl


def chain(*readers):
    def impl():
        return itertools.chain(*[r() for r in readers])

    return impl


class ComposeNotAligned(ValueError):
    """reference reader/decorator.py: composed readers differ in length."""


def compose(*readers, check_alignment=True):
    def impl():
        MISSING = object()
        for items in itertools.zip_longest(*[r() for r in readers],
                                           fillvalue=MISSING):
            if MISSING in items:
                if check_alignment:
                    raise ComposeNotAligned(
                        "composed readers have different lengths")
                return
            out = []
            for it in items:
                out.extend(it if isinstance(it, (list, tuple)) else [it])
            yield tuple(out)

    return impl


def map_readers(func, *readers):
    def impl():
        for items in zip(*[r() for r in readers]):
            yield func(*items)

    return impl


def xmap_readers(mapper, reader, process_num, buffer_size, order=True):
    """Thread-pool mapper (reference xmap_readers; threads, not processes —
    mappers here are numpy-level and the GIL releases in numpy).
    order=True preserves input order; order=False yields completion order
    within the sliding buffer. Abandoning the generator cancels queued
    work instead of blocking on the pool."""
    from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait

    def impl():
        pool = ThreadPoolExecutor(process_num)
        pending = []
        try:
            for item in reader():
                pending.append(pool.submit(mapper, item))
                if len(pending) >= buffer_size:
                    if order:
                        yield pending.pop(0).result()
                    else:
                        done, _ = wait(pending, return_when=FIRST_COMPLETED)
                        f = next(iter(done))
                        pending.remove(f)
                        yield f.result()
            for f in (pending if order else list(pending)):
                yield f.result()
        finally:
            pool.shutdown(wait=False, cancel_futures=True)

    return impl


def firstn(reader, n):
    def impl():
        return itertools.islice(reader(), n)

    return impl
