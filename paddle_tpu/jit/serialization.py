"""paddle.jit.save / paddle.jit.load.

Reference (python/paddle/jit/api.py jit.save -> translated_layer.py) exports
a static Program + params. TPU-native export: the layer's compiled forward is
serialized as a StableHLO module (jax.export) next to the state_dict; load
rebuilds a callable TranslatedLayer that runs the module via jax. An
InputSpec dim of None exports as a shared SYMBOLIC batch dim (shape
polymorphism), the serving tier's one-module-any-batch contract — the
Predictor's bucket ladder compiles per-rung specializations from it. Where
jax.export is unavailable for a program, falls back to pickling the
state_dict + re-tracing on load from the saved Layer class is NOT attempted
(matching the reference's requirement of InputSpec at save time).
"""
from __future__ import annotations

import os
import pickle

import numpy as np

import jax

from ..core.tensor import Tensor, unwrap
from ..framework import io as fio


def save(layer, path, input_spec=None, **configs):
    """Save layer params + (if input_spec given) an exported StableHLO fwd.

    configs["quantize"]: optional — "weight_only_int8" / "weight_only_int4"
    converts every Linear to int8/int4 weight storage before export
    (quantization/ptq.py::quantize_weight_only), so the exported program
    carries quantized weights and runs the fused dequant-matmul path.
    """
    quantize = configs.pop("quantize", None)
    if quantize:
        from ..quantization.ptq import quantize_weight_only

        layer = quantize_weight_only(layer, algo=quantize)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    state = layer.state_dict()
    fio.save(state, path + ".pdiparams")
    meta = {"class": type(layer).__name__, "has_program": False,
            "quantize": quantize}
    if input_spec is not None:
        from jax import export as jax_export

        from ..base import dtype as dtype_mod

        # A None dim in an InputSpec becomes a symbolic dim (jax.export
        # shape polymorphism): the exported module then serves ANY size on
        # that axis, and the serving tier warm-compiles one specialization
        # per bucket rung instead of one export per shape. Symbols are
        # assigned by RANK — the first None dim of every input shares "b"
        # (the batch axis), the second shares "s" (the sequence axis), and
        # so on — so a GPT forward exported with InputSpec([None, None])
        # carries a TWO-AXIS ladder (batch x seq) from one module, while
        # single-None exports keep the historical one-symbol contract.
        _SYM_NAMES = ("b", "s", "d2", "d3")
        # all symbols must share ONE scope: count the ranks first, then
        # mint them together in a single symbolic_shape call
        n_ranks = 0
        for s in input_spec:
            if not isinstance(s, Tensor) and hasattr(s, "shape"):
                n_ranks = max(n_ranks,
                              sum(1 for d in s.shape if d is None))
        names = [(_SYM_NAMES[r] if r < len(_SYM_NAMES) else f"d{r}")
                 for r in range(n_ranks)]
        syms = (list(jax_export.symbolic_shape(", ".join(names)))
                if names else [])
        dynamic_axes = []
        dynamic_ranks = []  # (input_idx, axis, rank) triples

        def _sym(rank):
            return syms[rank]

        def _as_shaped(s, idx):
            if isinstance(s, Tensor):
                return unwrap(s)
            if hasattr(s, "shape") and hasattr(s, "dtype"):  # InputSpec
                shape = list(s.shape)
                rank = 0
                for ax, d in enumerate(shape):
                    if d is None:
                        dynamic_axes.append((idx, ax))
                        dynamic_ranks.append((idx, ax, rank))
                        shape[ax] = _sym(rank)
                        rank += 1
                return jax.ShapeDtypeStruct(tuple(shape), dtype_mod.np_dtype(s.dtype))
            return s

        leaves = [_as_shaped(s, i) for i, s in enumerate(input_spec)]
        params = {k: v._value for k, v in state.items()}

        modes = [(l, l.training) for l in layer.sublayers(include_self=True)]

        def fwd(params, *args):
            saved = {k: t._value for k, t in state.items()}
            for k, t in state.items():
                t._value = params[k]
            try:
                layer.eval()  # export inference graph; mode restored below
                out = layer.forward(*[Tensor(a) for a in args])
                # strip Tensor wrappers: exported modules carry plain arrays
                return jax.tree_util.tree_map(
                    lambda x: x._value if isinstance(x, Tensor) else x,
                    out,
                    is_leaf=lambda x: isinstance(x, Tensor),
                )
            finally:
                for k, t in state.items():
                    t._value = saved[k]

        args_shaped = [jax.ShapeDtypeStruct(np.shape(l), np.asarray(l).dtype if not hasattr(l, "dtype") else l.dtype) for l in leaves]
        params_shaped = jax.tree_util.tree_map(lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype), params)
        try:
            exported = jax_export.export(jax.jit(fwd))(params_shaped, *args_shaped)
        finally:
            for l, was_training in modes:
                l.training = was_training
        with open(path + ".pdmodel", "wb") as f:
            f.write(exported.serialize())
        meta["has_program"] = True
        meta["n_inputs"] = len(leaves)
        # symbolic dims pickle poorly and mean "any size" anyway: record None
        meta["input_shapes"] = [
            ([d if isinstance(d, int) else None for d in a.shape],
             str(a.dtype))
            for a in args_shaped]
        meta["dynamic_axes"] = dynamic_axes
        # which symbol each dynamic axis bound to: rank 0 = the batch
        # ladder, rank 1 = the sequence ladder (the two-axis bucket grid)
        meta["dynamic_ranks"] = dynamic_ranks
    with open(path + ".pdmeta", "wb") as f:
        pickle.dump(meta, f)


class TranslatedLayer:
    """Loaded exported program (reference jit/translated_layer.py)."""

    def __init__(self, exported, params, meta):
        self._exported = exported
        self._params = params
        self._meta = meta
        # content identity of the serialized module (set by load): the
        # persistent compile cache keys serving-ladder executables on it
        self._content_hash = None
        self.training = False

    def __call__(self, *args):
        vals = [unwrap(a) for a in args]
        out = self._exported.call(self._params, *vals)
        return jax.tree_util.tree_map(lambda x: Tensor(x) if hasattr(x, "shape") else x, out)

    forward = __call__

    def eval(self):
        return self

    def state_dict(self):
        return {k: Tensor(v) for k, v in self._params.items()}


def load(path, **configs):
    state = fio.load(path + ".pdiparams")
    with open(path + ".pdmeta", "rb") as f:
        meta = pickle.load(f)
    if meta.get("has_program"):
        import hashlib

        from jax import export as jax_export

        with open(path + ".pdmodel", "rb") as f:
            raw = f.read()
        exported = jax_export.deserialize(raw)
        params = {k: v._value for k, v in state.items()}
        layer = TranslatedLayer(exported, params, meta)
        layer._content_hash = hashlib.sha256(raw).hexdigest()
        return layer
    return state
