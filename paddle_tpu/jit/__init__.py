"""paddle.jit surface (reference: python/paddle/jit/api.py to_static :196,
paddle.jit.save/load)."""
from .api import TrainStep, ignore_module, not_to_static, to_static  # noqa: F401
from .functionalize import CompiledFunction, functionalize  # noqa: F401
from .serialization import load, save  # noqa: F401
