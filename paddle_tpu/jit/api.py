"""paddle.jit.to_static + TrainStep.

to_static (reference python/paddle/jit/api.py:196) compiles a function or a
Layer's forward into one XLA program via the discovery functionalizer —
the TPU-native replacement for SOT bytecode capture + PIR programs: jax
tracing IS the program capture, XLA IS the executor (SURVEY.md §7).

TrainStep is the blessed whole-step compile: forward + backward + optimizer
in one donated XLA program. hapi.Model and bench.py train through it.
"""
from __future__ import annotations

import functools
import time
from typing import Callable, Optional

from ..core.tensor import Tensor
from .functionalize import CompiledFunction, functionalize


def to_static(function=None, input_spec=None, build_strategy=None, backend=None, full_graph=True, **kwargs):
    """Decorator/wrapper: compile a function or Layer for whole-graph execution."""
    from ..nn.layer.layers import Layer

    if function is None:
        return functools.partial(to_static, input_spec=input_spec, build_strategy=build_strategy, backend=backend, full_graph=full_graph)

    if isinstance(function, Layer):
        layer = function
        orig_forward = layer.forward  # bound method, before the override below
        compiled = CompiledFunction(
            lambda *a, **k: orig_forward(*a, **k),
            static_key_fn=lambda: ("train" if layer.training else "eval"),
            name=type(layer).__name__,
        )
        layer._compiled_forward = compiled
        # Layer.__call__ already runs forward pre/post hooks around
        # self.forward, so the override is just the compiled function
        layer.forward_origin = orig_forward
        object.__setattr__(layer, "forward", compiled)
        return layer

    return CompiledFunction(function, name=getattr(function, "__name__", "fn"))


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    return None


class TrainStep:
    """Compile (forward + loss + backward + optimizer.step) into one XLA
    program with donated parameter/optimizer-state buffers.

    loss_fn(*batch) must build the loss from the model; or pass model and a
    criterion: step = TrainStep(model=m, optimizer=opt, loss_fn=lambda x, y:
    criterion(m(x), y)).

    The scheduler LR enters the program as a traced input (not a baked
    constant), so LR schedules do not retrace.
    """

    def __init__(self, model=None, optimizer=None, loss_fn: Optional[Callable] = None, grad_accum_steps: int = 1,
                 bucket_axes: Optional[dict] = None, bucket_range: Optional[tuple] = None,
                 bucket_pad_values: Optional[dict] = None,
                 sharding: Optional[str] = None):
        import jax.numpy as jnp

        if sharding not in (None, "zero1", "replicated"):
            raise ValueError(f"unknown TrainStep sharding {sharding!r} "
                             "(None|'zero1'|'replicated')")
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        # zero1 engagement override: "zero1" forces the sharded update,
        # "replicated" forces it off, None defers to FLAGS_sharding_stage /
        # an attached group_sharded strategy (distributed/sharding/zero1.py)
        self._sharding = sharding
        self._lr_cell = Tensor(jnp.asarray(0.0, jnp.float32), name="lr_cell")
        # host-side mirror of the cell's value: the device scalar re-uploads
        # only when the schedule actually moves, so a constant-LR steady
        # state issues zero H2D transfers per step
        self._lr_host = 0.0

        def step_fn(*batch):
            loss = self.loss_fn(*batch)
            loss.backward()
            if self._zero1_spec() is None:
                # zero1 replaces the dp grad sync: its reduce-scatter IS
                # the sync, fused into the sharded update
                self._sync_dp_grads()
            # read the LR through the dispatcher so the functionalizer records
            # the cell (traced input, not baked constant)
            lr_traced = (self._lr_cell + 0.0)._value
            prev = getattr(self.optimizer, "_lr_override", None)
            prev_sh = getattr(self.optimizer, "_sharding_override", None)
            self.optimizer._lr_override = lr_traced
            self.optimizer._sharding_override = self._sharding
            try:
                self.optimizer.step()
            finally:
                self.optimizer._lr_override = prev
                self.optimizer._sharding_override = prev_sh
            self.optimizer.clear_grad()
            return loss

        # the quantized dp-sync engagement AND the zero1 sharded-update
        # tier are part of the program's shape: flipping
        # FLAGS_comm_quantize_dp_grads / FLAGS_sharding_stage (or entering
        # an amp.auto_cast(comm_dtype=...) region) must recompile, not
        # silently serve the other tier's cached program
        base_key = (lambda: ("train" if model.training else "eval")) \
            if model is not None else (lambda: "fn")
        static_key = lambda: (base_key(), self._dp_sync_key(), self._sharding_key())  # noqa: E731
        if bucket_axes:
            # dynamic-shape policy: pad variable dims to the log2 bucket
            # ladder so distinct lengths share ≤ log2(max/min)+1 programs
            from .bucketing import BucketedFunction

            lo, hi = bucket_range or (16, 4096)
            self._compiled = BucketedFunction(
                step_fn, bucket_axes=bucket_axes, min_len=lo, max_len=hi,
                pad_values=bucket_pad_values, static_key_fn=static_key,
                name="train_step")
        else:
            self._compiled = CompiledFunction(step_fn, static_key_fn=static_key, name="train_step")

    def _dp_sync_key(self):
        """Static cache-key component for the quantized dp grad-sync tier
        (axis + size when engaged, 'fp32' otherwise)."""
        from ..distributed import collective_opt as copt

        spec = copt.gspmd_sync_axis()
        return "fp32" if spec is None else ("int8", spec[1], spec[2])

    def _zero1_spec(self):
        """(mesh, axis, n) when the zero1 sharded weight update engages
        for this step (explicit sharding= > FLAGS_sharding_stage >
        group_sharded strategy), else None."""
        if self.optimizer is None:
            return None
        from ..distributed.sharding import zero1

        return zero1.step_spec(self.optimizer, explicit=self._sharding)

    def _sharding_key(self):
        """Static cache-key component for the zero1 sharded-update tier:
        (axis, size, gather wire dtype) when engaged, 'replicated'
        otherwise — flag flips retrace instead of replaying the other
        tier's program."""
        spec = self._zero1_spec()
        if spec is None:
            return "replicated"
        from ..distributed import collective_opt as copt

        return ("zero1", spec[1], spec[2],
                copt.engaged_comm_dtype() or "fp32")

    def _sync_dp_grads(self):
        """The dp gradient-sync stage (between backward and the optimizer
        update): when the quantized tier engages
        (FLAGS_comm_quantize_dp_grads / amp comm_dtype) and an installed
        mesh has dp > 1, every eligible parameter grad reduce-scatters in
        fp32 and gathers back as int8 blocks + scales
        (collective_opt.dp_sync_gspmd). Off = zero work."""
        from ..distributed import collective_opt as copt

        spec = copt.gspmd_sync_axis()
        if spec is None:
            return
        mesh, axis, _n = spec
        params = getattr(self.optimizer, "_parameter_list", None) or []
        copt.sync_gspmd_grads(params, mesh, axis)

    def __call__(self, *batch):
        # refresh the LR cell from the schedule before entering the program
        # — but only when the value changed (the compiled program threads
        # the cell through as donated state, so the device scalar persists
        # across steps on its own)
        lr = self.optimizer.get_lr()
        if lr != self._lr_host:
            import jax.numpy as jnp

            self._lr_cell._replace_value(jnp.asarray(lr, jnp.float32))
            self._lr_host = lr
        from ..observability import numerics
        from ..observability.anomaly import monitor
        from ..observability.tracing import tracer

        if not (tracer.enabled or monitor.enabled
                or numerics._enabled):
            # all telemetry surfaces dark: three attribute reads, no clock
            return self._compiled(*batch)
        # snapshot once: the clock is only read for the monitor (tracer-only
        # mode stays clock-free here — the span stamps its own), and a flag
        # flip mid-step must not leave t0 unset at the close
        timed = monitor.enabled
        t0 = time.perf_counter() if timed else 0.0
        if tracer.enabled:
            with tracer.span("train.step", track="train_loop"):
                out = self._compiled(*batch)
        else:
            out = self._compiled(*batch)
        if timed:
            # train-step close: the flight recorder's step-time regression
            # detector sees the host-side dispatch wall (a retrace or a
            # blocking sync shows up here orders of magnitude over median)
            monitor.on_step(time.perf_counter() - t0)
        # NaN/Inf + dynamic-range sentinel on the step's loss (one bool
        # read when the numerics witness is dark)
        numerics.watch("train.loss", out[0] if isinstance(out, (tuple, list))
                       and out else out)
        return out

    @property
    def fallback_reason(self):
        return self._compiled.fallback_reason

    def audit(self, max_cache_keys=None):
        """JX3xx findings over every compiled whole-step program (see
        paddle_tpu.analysis.jaxpr_audit). On-demand only — never runs on
        the step's hot path."""
        return self._compiled.audit(max_cache_keys=max_cache_keys)

    def audit_report(self) -> dict:
        """Per-cache-key compile counts for the whole-step program cache
        (no compilation, no tracing — counter reads only)."""
        return self._compiled.audit_report()

    def cost(self):
        """Static ``CostReport`` of the whole-step program: FLOPs, bytes,
        collective volume per mesh axis, and the liveness peak-residency
        estimate the planner cross-checks against XLA ``memory_analysis``
        (see analysis/cost_model.py). On-demand only — never runs on the
        step's hot path."""
        return self._compiled.cost()
