"""The compile path: eager code -> one XLA program.

TPU-native replacement for the reference's whole to_static stack (SOT bytecode
capture paddle/fluid/pybind/sot/eval_frame.c + dy2static AST transforms +
PIR program + StandaloneExecutor, see SURVEY.md §3.3). The rebuild exploits
that this framework's eager layer is jax-traceable end to end:

1. **Discovery run** — execute the python function once eagerly while
   intercepting every Tensor the dispatcher reads and every payload write
   (core/hooks.py). That yields the *state cells*: parameters, buffers
   (BatchNorm running stats), optimizer accumulators, the global RNG key —
   exactly the variables the reference's program would hold. Writes are
   rolled back afterwards, so discovery is side-effect-free.
2. **Functionalization** — build ``pure(cell_values, args) -> (out,
   new_cell_values)`` by installing traced values into the cells and re-running
   the same python; jax.jit compiles it with the cell inputs donated (in-place
   buffer reuse on TPU, the analog of the reference's inplace pass).
3. **Execution** — subsequent calls run the compiled program and write the new
   cell values back into the live objects.

Python control flow on tensor *values* is handled with SOT-style branch
guards (reference python/paddle/jit/sot/ graph breaks, VERDICT r3 #6):
``if some_tensor_cond:`` records the concrete outcome during discovery and
compiles a specialization per branch signature; the compiled program also
RETURNS the predicate values, so each call verifies its speculation and, on
a flip, re-runs the specialization for the actual branch (cells are not
donated for guarded programs, so the originals stay intact). Only an
unseen branch signature — or a conversion the guard can't see, like
``float(loss)`` — costs an eager step (recorded in ``fallback_reason`` /
``stats``).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..base.log import get_logger
from ..core import hooks
from ..core.tensor import Tensor, unwrap

# process-wide program-build count across every CompiledFunction — the
# whole-step analog of kernel_cache's miss counter, re-homed into
# observability.snapshot() under "jit.compile" (adapters.py). Build-time
# only: the hot __call__ replay path never touches it.
_build_totals = {"programs": 0}


def build_totals() -> int:
    """Total compiled-program builds this process (all CompiledFunctions)."""
    return _build_totals["programs"]


def _record_build(name: str, t0: float) -> None:
    """Count one program build and, when tracing, span it on the dispatch
    track (signature-level detail lives in the kernel-cache events; here
    the unit is one whole-step XLA program)."""
    import time

    _build_totals["programs"] += 1
    from ..observability.tracing import tracer

    if tracer.enabled:
        tracer.emit("jit.build", t0, time.perf_counter() - t0,
                    track="dispatch", program=name)


class _BranchRecorder:
    """Eager-run mode of the branch hook: log every tensor-bool outcome."""

    def __init__(self):
        self.outcomes: List[bool] = []

    def on_bool(self, t: Tensor) -> bool:
        val = bool(np.asarray(t._value).item()) if not isinstance(
            t._value, jax.core.Tracer) else None
        if val is None:
            raise jax.errors.TracerBoolConversionError(t._value)
        self.outcomes.append(val)
        return val


class _BranchReplayer:
    """Trace-time mode: return the recorded outcome so tracing follows the
    recorded path, and collect the predicate tracer as a guard output."""

    def __init__(self, outcomes):
        self.outcomes = list(outcomes)
        self.idx = 0
        self.preds: List[Any] = []

    def on_bool(self, t: Tensor) -> bool:
        if self.idx >= len(self.outcomes):
            raise _BranchMismatch(
                "branch structure changed during replay (more tensor-bool "
                "conversions than the recorded path)")
        self.preds.append(jnp.asarray(t._value).reshape(()).astype(jnp.bool_))
        val = self.outcomes[self.idx]
        self.idx += 1
        return val


class _BranchMismatch(RuntimeError):
    pass


class DiscoveryContext:
    def __init__(self):
        self.cells: Dict[int, Tensor] = {}
        self.old_values: Dict[int, Any] = {}
        self.arg_ids = set()
        self.internal_ids = set()  # tensors created during discovery (intermediates)

    def record_create(self, t: Tensor):
        self.internal_ids.add(id(t))

    def record_reads(self, tensor_args):
        for a in tensor_args:
            if (
                isinstance(a, Tensor)
                and id(a) not in self.arg_ids
                and id(a) not in self.internal_ids
                and id(a) not in self.cells
            ):
                self.cells[id(a)] = a

    def record_write(self, t: Tensor):
        if id(t) in self.arg_ids:
            return
        if id(t) not in self.old_values:
            self.old_values[id(t)] = t._value
        if id(t) not in self.cells:
            self.cells[id(t)] = t

    def prune_tracer_cells(self):
        """Drop cells whose value is a dead tracer. Tensors created inside an
        inner trace during the eager discovery run (e.g. the pipeline
        schedule's per-tick RNG cells) get registered by their writes but die
        with that trace — keeping them would pin a leaked tracer into the
        compiled entry's state. Real state (params, optimizer moments created
        lazily on the first step) holds concrete arrays and stays."""
        import jax.core as jcore

        dead = [tid for tid, c in self.cells.items()
                if isinstance(c._value, jcore.Tracer)]
        for tid in dead:
            self.cells.pop(tid, None)
            self.old_values.pop(tid, None)

    def rollback(self):
        for tid, old in self.old_values.items():
            self.cells[tid]._value = old  # raw restore, no re-interception


def _tree_key(tree):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    sig = tuple(
        (tuple(l.shape), str(l.dtype)) if hasattr(l, "shape") else (type(l).__name__, l if isinstance(l, (int, float, bool, str, type(None))) else None)
        for l in leaves
    )
    return treedef, sig



def _abstract_call(args, kwargs):
    """(args, kwargs) with every array leaf replaced by its
    ShapeDtypeStruct: memory_analysis only needs shapes/dtypes to re-lower,
    and storing live arrays would pin a whole input batch in memory between
    steps."""
    return jax.tree_util.tree_map(
        lambda x: (jax.ShapeDtypeStruct(x.shape, x.dtype)
                   if hasattr(x, "shape") and hasattr(x, "dtype") else x),
        (args, kwargs))

def _clear_trace_residue(tensors):
    """Drop autograd residue that closes over tracers after a trace."""
    for t in tensors:
        t._grad_node = None
        if t._grad is not None and isinstance(t._grad._value, jax.core.Tracer):
            t._grad = None


class CompiledFunction:
    """One to_static-compiled callable with a per-signature program cache."""

    def __init__(self, fn: Callable, static_key_fn: Optional[Callable] = None, donate_cells=True, name=None):
        self.fn = fn
        self.static_key_fn = static_key_fn
        self.donate_cells = donate_cells
        self.name = name or getattr(fn, "__name__", "fn")
        self._cache: Dict[Any, dict] = {}
        self.fallback_reason: Optional[str] = None
        self.last_entry: Optional[dict] = None
        # compiled-vs-eager accounting (VERDICT r3 #6): how often do steps
        # actually run compiled, and how often do branch guards miss?
        self.stats = {"compiled_steps": 0, "eager_steps": 0, "guard_misses": 0}
        # per-cache-key program-build counts, maintained at BUILD time only —
        # the hot __call__ path never touches this (audit is on-demand)
        self._compile_counts: Dict[Any, int] = {}

    def _cache_key(self, args, kwargs):
        # treedefs are hashable and compare structurally — keying on the
        # object skips a per-call str() of the whole tree structure
        treedef, sig = _tree_key((args, kwargs))
        extra = self.static_key_fn() if self.static_key_fn else None
        return (treedef, sig, extra)

    def __call__(self, *args, **kwargs):
        key = self._cache_key(args, kwargs)
        entry = self._cache.get(key)
        if entry is None:
            entry = self._build(key, args, kwargs)
        # memoized per key: memory_analysis only needs the last call's
        # abstract (shape, dtype) tree, which cannot change while the key
        # doesn't — steady-state steps skip the tree_map
        if key != getattr(self, "_last_key", None):
            self._last_call = _abstract_call(args, kwargs)
            self._last_key = key
        self.last_entry = entry
        if entry.get("eager"):
            self.stats["eager_steps"] += 1
            return self.fn(*args, **kwargs)
        if entry.get("guarded"):
            return self._run_guarded(key, entry, args, kwargs)
        return self._run(entry, args, kwargs)

    # ------------------------------------------------------------------ build
    def _discover(self, args, kwargs):
        """Eager side-effect-free run: collects state cells AND the concrete
        outcome of every tensor-bool branch taken for these inputs."""
        ctx = DiscoveryContext()
        arg_leaves = [
            l
            for l in jax.tree_util.tree_leaves(
                (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor)
            )
            if isinstance(l, Tensor)
        ]
        ctx.arg_ids = {id(l) for l in arg_leaves}
        recorder = _BranchRecorder()
        prev = hooks.discovery
        prev_branch = hooks.branch_trace
        hooks.discovery = ctx
        hooks.branch_trace = recorder
        try:
            self.fn(*args, **kwargs)
        finally:
            hooks.discovery = prev
            hooks.branch_trace = prev_branch
            ctx.rollback()
        return ctx, tuple(recorder.outcomes)

    def _build(self, key, args, kwargs):
        import time

        t0 = time.perf_counter()
        try:
            ctx, outcomes = self._discover(args, kwargs)
        except jax.errors.JaxRuntimeError as e:
            if "RESOURCE_EXHAUSTED" not in str(e):
                raise
            # The eager discovery run holds every intermediate live at the
            # full batch shape. The cell SET does not depend on the batch
            # size, so retry discovery on a batch-1 probe slice; the jit
            # below still traces/compiles at the real shape, where XLA
            # schedules within HBM.
            get_logger().warning(
                "discovery OOM for %s at full shape; retrying with batch-1 probe",
                self.name,
            )
            import gc

            gc.collect()
            probe_args, probe_kwargs = jax.tree_util.tree_map(
                lambda l: (
                    Tensor(l._value[:1], stop_gradient=l.stop_gradient)
                    if isinstance(l, Tensor) and l.ndim >= 1 and l.shape[0] > 1
                    else l
                ),
                (args, kwargs),
                is_leaf=lambda x: isinstance(x, Tensor),
            )
            ctx, outcomes = self._discover(probe_args, probe_kwargs)

        if outcomes:
            family = {"guarded": True, "entries": {}, "last": outcomes,
                      "eager": False, "key": key,
                      "abstract_call": _abstract_call(args, kwargs)}
            self._cache[key] = family
            self._specialize(family, outcomes, ctx)
            return family

        entry = self._make_entry(ctx, guards=None)
        entry["abstract_call"] = _abstract_call(args, kwargs)
        self._cache[key] = entry
        self._compile_counts[key] = self._compile_counts.get(key, 0) + 1
        _record_build(self.name, t0)
        self._maybe_runtime_audit(entry)
        return entry

    def _maybe_runtime_audit(self, entry):
        """FLAGS_jaxpr_audit_runtime: audit + cost each program at BUILD
        time (cache misses only — steady-state replay never pays this),
        logging through base.log so arbitrary user CompiledFunctions get
        the analysis tier without on-demand calls. Only the just-built
        entry is retraced (plus the cheap cache-shape heuristics) — a
        ladder of N builds pays N retrace audits, not N²."""
        from ..base.flags import get_flag

        try:
            if not get_flag("jaxpr_audit_runtime"):
                return
        except Exception:
            return
        log = get_logger()
        try:
            from ..analysis.cost_model import cost_jaxpr
            from ..analysis.jaxpr_audit import (audit_compiled_function,
                                                retrace_entry)

            for f in audit_compiled_function(self, only_entry=entry):
                log.warning("jaxpr_audit[%s]: %s", self.name, f)
            closed, _n_user, _n_cells = retrace_entry(entry)
            rep = cost_jaxpr(closed, location=self.name)
            log.info(
                "cost[%s]: flops=%.3e bytes=%.3e peak=%.1f MiB "
                "intensity=%.3f",
                self.name, rep.flops, rep.bytes_read + rep.bytes_written,
                rep.peak_bytes / 2**20, rep.arithmetic_intensity)
        except Exception as e:  # a debug aid must never sink the build
            log.warning("jaxpr_audit_runtime failed for %s: %s", self.name, e)

    def _make_entry(self, ctx, guards):
        ctx.prune_tracer_cells()
        cells: List[Tensor] = list(ctx.cells.values())
        fn = self.fn

        def pure(cell_vals, a, kw):
            saved = [c._value for c in cells]
            for c, v in zip(cells, cell_vals):
                c._value = v
            replayer = _BranchReplayer(guards) if guards is not None else None
            prev_branch = hooks.branch_trace
            if replayer is not None:
                hooks.branch_trace = replayer
            try:
                out = fn(*a, **kw)
                new_vals = [c._value for c in cells]
            finally:
                hooks.branch_trace = prev_branch
                for c, v in zip(cells, saved):
                    c._value = v
                _clear_trace_residue(cells)
            # Tensors are pytree nodes: jit flattens/reconstructs the output
            # structure itself (fresh Tensor wrappers around result arrays)
            if replayer is not None:
                return out, new_vals, replayer.preds
            return out, new_vals

        # guarded programs never donate: a guard miss must re-run the actual
        # specialization on the ORIGINAL cell values
        donate = (0,) if (self.donate_cells and guards is None) else ()
        jitted = jax.jit(pure, donate_argnums=donate)
        return {"cells": cells, "jitted": jitted, "pure": pure, "eager": False,
                "compiled_once": False, "guards": guards}

    def _specialize(self, family, outcomes, ctx=None, args=None, kwargs=None):
        import time

        t0 = time.perf_counter()
        if ctx is None:
            ctx, outcomes = self._discover(args, kwargs)  # path actually taken
        if outcomes not in family["entries"]:
            entry = self._make_entry(ctx, guards=outcomes)
            entry["abstract_call"] = (
                _abstract_call(args, kwargs) if args is not None or kwargs
                else family.get("abstract_call"))
            family["entries"][outcomes] = entry
            key = family.get("key")
            self._compile_counts[key] = self._compile_counts.get(key, 0) + 1
            _record_build(self.name, t0)
            self._maybe_runtime_audit(entry)  # guard-miss builds too
        family["last"] = outcomes
        return outcomes

    # ------------------------------------------------------------------ run
    def _call_entry(self, entry, cell_vals, args, kwargs):
        """Dispatch one cache entry: the AOT executable once the
        persistent compile cache armed it, else the jitted wrapper.
        With FLAGS_compile_cache off this is exactly the legacy
        ``entry["jitted"](...)`` call. Trace-time exceptions
        (concretization, branch mismatch) propagate unchanged — the
        callers' fallback handling is the same on both paths."""
        ex = entry.get("exec")
        if ex is not None:
            return ex(cell_vals, args, kwargs)
        if not entry.get("compiled_once"):
            from .. import compile_cache as cc

            if cc.enabled():
                compiled = self._aot_entry(entry, cell_vals, args, kwargs)
                entry["exec"] = compiled
                return compiled(cell_vals, args, kwargs)
        return entry["jitted"](cell_vals, args, kwargs)

    def _aot_entry(self, entry, cell_vals, args, kwargs):
        """AOT-lower one entry and restore its executable from the
        persistent cache — or compile and publish it. The portable key is
        the lowered StableHLO text (+ the environment fingerprint): the
        in-process cache key is treedef/callsite identity, which no other
        process shares, but what XLA is handed is content. The lowering
        trace is paid either way (the jitted call would trace too); the
        warm win is skipping the XLA compile."""
        from .. import compile_cache as cc

        lowered = entry["jitted"].lower(cell_vals, args, kwargs)
        try:
            digest = cc.derive_digest("jit", lowered.as_text().encode())
        except Exception:
            cc.record("key_skip")
            digest = None
        compiled = cc.load_executable(digest, site="jit:" + self.name)
        if compiled is None:
            import time

            t0 = time.perf_counter()
            compiled = lowered.compile()
            from ..observability.tracing import tracer

            if tracer.enabled:
                tracer.emit("compile_cache.compile", t0,
                            time.perf_counter() - t0, track="dispatch",
                            site="jit:" + self.name)
            cc.store_executable(digest, compiled,
                                key_meta={"site": "jit",
                                          "program": self.name,
                                          "donated": bool(
                                              self.donate_cells
                                              and entry.get("guards") is None)})
        return compiled

    def _run_guarded(self, key, family, args, kwargs):
        """Speculative execution against the last-seen branch signature:
        the compiled program returns its predicate values; a mismatch
        re-runs the right specialization (cells not donated → originals
        intact). Unseen signatures build a new specialization from a fresh
        side-effect-free discovery — no committed eager steps."""
        guard = family["last"]
        entry = family["entries"][guard]
        try:
            out, ok = self._exec_entry(entry, args, kwargs)
        except _BranchMismatch as e:
            family["eager"] = True
            self.fallback_reason = str(e)
            get_logger().warning("to_static fallback to eager for %s: %s",
                                 self.name, self.fallback_reason)
            self.stats["eager_steps"] += 1
            return self.fn(*args, **kwargs)
        if ok:
            self.stats["compiled_steps"] += 1
            return out
        self.stats["guard_misses"] += 1
        actual = self._specialize(family, None, args=args, kwargs=kwargs)
        entry = family["entries"][actual]
        out, ok = self._exec_entry(entry, args, kwargs)
        if not ok:
            # predicates depend on state mutated between runs in a way the
            # guard can't pin — degrade honestly
            family["eager"] = True
            self.fallback_reason = "branch guard unstable across re-run"
            self.stats["eager_steps"] += 1
            return self.fn(*args, **kwargs)
        self.stats["compiled_steps"] += 1
        return out

    def _exec_entry(self, entry, args, kwargs):
        """Run one guarded specialization; commit writes only when the
        observed predicates match the speculated signature."""
        cells = entry["cells"]
        cell_vals = [c._value for c in cells]
        out_vals, new_vals, preds = self._call_entry(entry, cell_vals,
                                                     args, kwargs)
        observed = tuple(bool(np.asarray(p)) for p in preds)
        if observed != entry["guards"]:
            return None, False
        entry["compiled_once"] = True
        for c, v in zip(cells, new_vals):
            c._value = v
            c._version += 1
        return out_vals, True

    def memory_analysis(self):
        """Compiled-memory report of the last-run program (XLA
        memory_analysis) — the ground truth the planner's HBM estimates
        calibrate against (VERDICT r3 #9). None when the last call ran
        eagerly or nothing has run yet."""
        entry = self.last_entry
        if not entry or entry.get("eager"):
            return None
        if entry.get("guarded"):
            # unwrap to the active specialization; compiled_once lives there,
            # not on the family dict
            entry = entry["entries"][entry["last"]]
        if not entry.get("compiled_once"):
            return None
        last = getattr(self, "_last_call", None)
        if last is None:
            return None
        args, kwargs = last
        cells = entry["cells"]
        cell_vals = [c._value for c in cells]
        return entry["jitted"].lower(cell_vals, args, kwargs).compile(
        ).memory_analysis()

    def _run(self, entry, args, kwargs):
        cells = entry["cells"]
        cell_vals = [c._value for c in cells]
        if self.donate_cells:
            # donated buffers must be unique and must not alias non-donated
            # args (jax caches small constants, so fresh zeros can share one
            # buffer); copy aliased values
            arg_ids = {
                id(l._value)
                for l in jax.tree_util.tree_leaves(
                    (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor)
                )
                if isinstance(l, Tensor)
            }
            seen = set(arg_ids)
            for i, v in enumerate(cell_vals):
                if id(v) in seen:
                    cell_vals[i] = jnp.array(v)
                else:
                    seen.add(id(v))
        try:
            out_vals, new_vals = self._call_entry(entry, cell_vals,
                                                  args, kwargs)
        except (
            jax.errors.ConcretizationTypeError,
            jax.errors.TracerArrayConversionError,
            jax.errors.TracerBoolConversionError,
        ) as e:  # data-dependent value use the guards can't see -> eager
            entry["eager"] = True
            self.fallback_reason = str(e).split("\n")[0]
            get_logger().warning("to_static fallback to eager for %s: %s", self.name, self.fallback_reason)
            self.stats["eager_steps"] += 1
            return self.fn(*args, **kwargs)
        entry["compiled_once"] = True
        self.stats["compiled_steps"] += 1
        for c, v in zip(cells, new_vals):
            c._value = v
            c._version += 1
        return out_vals

    # ------------------------------------------------------------------ audit
    def audit_report(self) -> dict:
        """Per-cache-key program-build counts + run accounting. Pure reads
        of counters maintained at build time — never triggers discovery,
        tracing, or compilation (ISSUE 2 acceptance)."""
        keys = []
        for key, entry in self._cache.items():
            row = {
                "key": repr(key),
                "builds": self._compile_counts.get(key, 0),
                "eager": bool(entry.get("eager")),
                "guarded": bool(entry.get("guarded")),
            }
            if entry.get("guarded"):
                row["specializations"] = len(entry["entries"])
            keys.append(row)
        return {
            "name": self.name,
            "n_cache_keys": len(self._cache),
            "total_builds": sum(self._compile_counts.values()),
            "keys": keys,
            "stats": dict(self.stats),
            "fallback_reason": self.fallback_reason,
        }

    def audit(self, max_cache_keys=None):
        """Static audit of every cached program's jaxpr plus the
        recompilation heuristics; returns ``analysis.Finding`` objects
        (JX3xx). Retraces via ``jax.make_jaxpr`` — no XLA compilation."""
        from ..analysis.jaxpr_audit import audit_compiled_function

        return audit_compiled_function(self, max_cache_keys=max_cache_keys)

    def cost(self):
        """Static cost model of every cached program (FLOPs / bytes /
        collective volume / liveness peak residency): a
        ``analysis.cost_model.CostReport`` for the costliest entry, with
        the per-entry breakdown under ``.per_entry``. Same retrace
        machinery as ``audit()`` — tracing only, never compiles, never
        touches the hot ``__call__`` path."""
        from ..analysis.cost_model import cost_compiled_function

        return cost_compiled_function(self)


def functionalize(fn=None, *, static_key_fn=None, donate_cells=True, name=None):
    if fn is None:
        return functools.partial(functionalize, static_key_fn=static_key_fn, donate_cells=donate_cells, name=name)
    return CompiledFunction(fn, static_key_fn=static_key_fn, donate_cells=donate_cells, name=name)
