"""Dynamic-shape policy: pad-to-bucket compilation (SURVEY §7 hard part #4).

The reference keeps compiled coverage under dynamic shapes with SOT frame
capture (python/paddle/jit/sot/, paddle/fluid/pybind/sot/eval_frame.c) —
bytecode-level graph breaks around dynamic regions. Under XLA, shapes are
static per compile, so the TPU-native policy is *shape quantization*:
variable dims are padded up to a small ladder of bucket sizes, and the jit
cache keys on the bucket — a job with seq lens in [min, max] compiles at
most ``log2(max/min) + 1`` programs instead of one per distinct length, and
never silently falls back to eager.

Pieces:
- ``powers_of_two_buckets`` / ``bucket_for`` — the ladder
- ``assemble_bucket``     — mixed-size serving batch assembly: how many
  FIFO requests to take and which rung to pad them to (serving tier)
- ``pad_to_bucket``       — right-pad one array along an axis
- ``BucketedFunction``    — wraps ``functionalize``; pads declared args
  before dispatch (loss masking stays the caller's contract, as with any
  padded-batch training)
- ``bucket_collate``      — DataLoader collate that pads each batch's
  variable-length samples to the bucket of the batch max
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..core.tensor import Tensor


def powers_of_two_buckets(min_len: int, max_len: int) -> List[int]:
    """[min, 2·min, …, ≥max] — the log₂ ladder."""
    buckets = []
    b = max(int(min_len), 1)
    while b < max_len:
        buckets.append(b)
        b *= 2
    buckets.append(b)
    return buckets


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return int(b)
    raise ValueError(f"length {n} exceeds largest bucket {buckets[-1]}")


def bucket_grid(batch_ladder: Sequence[int],
                seq_ladder: Sequence[int]) -> List[tuple]:
    """Every ``(batch, seq)`` rung pair of a two-axis ladder — the warmup
    set of a seq-dynamic serving program (one compiled specialization per
    pair; ``len(batch) · len(seq)`` programs total, all restorable whole
    from the persistent compile cache)."""
    return [(int(b), int(s)) for b in batch_ladder for s in seq_ladder]


def bucket_pair_for(n: int, seq_len: int, batch_ladder: Sequence[int],
                    seq_ladder: Sequence[int]) -> tuple:
    """The two-axis rung for one request shape: batch count ``n`` and
    sequence length ``seq_len`` each round up their own ladder
    independently — a short prompt in a big batch never pays a long
    rung's compute."""
    return bucket_for(n, batch_ladder), bucket_for(seq_len, seq_ladder)


def table_ladder(max_seq: int, page_size: int) -> List[int]:
    """The block-table-width ladder of a paged KV pool: powers of two
    from one page up to ``ceil(max_seq / page_size)`` pages. The paged
    decode program keys on (batch rung × table rung) — the table rung
    bounds how many pages the gather reads, so a 128-token context in a
    4k-capable pool pays a 1-page-rung gather, not the 4k one."""
    if page_size < 1:
        raise ValueError(f"page_size must be >= 1, got {page_size}")
    max_pages = -(-int(max_seq) // int(page_size))
    return powers_of_two_buckets(1, max_pages)


def assemble_bucket(counts: Sequence[int], buckets: Sequence[int],
                    max_total: Optional[int] = None):
    """Mixed-size batch assembly for the serving tier: given the FIFO
    sample counts of pending requests, pick how many leading requests to
    take and the ladder rung to pad them to. Returns ``(k, bucket)`` —
    take ``counts[:k]`` and pad their ``sum`` up to ``bucket`` — or
    ``(0, None)`` when nothing fits.

    Policy: greedy FIFO fill, then top up the pad for free — after the
    rung is fixed by the greedy prefix, any further requests that fit in
    the rung's padding slots ride along at zero extra compute (the pad
    rows were going to be multiplied either way). FIFO order is never
    violated (no reordering ahead of an older request), so per-tenant
    latency stays predictable under load.
    """
    cap = int(max_total) if max_total else int(buckets[-1])
    cap = min(cap, int(buckets[-1]))
    total = 0
    k = 0
    for n in counts:
        n = int(n)
        if n > cap:
            if k == 0:
                raise ValueError(
                    f"request of {n} samples exceeds the largest bucket "
                    f"({cap}); split it or raise FLAGS_serving_max_batch")
            break
        if total + n > cap:
            break
        total += n
        k += 1
    if k == 0:
        return 0, None
    bucket = bucket_for(total, buckets)
    # free top-up: later requests that fit inside the pad — still bounded
    # by the caller's cap (the rung may exceed max_total when the greedy
    # total landed between rungs; padding slots beyond the cap stay pad)
    for n in counts[k:]:
        if total + int(n) > bucket or total + int(n) > cap:
            break
        total += int(n)
        k += 1
    return k, bucket


def pad_to_bucket(value, axis: int, bucket: int, pad_value=0):
    """Right-pad ``value`` along ``axis`` up to ``bucket``; returns the
    padded array (unchanged when already that size)."""
    import jax.numpy as jnp

    v = value._value if isinstance(value, Tensor) else jnp.asarray(value)
    n = v.shape[axis]
    if n == bucket:
        return value
    if n > bucket:
        raise ValueError(f"dim {n} larger than bucket {bucket}")
    widths = [(0, 0)] * v.ndim
    widths[axis] = (0, bucket - n)
    padded = jnp.pad(v, widths, constant_values=pad_value)
    if isinstance(value, Tensor):
        return Tensor(padded, stop_gradient=value.stop_gradient)
    return padded


class BucketedFunction:
    """functionalize() with pad-to-bucket on declared argument axes.

    bucket_axes: {arg_index: axis} — which positional args have a variable
    dim. All declared dims share one bucket per call (the common seq-len
    case); pad_values supplies per-arg fill (e.g. ignore_index for labels).
    """

    def __init__(self, fn: Callable, *, bucket_axes: Dict[int, int],
                 min_len: int, max_len: int,
                 pad_values: Optional[Dict[int, float]] = None,
                 buckets: Optional[Sequence[int]] = None,
                 static_key_fn=None, name=None):
        from .functionalize import CompiledFunction

        self.buckets = list(buckets) if buckets else powers_of_two_buckets(min_len, max_len)
        self.bucket_axes = dict(bucket_axes)
        self.pad_values = dict(pad_values or {})
        self._compiled = CompiledFunction(fn, static_key_fn=static_key_fn,
                                          name=name or getattr(fn, "__name__", "fn"))

    @property
    def num_compiled(self) -> int:
        return len(self._compiled._cache)

    def audit(self, max_cache_keys=None):
        """JX3xx findings: the wrapped function's program audits plus the
        bucket-ladder growth heuristic (JX313)."""
        from ..analysis.jaxpr_audit import audit_bucketed_function

        return audit_bucketed_function(self, max_cache_keys=max_cache_keys)

    def audit_report(self) -> dict:
        report = self._compiled.audit_report()
        report["buckets"] = list(self.buckets)
        return report

    def cost(self):
        """Static ``CostReport`` over the engaged bucket rungs (one cache
        entry per rung; ``.per_entry`` breaks them out)."""
        from ..analysis.cost_model import cost_bucketed_function

        return cost_bucketed_function(self)

    def __call__(self, *args, **kwargs):
        lengths = []
        for idx, axis in self.bucket_axes.items():
            v = args[idx]
            shape = (v._value.shape if isinstance(v, Tensor)
                     else np.asarray(v).shape)
            lengths.append(shape[axis])
        bucket = bucket_for(max(lengths), self.buckets) if lengths else None
        if bucket is not None:
            args = list(args)
            for idx, axis in self.bucket_axes.items():
                args[idx] = pad_to_bucket(args[idx], axis, bucket,
                                          self.pad_values.get(idx, 0))
        return self._compiled(*args, **kwargs)


def bucket_collate(axis: int = 0, min_len: int = 16, max_len: int = 4096,
                   pad_value=0, buckets: Optional[Sequence[int]] = None,
                   base_collate=None):
    """DataLoader collate_fn factory: pads each sample's ``axis`` to the
    bucket of the batch max before stacking, so downstream compiles see at
    most the bucket ladder's shapes (reference analog: the bucketing
    samplers in text data pipelines)."""
    ladder = list(buckets) if buckets else powers_of_two_buckets(min_len, max_len)

    def collate(batch):
        from .. import io as io_mod

        def pad_leaf(samples):
            arrs = [np.asarray(s) for s in samples]
            if arrs[0].ndim <= axis or not np.issubdtype(arrs[0].dtype, np.number):
                return io_mod.dataloader.default_collate_fn(samples)
            mx = max(a.shape[axis] for a in arrs)
            b = bucket_for(mx, ladder)
            out = []
            for a in arrs:
                widths = [(0, 0)] * a.ndim
                widths[axis] = (0, b - a.shape[axis])
                out.append(np.pad(a, widths, constant_values=pad_value))
            return io_mod.dataloader.default_collate_fn(out)

        sample = batch[0]
        if isinstance(sample, (tuple, list)):
            return tuple(pad_leaf(list(f)) for f in zip(*batch))
        if isinstance(sample, dict):
            return {k: pad_leaf([s[k] for s in batch]) for k in sample}
        return pad_leaf(batch)

    return collate
