"""Device management (reference: python/paddle/device/__init__.py set_device).

On TPU the device runtime is PJRT (the analog of the reference's
DeviceManager + custom-device C-ABI, paddle/phi/backends/device_manager.h):
jax enumerates devices; set_device picks the default placement.
"""
from __future__ import annotations

import jax

_current_device = None


def _resolve_device(device):
    if device is None:
        return get_device_object()
    if not isinstance(device, str):
        return device  # already a jax.Device
    name = device.lower()
    if ":" in name:
        kind, idx = name.split(":")
        idx = int(idx)
    else:
        kind, idx = name, 0
    if kind in ("tpu", "gpu", "cuda", "xpu"):
        accel = [d for d in jax.devices() if d.platform != "cpu"]
        pool = accel or jax.devices()
        return pool[idx % len(pool)]
    if kind == "cpu":
        return jax.devices("cpu")[idx % len(jax.devices("cpu"))]
    return jax.devices()[idx % len(jax.devices())]


def get_device_object():
    if _current_device is not None:
        return _current_device
    return jax.devices()[0]


def set_device(device):
    global _current_device
    _current_device = _resolve_device(device)
    return _current_device


def get_device():
    d = get_device_object()
    plat = d.platform
    if plat == "cpu":
        return "cpu"
    return f"{plat}:{d.id}"


def get_all_custom_device_type():
    return [d for d in {dd.platform for dd in jax.devices()} if d not in ("cpu", "gpu", "tpu")]


def is_compiled_with_cuda():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_tpu():
    try:
        return any(d.platform != "cpu" for d in jax.devices())
    except Exception:
        return False


def device_count():
    return len(jax.devices())


def synchronize(device=None):
    """Block until all async dispatches complete (reference: device sync)."""
    import jax.numpy as jnp

    jnp.zeros(()).block_until_ready()


class Stream:
    """Compat shim: XLA schedules streams internally; explicit streams are a no-op."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_stream(self, other):
        pass


class Event:
    def __init__(self, enable_timing=False):
        import time

        self._t = None
        self._time = time

    def record(self, stream=None):
        synchronize()
        self._t = self._time.perf_counter()

    def synchronize(self):
        synchronize()

    def elapsed_time(self, end):
        return (end._t - self._t) * 1000.0


# ---- memory observability (reference paddle.device.cuda.max_memory_allocated
# family, paddle/phi/core/memory/stats.cc) — mapped onto PJRT memory_stats --

def _mem_stats(device=None):
    dev = get_device_object() if device is None else _resolve_device(device)
    stats = getattr(dev, "memory_stats", lambda: None)()
    return stats or {}


def memory_allocated(device=None) -> int:
    """Bytes currently allocated on the device (PJRT ``bytes_in_use``;
    0 when the backend does not report memory stats, e.g. CPU)."""
    return int(_mem_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    """Peak bytes allocated on the device (PJRT ``peak_bytes_in_use``)."""
    return int(_mem_stats(device).get("peak_bytes_in_use", 0))


def memory_reserved(device=None) -> int:
    """Bytes reserved by the allocator pool (PJRT pool stats; falls back to
    bytes_in_use when the backend has no pool accounting)."""
    s = _mem_stats(device)
    return int(s.get("pool_bytes", s.get("bytes_in_use", 0)))


def max_memory_reserved(device=None) -> int:
    s = _mem_stats(device)
    return int(s.get("peak_pool_bytes", s.get("peak_bytes_in_use", 0)))


def memory_limit(device=None) -> int:
    """The device's usable memory budget (PJRT ``bytes_limit``)."""
    return int(_mem_stats(device).get("bytes_limit", 0))
