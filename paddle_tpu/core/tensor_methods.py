"""Tensor method surface.

Rebuild of the reference's method patching (python/paddle/tensor/__init__.py
registers every functional op as a Tensor method; C++ side
paddle/fluid/pybind/eager_method.cc). Every public function in the ops
modules whose first parameter takes a Tensor becomes a bound method, so
`x.sum(axis=1)`, `x.reshape([...])`, `x.matmul(y)` work exactly like
`paddle.sum(x, axis=1)` etc.
"""
from __future__ import annotations

import inspect

import jax.numpy as _jnp

from ..ops import (
    activation,
    creation,
    einsum_ops,
    linalg,
    logic,
    manipulation,
    math,
    random as random_ops,
    search,
    stat,
)
from .tensor import Tensor

# names that must not be shadowed on the Tensor class
_SKIP = {
    "to_tensor", "arange", "linspace", "logspace", "eye", "meshgrid", "rand",
    "randn", "randint", "randperm", "uniform", "normal", "standard_normal",
    "empty", "full", "ones", "zeros", "tril_indices", "triu_indices",
    "assign", "broadcast_shape",
}

_FIRST_PARAM_OK = {"x", "input", "tensor", "a", "t"}


def _patchable(name, fn):
    if name.startswith("_") or name in _SKIP:
        return False
    if not callable(fn) or inspect.isclass(fn):
        return False
    try:
        params = list(inspect.signature(fn).parameters)
    except (TypeError, ValueError):
        return False
    return bool(params) and params[0] in _FIRST_PARAM_OK


def _install(module):
    for name in dir(module):
        fn = getattr(module, name)
        if not _patchable(name, fn):
            continue
        if name in Tensor.__dict__:
            continue
        setattr(Tensor, name, fn)


for _m in (math, manipulation, logic, search, stat, linalg, activation, einsum_ops, creation, random_ops):
    _install(_m)


# ---- specials whose functional signature differs from the method form ------
def _not_shadow(name):
    return name not in Tensor.__dict__


if _not_shadow("matmul"):
    Tensor.matmul = lambda self, y, transpose_x=False, transpose_y=False: math.matmul(
        self, y, transpose_x, transpose_y
    )

Tensor.dim = lambda self: self.ndim
Tensor.rank = lambda self: self.ndim
Tensor.element_size = lambda self: self._value.dtype.itemsize
Tensor.dot = lambda self, y: math.dot(self, y)
Tensor.is_floating_point = lambda self: "float" in self.dtype.name or "bfloat" in self.dtype.name
Tensor.is_complex = lambda self: "complex" in self.dtype.name
Tensor.is_integer = lambda self: _jnp.issubdtype(self._value.dtype, _jnp.integer)
