"""Eager autograd engine.

TPU-native rebuild of the reference's dygraph tape
(/root/reference/paddle/fluid/eager/backward.cc RunBackward, grad_node_info.h
GradNodeBase): ops record GradNodes holding a jax VJP closure; ``backward()``
runs a reverse-topological ready-queue with dependency counting and gradient
accumulation, writing ``.grad`` on leaf tensors.

Differences from the reference, by design:
- the VJP of every op comes from jax at forward time instead of hand-written
  GradNode classes. On the dispatch fast path (core/kernel_cache.py) the node
  holds a :class:`~paddle_tpu.core.kernel_cache.CachedVJP` — a residual-
  carrying handle onto a cached backward executable, applied lazily and
  without tracing when backward() reaches the node; on the slow path it holds
  the live jax.vjp closure (residuals are device arrays held by the closure);
- for ``create_graph=True`` (higher-order grad, reference general_grad.h) the
  node re-runs the op's VJP *through the dispatcher* so the backward ops are
  themselves recorded on the tape;
- the engine is pure Python over async XLA dispatch and fully traceable:
  running it under jax.jit (paddle_tpu/jit) stages forward+backward into one
  XLA program.

Cotangents flow through the engine as Tensors (stop_gradient=True on the
first-order path), so hooks, accumulation, and create_graph share one code
path.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..base import global_state
from ..base.enforce import enforce
from .tensor import Tensor


class Edge:
    """Snapshot of an input's producer at record time (reference
    grad_node_info.h Edge): mutation of the Tensor afterwards (inplace ops,
    optimizer writes) must not rewire already-recorded graph edges."""

    __slots__ = ("tensor", "node", "index")

    def __init__(self, tensor: Tensor):
        self.tensor = tensor
        self.node = tensor._grad_node
        self.index = tensor._output_index


class GradNode:
    """One recorded op: maps output cotangents -> input cotangents."""

    __slots__ = (
        "name",
        "vjp_fn",
        "inputs",
        "n_outputs",
        "out_specs",
        "recompute",
        "_out_grads",
    )

    def __init__(self, name, vjp_fn, inputs: List[Tensor], n_outputs: int, out_specs, recompute=None):
        self.name = name
        # arrays -> arrays backward: either the residual closure from an
        # eager jax.vjp (slow path), or a kernel_cache.CachedVJP replaying a
        # compiled backward executable (fast path — applying it never traces)
        self.vjp_fn = vjp_fn
        self.inputs = [e if isinstance(e, Edge) else Edge(e) for e in inputs]
        self.n_outputs = n_outputs
        self.out_specs = out_specs  # (shape, dtype) per output for zero-fill
        self.recompute = recompute  # (fn, values, attrs, diff_idx) for create_graph
        self._out_grads: Optional[list] = None

    def accumulate(self, index: int, grad: Tensor):
        if self._out_grads is None:
            self._out_grads = [None] * self.n_outputs
        cur = self._out_grads[index]
        self._out_grads[index] = grad if cur is None else cur + grad

    def _is_int_output(self, i: int) -> bool:
        _, dt = self.out_specs[i]
        return not jnp.issubdtype(jnp.empty((), dt).dtype, jnp.inexact)

    def _ready_outputs(self, create_graph: bool):
        outs = []
        for i in range(self.n_outputs):
            g = self._out_grads[i] if self._out_grads else None
            if g is None and not self._is_int_output(i):
                shape, dt = self.out_specs[i]
                g = Tensor(jnp.zeros(shape, dt), stop_gradient=True)
            outs.append(g)  # None stays None for integer outputs
        return outs

    def _raw_cotangent(self, i: int, g):
        """jax.vjp cotangent for output i: float0 zeros for integer outputs
        (jax's convention for non-differentiable primal outputs)."""
        import numpy as np

        shape, dt = self.out_specs[i]
        if self._is_int_output(i):
            return np.zeros(shape, jax.dtypes.float0)
        return g._value

    def run_backward(self, create_graph: bool) -> List[Optional[Tensor]]:
        gouts = self._ready_outputs(create_graph)
        if create_graph and self.recompute is not None:
            return self._run_recompute(gouts)
        enforce(self.vjp_fn is not None, f"grad node '{self.name}' was already released; "
                "pass retain_graph=True to backward() to keep it")
        cotans = tuple(self._raw_cotangent(i, g) for i, g in enumerate(gouts))
        with global_state.no_grad_guard():
            raw = self.vjp_fn(cotans if self.n_outputs > 1 else cotans[0])
        if not isinstance(raw, (tuple, list)):
            raw = (raw,)
        return [None if g is None else Tensor(g, stop_gradient=True) for g in raw]

    def _run_recompute(self, gouts: List[Tensor]) -> List[Tensor]:
        """Differentiable backward: re-run fn's VJP through the dispatcher so
        the produced grads carry their own GradNodes (double grad)."""
        from .dispatch import primitive

        fn, values, attrs, diff_idx = self.recompute
        n_diff = len(diff_idx)

        import numpy as np

        int_out = [self._is_int_output(i) for i in range(self.n_outputs)]

        def grad_op(*prims_and_gouts):
            prims = prims_and_gouts[:n_diff]
            gs = list(prims_and_gouts[n_diff:])

            def partial_fn(*diff_vals):
                full = list(values)
                for i, v in zip(diff_idx, diff_vals):
                    full[i] = v
                return fn(*full, **attrs)

            _, vjp = jax.vjp(partial_fn, *prims)
            full_gs = []
            float_cursor = 0
            for i in range(self.n_outputs):
                if int_out[i]:
                    shape, _ = self.out_specs[i]
                    full_gs.append(np.zeros(shape, jax.dtypes.float0))
                else:
                    full_gs.append(gs[float_cursor])
                    float_cursor += 1
            cotan = tuple(full_gs) if self.n_outputs > 1 else full_gs[0]
            return tuple(vjp(cotan))

        float_gouts = [g for i, g in enumerate(gouts) if not int_out[i]]
        outs = primitive(f"{self.name}_grad", grad_op, [e.tensor for e in self.inputs] + float_gouts)
        return list(outs) if isinstance(outs, tuple) else [outs]

    def release(self):
        self.vjp_fn = None
        self.recompute = None
        self._out_grads = None


def _apply_hooks(t: Tensor, g: Tensor) -> Tensor:
    if t._backward_hooks:
        for hook in t._backward_hooks:
            res = hook(g)
            if res is not None:
                g = res if isinstance(res, Tensor) else Tensor(res, stop_gradient=True)
    return g


def _count_dependencies(root_nodes) -> Dict[int, int]:
    """#times each reachable node appears as producer of another's input."""
    dep: Dict[int, int] = {}
    visited = set()
    stack = list(root_nodes)
    while stack:
        node = stack.pop()
        if id(node) in visited:
            continue
        visited.add(id(node))
        for e in node.inputs:
            prod = e.node
            if prod is not None:
                dep[id(prod)] = dep.get(id(prod), 0) + 1
                if id(prod) not in visited:
                    stack.append(prod)
    return dep


def _run_engine(roots, root_grads, retain_graph=False, accumulate_into=None, create_graph=False):
    """roots: list[Tensor]; root_grads: list[Tensor] cotangents.

    accumulate_into: optional dict id(Tensor)->Tensor|None collecting grads for
    requested tensors (paddle.grad path). If None, grads land on leaf .grad.
    """
    root_nodes = []
    for t, g in zip(roots, root_grads):
        node = t._grad_node
        g = _apply_hooks(t, g)
        if node is None:
            _sink_grad(t, g, accumulate_into, create_graph)
            continue
        node.accumulate(t._output_index, g)
        root_nodes.append(node)

    dep = _count_dependencies(root_nodes)
    queue, seen = [], set()
    for n in root_nodes:
        if id(n) not in seen and dep.get(id(n), 0) == 0:
            seen.add(id(n))
            queue.append(n)

    while queue:
        node = queue.pop()
        in_grads = node.run_backward(create_graph)
        node._out_grads = None  # never reuse cotangents across engine runs
        enforce(
            len(in_grads) == len(node.inputs),
            f"vjp of {node.name} returned {len(in_grads)} grads for {len(node.inputs)} inputs",
        )
        for e, g in zip(node.inputs, in_grads):
            t = e.tensor
            prod = e.node
            skip = g is None or t.stop_gradient
            if not skip:
                g = _apply_hooks(t, g)
                if accumulate_into is not None and id(t) in accumulate_into:
                    cur = accumulate_into[id(t)]
                    accumulate_into[id(t)] = g if cur is None else cur + g
                if prod is None and accumulate_into is None:
                    _sink_grad(t, g, accumulate_into, create_graph)
                elif prod is not None:
                    prod.accumulate(e.index, g)
            # dependency bookkeeping runs even for skipped grads, so producers
            # reachable through other live paths still get scheduled
            if prod is not None:
                dep[id(prod)] -= 1
                if dep[id(prod)] == 0:
                    queue.append(prod)
        if not retain_graph:
            node.release()


def _sink_grad(t: Tensor, g: Tensor, accumulate_into, create_graph):
    if accumulate_into is not None:
        if id(t) in accumulate_into:
            cur = accumulate_into[id(t)]
            accumulate_into[id(t)] = g if cur is None else cur + g
        return
    if t._grad is None:
        t._grad = g if create_graph else Tensor(g._value, stop_gradient=True)
    else:
        if create_graph:
            t._grad = t._grad + g
        else:
            t._grad._replace_value(t._grad._value + g._value)


def _ones_like(t: Tensor) -> Tensor:
    return Tensor(jnp.ones(t._value.shape, t._value.dtype), stop_gradient=True)


def _as_cotangent(t: Tensor, g) -> Tensor:
    if g is None:
        return _ones_like(t)
    if isinstance(g, Tensor):
        return g
    return Tensor(jnp.asarray(g), stop_gradient=True)


def backward_from(tensor: Tensor, grad_tensor=None, retain_graph=False):
    """loss.backward() entry (reference eager_functions.cc run_backward)."""
    if tensor.stop_gradient and tensor._grad_node is None:
        return
    _run_engine([tensor], [_as_cotangent(tensor, grad_tensor)], retain_graph=retain_graph)


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward on multiple roots."""
    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]
    gs = [_as_cotangent(t, g) for t, g in zip(tensors, grad_tensors)]
    _run_engine(list(tensors), gs, retain_graph=retain_graph)


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph=None,
    create_graph=False,
    only_inputs=True,
    allow_unused=False,
    no_grad_vars=None,
):
    """paddle.grad analog (reference eager general_grad.h partial-graph backward)."""
    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]
    if retain_graph is None:
        retain_graph = create_graph
    gs = [_as_cotangent(t, g) for t, g in zip(outputs, grad_outputs)]
    sink = {id(t): None for t in inputs}
    _run_engine(
        list(outputs), gs, retain_graph=retain_graph, accumulate_into=sink, create_graph=create_graph
    )
    results = []
    for t in inputs:
        g = sink[id(t)]
        if g is None:
            if not allow_unused:
                raise ValueError(
                    f"tensor {t.name} is unreachable from outputs (set allow_unused=True to return None)"
                )
            results.append(None)
        else:
            results.append(g)
    return results
