"""Auxiliary tensor containers (reference:
paddle/phi/core/tensor_array.h TensorArray,
paddle/phi/core/selected_rows.h SelectedRows,
paddle/phi/core/string_tensor.h StringTensor).

TPU-native notes: TensorArray inside compiled code is a `lax.scan` output —
this eager container covers the dynamic-graph API (write/read/stack) and
converts to a stacked array at the jit boundary. SelectedRows represents
row-sparse gradients (embedding tails); on TPU the dense scatter-add is
usually faster than gather-compaction, so SelectedRows is an interchange
format, with `to_dense`/`merge` the conversion points. StringTensor is
host-side by design (TPUs do not compute on strings; tokenizers run in the
input pipeline).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .tensor import Tensor


class TensorArray:
    """Growable list of same-rank tensors (reference TensorArray)."""

    def __init__(self, values: Optional[Sequence[Tensor]] = None):
        self._items: List[Tensor] = list(values or [])

    def append(self, t) -> "TensorArray":
        self._items.append(t if isinstance(t, Tensor) else Tensor(t))
        return self

    write = append

    def read(self, i: int) -> Tensor:
        return self._items[i]

    def __getitem__(self, i):
        return self._items[i]

    def __len__(self):
        return len(self._items)

    def stack(self, axis: int = 0) -> Tensor:
        from ..ops.manipulation import stack

        return stack(self._items, axis)

    def concat(self, axis: int = 0) -> Tensor:
        from ..ops.manipulation import concat

        return concat(self._items, axis)

    def pop(self, i: int = -1) -> Tensor:
        return self._items.pop(i)


class SelectedRows:
    """Row-sparse value container (reference SelectedRows): `rows` are the
    touched indices of a [height, ...] dense space, `value` their data."""

    def __init__(self, rows, value, height: int):
        self.rows = rows if isinstance(rows, Tensor) else Tensor(np.asarray(rows))
        self.value = value if isinstance(value, Tensor) else Tensor(np.asarray(value))
        self.height = int(height)

    def to_dense(self) -> Tensor:
        import jax

        from .dispatch import primitive

        h = self.height

        def fn(rows, vals):
            return jax.ops.segment_sum(vals, rows, h)

        return primitive("selected_rows_to_dense", fn, [self.rows, self.value])

    def merge(self) -> "SelectedRows":
        """Deduplicate rows by summation (reference merge_selected_rows)."""
        idx = np.asarray(self.rows.numpy())
        uniq, inv = np.unique(idx, return_inverse=True)
        import jax

        from .dispatch import primitive

        n = len(uniq)
        vals = primitive(
            "merge_selected_rows",
            lambda v: jax.ops.segment_sum(v, np.asarray(inv), n),
            [self.value])
        return SelectedRows(uniq.astype(np.int64), vals, self.height)

    def __repr__(self):
        return f"SelectedRows(height={self.height}, nnz_rows={self.rows.shape[0]})"


class StringTensor:
    """Host-side string tensor (reference StringTensor) — numpy object array
    with shape semantics; compute stays in the input pipeline."""

    def __init__(self, data, name: Optional[str] = None):
        self._data = np.asarray(data, dtype=object)
        self.name = name

    @property
    def shape(self):
        return list(self._data.shape)

    def numpy(self):
        return self._data

    def __getitem__(self, i):
        out = self._data[i]
        return out if isinstance(out, str) else StringTensor(out)

    def __len__(self):
        return self._data.shape[0]

    def __repr__(self):
        return f"StringTensor(shape={self.shape})"
