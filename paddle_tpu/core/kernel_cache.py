"""Signature-keyed kernel cache: the eager dispatch fast path.

Rebuild of the reference's generated ``xxx_ad_func`` fast path (the eager
auto-code-generated layer caches kernel selection and backward-node shape
per op signature): here the cached object is a **jitted executable** — the
op's forward, and for differentiable calls the forward+VJP pair — keyed by

    (op name, kernel identity, per-arg (shape, dtype, is-diff) spec,
     frozen static args, frozen attrs)

so steady-state eager steps replay compiled programs instead of re-running
``jax.vjp`` tracing per op (~1ms/op eager trace vs ~10µs/op cached replay
on CPU). The VJP side rides on jax's contract that ``jax.vjp`` under
``jax.jit`` returns its pullback as a ``jax.tree_util.Partial`` pytree:
the compiled forward emits the residuals as ordinary outputs, and a shared
jitted applier (:data:`_VJP_APPLIER`) replays the backward without ever
tracing on the hot path. :class:`CachedVJP` is what ``GradNode`` holds in
place of a live ``vjp_fn`` closure (core/autograd.py).

Kernels must be pure (the trace-safety linter enforces this for the
framework's own ops): staging executes the python body once under trace, so
a host side effect in a custom kernel fires during the staging attempt and
— if staging fails and the call falls back — again on the eager re-run.
Only global-RNG corruption is actively detected and repaired
(:func:`_staging_call`); other host side effects in kernels are undefined
under caching, as under any jit.

Kernel identity: op fns arrive as per-call-site lambdas that close over
their attrs (``lambda v: jnp.sum(v, axis=ax)``), so the key derives from
``fn.__code__`` (stable per call site) plus the **frozen closure cell
values** (the attrs). Anything that cannot be frozen to a hashable token —
arrays or Tensors in cells, unhashable attrs — bypasses the fast path for
that call; the dispatcher also self-disables whenever it cannot be
semantically transparent (active discovery / static_capture / op_observer
hooks, AMP cast insertion, tracer inputs). Every bypass is counted per op
with its reason (:func:`stats`), feeding the JX32x kernel-cache audit in
``analysis/jaxpr_audit.py``.

Flags: ``FLAGS_eager_kernel_cache`` (master switch),
``FLAGS_eager_kernel_cache_max_entries`` (LRU capacity).
"""
from __future__ import annotations

import time as _time
from collections import OrderedDict
from typing import Any, Optional, Sequence

import numpy as np

import jax

from ..base.flags import get_flag
from ..observability.tracing import tracer as _tracer

__all__ = ["CachedVJP", "clear", "cost_stats", "execute", "lookup",
           "poison", "record_bypass", "stats"]


class _Unhashable(Exception):
    """Internal signal: a key component cannot be frozen. ``reason`` is the
    bypass counter it lands in — ``array_capture`` for arrays/Tensors in
    the signature (the deliberate pattern: per-call PRNG keys, captured
    payloads), ``unhashable`` for everything else (the JX320 storm
    numerator)."""

    def __init__(self, reason="unhashable"):
        super().__init__(reason)
        self.reason = reason


# ---------------------------------------------------------------------------
# key derivation
# ---------------------------------------------------------------------------

_FREEZE_DEPTH = 4


def _freeze(v, depth=0):
    """Hashable token for a static key component, or raise :class:`_Unhashable`.

    Containers are frozen structurally (list/dict attrs like ``perm`` or
    ``axis`` lists are common); numeric scalars carry their type (``2``,
    ``2.0`` and ``True`` are ==/hash-equal but stage different programs);
    arrays and Tensors are refused — baking a mutable payload into a cache
    key would serve stale programs."""
    if v is None or v is Ellipsis or isinstance(v, (str, bytes, np.dtype)):
        return v
    if isinstance(v, (bool, int, float, complex, np.generic)):
        return (type(v), v)
    from .tensor import Tensor

    if isinstance(v, (Tensor, np.ndarray, jax.Array)) or hasattr(v, "aval"):
        raise _Unhashable("array_capture")
    if depth >= _FREEZE_DEPTH:
        raise _Unhashable
    if isinstance(v, slice):  # unhashable on py3.10
        return ("__slice__", _freeze(v.start, depth + 1),
                _freeze(v.stop, depth + 1), _freeze(v.step, depth + 1))
    if isinstance(v, (list, tuple)):
        return ("__seq__", tuple(_freeze(x, depth + 1) for x in v))
    if isinstance(v, (set, frozenset)):
        return ("__set__", frozenset(_freeze(x, depth + 1) for x in v))
    if isinstance(v, dict):
        return ("__map__", tuple(sorted(
            (k, _freeze(x, depth + 1)) for k, x in v.items())))
    if callable(v):
        return _fn_key(v, depth + 1)
    try:
        hash(v)
    except TypeError:
        raise _Unhashable from None
    return v


# code object -> content token. CPython code equality includes
# co_firstlineno, so the same kernel text at two call sites (or a factory
# re-exec'd at different lines) hashes apart and churns the cache with
# duplicate executables. The token hashes code CONTENT — bytecode, consts
# (recursing into nested code, so closures holding fresh inner lambdas
# collapse too), names — and drops filename/lineno. Memoized per code
# object: the content walk runs once per call site, the hot path pays one
# dict hit.
_CODE_TOKENS: dict = {}


def _const_token(c):
    """Type-aware token for one co_consts entry: ``1``, ``1.0`` and
    ``True`` are ==/hash-equal in Python but stage different programs, so
    a plain tuple compare would collide ``x * 1`` with ``x * 1.0`` (code
    objects themselves compare constants type-aware — keep that)."""
    if hasattr(c, "co_code"):
        return _code_token(c)
    if isinstance(c, (bool, int, float, complex)):
        return (type(c), c)
    if isinstance(c, tuple):
        return ("__tuple__", tuple(_const_token(x) for x in c))
    if isinstance(c, frozenset):
        return ("__fset__", frozenset(_const_token(x) for x in c))
    return c  # str/bytes/None/Ellipsis: type-unambiguous


def _code_token(code):
    tok = _CODE_TOKENS.get(code)
    if tok is None:
        consts = tuple(_const_token(c) for c in code.co_consts)
        tok = ("__code__", code.co_code, consts, code.co_names,
               code.co_argcount, code.co_posonlyargcount,
               code.co_kwonlyargcount, code.co_flags,
               code.co_freevars, code.co_cellvars)
        _CODE_TOKENS[code] = tok
    return tok


def _fn_key(fn, depth=0):
    """Identity of the kernel computation: code CONTENT token + frozen
    closure cell values (+ defaults). Call sites with identical code —
    even at different lines/files, even when their cells hold fresh inner
    lambdas — collapse to one key: cells hash by VALUE (``_freeze`` of the
    contents, recursing through :func:`_code_token` for function values),
    never by cell identity."""
    import functools

    if isinstance(fn, functools.partial):
        return ("__partial__", _fn_key(fn.func, depth + 1),
                tuple(_freeze(a, depth + 1) for a in fn.args),
                _freeze(fn.keywords, depth + 1))
    if getattr(fn, "__self__", None) is not None:
        # bound method: __code__/__closure__ proxy the underlying function
        # and would drop the instance (and its mutable state) from the key
        raise _Unhashable
    code = getattr(fn, "__code__", None)
    if code is None:
        return fn  # builtin / C function: stable by identity
    cells = getattr(fn, "__closure__", None) or ()
    return (_code_token(code),
            _freeze(getattr(fn, "__defaults__", None), depth),
            _freeze(getattr(fn, "__kwdefaults__", None), depth),
            tuple(_freeze(c.cell_contents, depth) for c in cells))


def _sig_str(spec_parts) -> str:
    """Compact human signature for trace events: ``float32[4,8],int64[4]``
    with static args elided. Cold-path only (compile events)."""
    parts = []
    for part in spec_parts:
        if part is None or part[0] == "__static__":
            continue
        shape, dtype = part[0], part[1]
        name = getattr(dtype, "name", str(dtype))
        parts.append(f"{name}[{','.join(str(d) for d in shape)}]")
    return ",".join(parts)


_STATIC, _ARRAY, _TRACER = 0, 1, 2
_KIND_BY_TYPE: dict = {}  # exact type -> kind (jax's abc isinstance is slow)


def _arg_kind(v) -> int:
    t = type(v)
    k = _KIND_BY_TYPE.get(t)
    if k is None:
        if isinstance(v, jax.core.Tracer):
            k = _TRACER
        elif isinstance(v, (jax.Array, np.ndarray)):
            k = _ARRAY
        else:
            k = _STATIC
        _KIND_BY_TYPE[t] = k
    return k


# ---------------------------------------------------------------------------
# cache state + stats
# ---------------------------------------------------------------------------

_cache: "OrderedDict[Any, _Entry]" = OrderedDict()
# ordered set of keys whose entry failed to trace (bypass without re-paying
# the failed trace). Bounded: an evicted key that fails again just re-pays
# one staging attempt, whereas an unbounded set leaks key tuples forever.
_poisoned: "OrderedDict[Any, None]" = OrderedDict()
_stats: dict = {}        # op name -> counter dict
_kernel_cacheable = None  # lazily bound registry.kernel_cacheable (import cycle)


def _poison_cap() -> int:
    cap = int(get_flag("eager_kernel_cache_max_entries"))
    return 4 * cap if cap > 0 else 4096


def _op_stats(op: str) -> dict:
    s = _stats.get(op)
    if s is None:
        s = _stats[op] = {"hits": 0, "misses": 0, "bypasses": 0,
                          "evictions": 0, "bypass_reasons": {}}
    return s


def record_bypass(op: str, reason: str) -> None:
    """Count one fast-path bypass for ``op``. Reasons in use: ``amp``,
    ``discovery``, ``static_capture``, ``observer`` (dispatcher-level
    transparency gates), ``tracer``, ``unhashable``, ``array_capture``
    (deliberate array/Tensor/PRNG-key in the signature — dropout et al.),
    ``denied``, ``trace_failed`` (cache-level). The JX320 storm audit
    counts only ``unhashable`` — ``array_capture`` is by design."""
    s = _op_stats(op)
    s["bypasses"] += 1
    s["bypass_reasons"][reason] = s["bypass_reasons"].get(reason, 0) + 1
    if _tracer.enabled:
        _tracer.instant("kernel_cache.bypass", track="dispatch",
                        op=op, reason=reason)


_bypass = record_bypass


def stats() -> dict:
    """Cache statistics snapshot: per-op ``hits/misses/bypasses/evictions``
    (+ ``bypass_reasons``) under ``"ops"``, aggregate ``"totals"``, and the
    current ``"size"``/``"capacity"``. Consumed by ``bench.py``
    (``extras.dispatch``) and the JX32x kernel-cache audit."""
    ops = {op: {**s, "bypass_reasons": dict(s["bypass_reasons"])}
           for op, s in _stats.items()}
    totals = {k: sum(s[k] for s in _stats.values())
              for k in ("hits", "misses", "bypasses", "evictions")}
    return {"ops": ops, "totals": totals, "size": len(_cache),
            "capacity": int(get_flag("eager_kernel_cache_max_entries"))}


def cost_stats(max_entries: Optional[int] = None) -> dict:
    """Per-entry static cost of every cached executable: retrace each
    entry's staged function from the (shape, dtype) specs its cache key
    already records and run the analysis cost model over the jaxpr
    (``analysis/cost_model.py`` — tracing only, no XLA compilation, no
    counters touched). On-demand companion to :func:`stats`, which stays
    a pure counter read; ``max_entries`` bounds the walk to the N most
    recently used entries."""
    import jax

    from ..analysis.cost_model import cost_jaxpr

    items = list(_cache.items())
    if max_entries is not None and max_entries > 0:
        items = items[-max_entries:]  # OrderedDict: tail = most recent
    entries = []
    totals = {"flops": 0.0, "bytes_read": 0.0, "bytes_written": 0.0,
              "peak_bytes": 0}
    for key, entry in items:
        sds = [jax.ShapeDtypeStruct(tuple(part[0]), part[1])
               for part in key[2] if part[0] != "__static__"]
        row = {"op": entry.op, "has_vjp": entry.has_vjp}
        try:
            closed = jax.make_jaxpr(entry.fwd)(*sds)
            rep = cost_jaxpr(closed, location=f"kernel_cache:{entry.op}")
        except Exception as e:
            row["error"] = str(e).splitlines()[0]
            entries.append(row)
            continue
        row.update(flops=rep.flops, bytes_read=rep.bytes_read,
                   bytes_written=rep.bytes_written, peak_bytes=rep.peak_bytes,
                   arithmetic_intensity=round(rep.arithmetic_intensity, 4))
        totals["flops"] += rep.flops
        totals["bytes_read"] += rep.bytes_read
        totals["bytes_written"] += rep.bytes_written
        totals["peak_bytes"] = max(totals["peak_bytes"], rep.peak_bytes)
        entries.append(row)
    return {"entries": entries, "totals": totals, "n_entries": len(entries)}


def clear(reset_stats: bool = True) -> None:
    """Drop every cached executable (and, by default, the counters)."""
    _cache.clear()
    _poisoned.clear()
    if reset_stats:
        _stats.clear()


def poison(key, op: str) -> None:
    """Bypass ``key`` from now on: its entry failed to trace or execute
    (data-dependent shapes, host ops or RNG draws inside the kernel). The
    slow path serves every later call without re-paying the failed trace."""
    _cache.pop(key, None)
    _poisoned[key] = None
    while len(_poisoned) > _poison_cap():
        _poisoned.popitem(last=False)
    _bypass(op, "trace_failed")


# ---------------------------------------------------------------------------
# entries
# ---------------------------------------------------------------------------

class _Entry:
    __slots__ = ("key", "op", "fwd", "bwd", "traced_idx", "has_vjp", "staged",
                 "exec")

    def __init__(self, key, op, fwd, bwd, traced_idx, has_vjp):
        self.key = key
        self.op = op
        self.fwd = fwd            # jitted: (*arrays) -> out | (out, vjp Partial)
        # per-ENTRY jitted pullback applier: each staging trace mints a
        # pullback with a fresh static identity, so a process-shared applier
        # would retain one compiled backward per staging forever — here the
        # executable's lifetime is the entry's (plus any live GradNode's)
        self.bwd = bwd
        self.traced_idx = traced_idx
        self.has_vjp = has_vjp
        self.staged = False       # first call traces; later calls replay
        # AOT Compiled from the persistent disk tier (compile_cache): set
        # at staging when FLAGS_compile_cache restored or published this
        # entry's executable; replaces fwd on the replay path (fwd stays —
        # cost_stats retraces it on demand, a Compiled is not traceable)
        self.exec = None


def _build(key, op, fn, values, attrs, diff_idx, traced_idx) -> _Entry:
    """Stage the op into one jitted executable. Static (non-array) args are
    baked from this call's values — the key proves equality for every
    future hit. For differentiable calls the staged function returns
    ``jax.vjp``'s ``(out, pullback)`` pair; the pullback crosses the jit
    boundary as a ``Partial`` pytree carrying the residual arrays."""
    tset = set(traced_idx)
    static_vals = tuple(None if i in tset else values[i]
                        for i in range(len(values)))
    diff = tuple(diff_idx)
    traced = tuple(traced_idx)
    has_vjp = bool(diff)

    def staged(*arrs):
        full = list(static_vals)
        for j, i in enumerate(traced):
            full[i] = arrs[j]
        if not has_vjp:
            return fn(*full, **attrs)
        dvals = tuple(full[i] for i in diff)

        def partial_fn(*dv):
            f2 = list(full)
            for i, v in zip(diff, dv):
                f2[i] = v
            return fn(*f2, **attrs)

        return jax.vjp(partial_fn, *dvals)

    bwd = (jax.jit(lambda pullback, cotangent: pullback(cotangent))
           if has_vjp else None)
    return _Entry(key, op, jax.jit(staged), bwd, traced, has_vjp)


def lookup(op: str, fn, values: Sequence[Any], attrs: dict,
           diff_idx: Sequence[int]) -> Optional[_Entry]:
    """The cached executable for this call signature, building it on a
    miss. ``None`` means bypass (reason recorded in :func:`stats`): the
    call must take the slow path. Never raises on key trouble — unhashable
    attrs/cells and tracer inputs degrade to a counted bypass."""
    global _kernel_cacheable
    if _kernel_cacheable is None:
        from ..ops.registry import kernel_cacheable as _kernel_cacheable
    if not _kernel_cacheable(op):
        _bypass(op, "denied")
        return None
    try:
        n = len(values)
        spec_parts = [None] * n  # pre-sized: no list growth on the hot path
        traced_idx = []
        if diff_idx:
            diff = set(diff_idx)
            for i in range(n):
                v = values[i]
                kind = _arg_kind(v)
                if kind == _TRACER:
                    _bypass(op, "tracer")
                    return None
                if kind == _ARRAY:
                    traced_idx.append(i)
                    spec_parts[i] = (v.shape, v.dtype, i in diff)
                else:
                    spec_parts[i] = ("__static__", _freeze(v))
        else:
            # no-grad fast path: on single-primitive ops the key build IS
            # the dispatch cost — skip the diff-set allocation and the
            # per-arg membership test entirely
            for i in range(n):
                v = values[i]
                kind = _arg_kind(v)
                if kind == _TRACER:
                    _bypass(op, "tracer")
                    return None
                if kind == _ARRAY:
                    traced_idx.append(i)
                    spec_parts[i] = (v.shape, v.dtype, False)
                else:
                    spec_parts[i] = ("__static__", _freeze(v))
        key = (op, _fn_key(fn), tuple(spec_parts),
               _freeze(attrs) if attrs else None)
        hash(key)
    except _Unhashable as e:
        _bypass(op, e.reason)
        return None
    except TypeError:
        _bypass(op, "unhashable")
        return None

    if key in _poisoned:
        _bypass(op, "trace_failed")
        return None

    entry = _cache.get(key)
    s = _op_stats(op)
    if entry is not None:
        s["hits"] += 1
        _cache.move_to_end(key)
        if _tracer.enabled:
            _tracer.instant("kernel_cache.hit", track="dispatch", op=op)
        return entry

    s["misses"] += 1
    t0 = _time.perf_counter() if _tracer.enabled else 0.0
    try:
        entry = _build(key, op, fn, values, attrs, tuple(diff_idx),
                       tuple(traced_idx))
    except Exception:
        poison(key, op)
        return None
    if _tracer.enabled:
        # the dispatch compile event: which op, what signature, why it
        # missed (a fresh signature — bypasses record their own reason),
        # and what the build cost on the wall clock
        _tracer.emit("kernel_cache.compile", t0, _time.perf_counter() - t0,
                     track="dispatch", op=op, signature=_sig_str(spec_parts),
                     reason="new_signature", has_vjp=bool(diff_idx))
    _cache[key] = entry
    cap = int(get_flag("eager_kernel_cache_max_entries"))
    while len(_cache) > cap > 0:
        _, evicted = _cache.popitem(last=False)
        _op_stats(evicted.op)["evictions"] += 1
    return entry


def execute(entry: _Entry, values: Sequence[Any]):
    """Run the cached executable on this call's array args. Returns the
    raw forward output, or ``(out, CachedVJP)`` for differentiable
    entries. Raises on the first call if the kernel cannot be staged
    (the dispatcher poisons the key and falls back)."""
    arrs = tuple(values[i] for i in entry.traced_idx)
    if not entry.staged:
        return _staging_call(entry, arrs)
    if not entry.has_vjp:
        fwd = entry.exec
        return entry.fwd(*arrs) if fwd is None else fwd(*arrs)
    out, pullback = entry.fwd(*arrs)
    return out, CachedVJP(pullback, entry.bwd)


def _staging_call(entry: _Entry, arrs):
    """First execution of a fresh entry — the call that traces the kernel.
    A kernel that draws from the global RNG inside its body would both
    freeze its randomness into the executable and write a jit tracer into
    the generator cell, corrupting every later random op process-wide
    (framework random ops split the key host-side, outside the kernel —
    this guards the custom-op surface). Detect it, repair the generator,
    and refuse the entry so the dispatcher poisons the key."""
    from ..base.global_state import default_generator as gen

    cell = gen._cell
    before = None if cell is None else cell._value
    clean_before = before is None or not isinstance(before, jax.core.Tracer)
    publish = None
    try:
        if not entry.has_vjp:
            result, publish = _persistent_stage(entry, arrs)
        else:
            _note_vjp_skip()
            out, pullback = entry.fwd(*arrs)
            result = (out, CachedVJP(pullback, entry.bwd))
    except Exception:
        if clean_before:
            _repair_rng(gen, cell, before)
        raise
    if clean_before and _repair_rng(gen, cell, before):
        raise RuntimeError(
            f"kernel for op '{entry.op}' drew from the global RNG under the "
            "staging trace — split the key outside the kernel body")
    entry.staged = True
    if publish is not None:
        # publish to the persistent tier only now, AFTER the RNG guard
        # accepted the staging: a refused kernel must never reach disk (a
        # warm restore replays the executable without tracing, so the
        # guard could not re-detect the frozen-randomness defect there)
        publish()
    return result


def _persistent_stage(entry: _Entry, arrs):
    """Stage one no-VJP entry, riding the persistent compile cache when
    FLAGS_compile_cache is on: restore the AOT executable from disk (zero
    trace, zero compile) or AOT-compile it. Returns ``(result,
    publish)`` — ``publish`` (or None) is the deferred disk write the
    caller runs only after the staging RNG guard accepts the kernel.
    Disabled, or when the signature cannot be canonicalized, this is
    exactly the legacy ``entry.fwd(*arrs)`` staging call. Trace/compile
    failures propagate — the dispatcher poisons the key the same way it
    always has."""
    from .. import compile_cache as cc

    if not cc.enabled():
        return entry.fwd(*arrs), None
    digest = cc.derive_digest("kernel", entry.key)
    if digest is None:
        cc.record("key_skip")
        return entry.fwd(*arrs), None
    compiled = cc.load_executable(digest, site="kernel:" + entry.op)
    publish = None
    if compiled is None:
        compiled = entry.fwd.lower(*arrs).compile()

        def publish(digest=digest, compiled=compiled):
            cc.store_executable(digest, compiled,
                                key_meta={"site": "kernel", "op": entry.op})

    entry.exec = compiled
    return compiled(*arrs), publish


def _note_vjp_skip() -> None:
    """Count a differentiable entry staying in-memory only: the pullback
    ``Partial``'s treedef closes over a jax-internal local function and
    cannot serialize (see compile_cache docs)."""
    from .. import compile_cache as cc

    if cc.enabled():
        cc.record("vjp_skip")


def _repair_rng(gen, cell_before, value_before) -> bool:
    """Restore the global generator if the staging trace leaked a tracer
    into it. Returns True when corruption was found (and undone)."""
    cell = gen._cell
    if cell is None or not isinstance(cell._value, jax.core.Tracer):
        return False
    if cell is cell_before and value_before is not None:
        cell._value = value_before
        return True
    gen._cell = None  # created (or swapped) under the trace: rebuild lazily
    return True


# ---------------------------------------------------------------------------
# lazy backward
# ---------------------------------------------------------------------------

def _has_float0(cotangent) -> bool:
    leaves = cotangent if isinstance(cotangent, (tuple, list)) else (cotangent,)
    return any(isinstance(leaf, np.ndarray) and leaf.dtype == jax.dtypes.float0
               for leaf in leaves)


class CachedVJP:
    """The lazy backward handle a fast-path ``GradNode`` holds instead of a
    live ``jax.vjp`` closure: a residual-carrying ``jax.tree_util.Partial``
    emitted by the cached forward executable, plus its entry's jitted
    applier. The Partial's treedef (fixed at the entry's one staging trace)
    is the applier's jit cache key, so steady-state backward replays a
    compiled program — and the executable dies with the entry/GradNode
    instead of accumulating in a process-wide cache. ``float0`` cotangents
    (integer primal outputs) fall back to direct application: float0 is not
    a jit-transferable type."""

    __slots__ = ("pullback", "applier")

    def __init__(self, pullback, applier):
        self.pullback = pullback
        self.applier = applier

    def __call__(self, cotangent):
        if self.applier is None or _has_float0(cotangent):
            return self.pullback(cotangent)
        return self.applier(self.pullback, cotangent)
