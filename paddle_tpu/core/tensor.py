"""The eager Tensor.

TPU-native rebuild of the reference's eager tensor
(/root/reference/paddle/fluid/pybind/eager.cc p_tensor_type, autograd meta in
paddle/fluid/eager/autograd_meta.h): a thin mutable handle over a jax.Array
(or tracer, so the whole eager API is jit-traceable), carrying autograd state
(stop_gradient, grad, grad_node edge) and Paddle tensor-method surface.

Design notes (TPU-first):
- the payload is ALWAYS a jax value; eager ops dispatch asynchronously through
  XLA, so there is no per-op device synchronization;
- mutation (inplace ops, optimizer updates) swaps the payload functionally —
  under jit tracing the swap writes a tracer, which is how the functionalizer
  (paddle_tpu/jit) turns eager training steps into pure compiled programs;
- Tensor is a pytree node, so pytrees of Tensors flow through jax transforms.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..base import dtype as dtype_mod
from ..base import global_state
from ..base.enforce import InvalidArgumentError, enforce
from . import hooks


def _to_jax(value, dtype=None):
    if dtype is None and isinstance(value, jax.Array):
        # hot path: op outputs are already device arrays (or tracers, which
        # subclass jax.Array) — re-running jnp.asarray's dtype lattice on
        # every output wrap was measurable per eager op
        return value
    if isinstance(value, Tensor):
        value = value._value
    if dtype is not None:
        npd = dtype_mod.np_dtype(dtype)
        if isinstance(value, (jax.Array,)) or hasattr(value, "aval"):
            return value.astype(npd) if value.dtype != npd else value
        return jnp.asarray(value, dtype=npd)
    if isinstance(value, (bool, int)):
        # Paddle promotes python ints to int64; keep int32 on TPU (native word).
        return jnp.asarray(value, dtype=jnp.bool_ if isinstance(value, bool) else jnp.int64)
    if isinstance(value, float):
        return jnp.asarray(value, dtype=dtype_mod.np_dtype(global_state.default_dtype))
    return jnp.asarray(value)


_tensor_counter = [0]


class Tensor:
    __slots__ = (
        "_value",
        "stop_gradient",
        "_grad",
        "_grad_node",
        "_output_index",
        "_name",
        "persistable",
        "_backward_hooks",
        "_placements",
        "_process_mesh",
        "is_parameter",
        "trainable",
        "_version",
        "__weakref__",
    )

    def __init__(self, value, dtype=None, stop_gradient=True, name=None, persistable=False):
        if hooks.discovery is not None:
            hooks.discovery.record_create(self)
        self._value = _to_jax(value, dtype)
        self.stop_gradient = bool(stop_gradient)
        self._grad = None
        self._grad_node = None
        self._output_index = 0
        self._name = name  # None -> lazily derived on first access
        self.persistable = persistable
        self._backward_hooks = None
        self._placements = None  # auto-parallel placement annotation
        self._process_mesh = None
        self.is_parameter = False
        self.trainable = True
        self._version = 0

    # -------------------------------------------------- meta
    @property
    def name(self):
        """Auto-generated names are derived lazily: allocating the counter
        and the f-string per Tensor was measurable on the eager dispatch
        hot path, and most tensors never have their name read."""
        n = self._name
        if n is None:
            _tensor_counter[0] += 1
            n = self._name = f"generated_tensor_{_tensor_counter[0]}"
        return n

    @name.setter
    def name(self, value):
        self._name = value

    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    dim = ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def dtype(self):
        return dtype_mod.convert_dtype(self._value.dtype)

    @property
    def place(self):
        try:
            dev = list(self._value.devices())[0]
            return str(dev)
        except Exception:
            return "traced"

    @property
    def is_leaf(self):
        return self._grad_node is None

    def numel(self):
        from ..ops import creation

        return creation.to_tensor(self.size, dtype="int64")

    def __len__(self):
        if not self._value.shape:
            raise TypeError("len() of a 0-D tensor")
        return self._value.shape[0]

    def __repr__(self):
        try:
            val = np.asarray(self._value)
            body = np.array2string(val, precision=8, separator=", ")
        except Exception:
            body = f"<traced {self._value}>"
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
            f"stop_gradient={self.stop_gradient},\n       {body})"
        )

    # -------------------------------------------------- value access
    def numpy(self):
        return np.asarray(self._value)

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def __int__(self):
        return int(self.item())

    def __float__(self):
        return float(self.item())

    def __bool__(self):
        if self.size != 1:
            raise InvalidArgumentError(
                "The truth value of a Tensor with more than one element is ambiguous"
            )
        from . import hooks

        if hooks.branch_trace is not None:
            return hooks.branch_trace.on_bool(self)
        return bool(self.item())

    def __index__(self):
        return int(self.item())

    def __hash__(self):
        return id(self)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # -------------------------------------------------- autograd
    @property
    def grad(self):
        return self._grad

    @grad.setter
    def grad(self, g):
        self._grad = g

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def backward(self, grad_tensor=None, retain_graph=False):
        from . import autograd

        autograd.backward_from(self, grad_tensor, retain_graph)

    def register_hook(self, hook):
        if self._backward_hooks is None:
            self._backward_hooks = []
        self._backward_hooks.append(hook)

        class _Removable:
            def __init__(self, hooks, h):
                self._hooks, self._h = hooks, h

            def remove(self):
                if self._h in self._hooks:
                    self._hooks.remove(self._h)

        return _Removable(self._backward_hooks, hook)

    def detach(self):
        t = Tensor(self._value, stop_gradient=True, name=self.name + "_detached")
        return t

    def detach_(self):
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self):
        from ..ops import math as math_ops

        return math_ops.assign(self)

    # -------------------------------------------------- mutation
    def _replace_value(self, new_value):
        """Swap the payload (functional mutation). Bumps the inplace version."""
        if hooks.discovery is not None:
            hooks.discovery.record_write(self)
        self._value = new_value
        self._version += 1

    def set_value(self, value):
        v = _to_jax(value)
        enforce(
            tuple(v.shape) == tuple(self._value.shape),
            f"set_value shape mismatch: {v.shape} vs {self._value.shape}",
        )
        v = v.astype(self._value.dtype)
        # keep an explicit mesh layout (TP/auto-parallel placement) sticky
        old_sharding = getattr(self._value, "sharding", None)
        if old_sharding is not None and getattr(old_sharding, "mesh", None) is not None and not isinstance(v, jax.core.Tracer):
            v = jax.device_put(v, old_sharding)
        self._replace_value(v)

    def copy_(self, other, blocking=True):
        self.set_value(other)
        return self

    def fill_(self, value):
        self._replace_value(jnp.full_like(self._value, value))
        return self

    def zero_(self):
        self._replace_value(jnp.zeros_like(self._value))
        return self

    # -------------------------------------------------- conversion / movement
    def astype(self, dtype):
        from ..ops import manipulation

        return manipulation.cast(self, dtype)

    def cast(self, dtype):
        return self.astype(dtype)

    def to(self, *args, **kwargs):
        """to(device), to(dtype), to(device, dtype) — device moves via device_put."""
        device = kwargs.get("device")
        dtype = kwargs.get("dtype")
        blocking = kwargs.get("blocking", None)  # noqa: F841 (accepted for compat)
        for a in args:
            if isinstance(a, str) and (
                a.startswith(("cpu", "tpu", "gpu", "xpu")) or ":" in a
            ):
                device = a
            elif isinstance(a, (dtype_mod.DType,)) or (isinstance(a, str)):
                dtype = a
            else:
                device = a
        out = self
        if dtype is not None:
            out = out.astype(dtype)
        if device is not None:
            from ..device import _resolve_device

            out = Tensor(
                jax.device_put(out._value, _resolve_device(device)),
                stop_gradient=out.stop_gradient,
            )
        return out

    def cpu(self):
        return Tensor(jax.device_put(self._value, jax.devices("cpu")[0]), stop_gradient=self.stop_gradient)

    def cuda(self, *a, **k):  # compat: maps to the accelerator
        return self.to(device="tpu")

    def tpu(self):
        return self.to(device="tpu")

    def pin_memory(self):
        return self

    def contiguous(self):
        return self

    def is_contiguous(self):
        return True

    # -------------------------------------------------- operator protocol
    def _binary(self, opname, other, reverse=False):
        from ..ops import math as m

        fn = getattr(m, opname)
        return fn(other, self) if reverse else fn(self, other)

    def __add__(self, o):
        return self._binary("add", o)

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary("subtract", o)

    def __rsub__(self, o):
        return self._binary("subtract", o, reverse=True)

    def __mul__(self, o):
        return self._binary("multiply", o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary("divide", o)

    def __rtruediv__(self, o):
        return self._binary("divide", o, reverse=True)

    def __floordiv__(self, o):
        return self._binary("floor_divide", o)

    def __rfloordiv__(self, o):
        return self._binary("floor_divide", o, reverse=True)

    def __mod__(self, o):
        return self._binary("mod", o)

    def __rmod__(self, o):
        return self._binary("mod", o, reverse=True)

    def __pow__(self, o):
        return self._binary("pow", o)

    def __rpow__(self, o):
        return self._binary("pow", o, reverse=True)

    def __matmul__(self, o):
        return self._binary("matmul", o)

    def __rmatmul__(self, o):
        return self._binary("matmul", o, reverse=True)

    def __neg__(self):
        from ..ops import math as m

        return m.neg(self)

    def __abs__(self):
        from ..ops import math as m

        return m.abs(self)

    def __eq__(self, o):
        from ..ops import logic

        return logic.equal(self, o)

    def __ne__(self, o):
        from ..ops import logic

        return logic.not_equal(self, o)

    def __lt__(self, o):
        from ..ops import logic

        return logic.less_than(self, o)

    def __le__(self, o):
        from ..ops import logic

        return logic.less_equal(self, o)

    def __gt__(self, o):
        from ..ops import logic

        return logic.greater_than(self, o)

    def __ge__(self, o):
        from ..ops import logic

        return logic.greater_equal(self, o)

    def __invert__(self):
        from ..ops import logic

        return logic.logical_not(self)

    def __and__(self, o):
        from ..ops import logic

        return logic.logical_and(self, o) if self.dtype == dtype_mod.bool_ else logic.bitwise_and(self, o)

    def __or__(self, o):
        from ..ops import logic

        return logic.logical_or(self, o) if self.dtype == dtype_mod.bool_ else logic.bitwise_or(self, o)

    def __xor__(self, o):
        from ..ops import logic

        return logic.logical_xor(self, o) if self.dtype == dtype_mod.bool_ else logic.bitwise_xor(self, o)

    def __getitem__(self, idx):
        from ..ops import manipulation

        return manipulation.getitem(self, idx)

    def __setitem__(self, idx, value):
        from ..ops import manipulation

        manipulation.setitem_(self, idx, value)

    # -------------------------------------------------- method surface
    # (populated further by paddle_tpu/core/tensor_methods.py monkey-patching,
    #  mirroring the reference's python/paddle/tensor method patching)

    @property
    def T(self):
        from ..ops import linalg

        return linalg.t_nd(self)

    @property
    def mT(self):
        from ..ops import manipulation

        perm = list(range(self.ndim))
        perm[-1], perm[-2] = perm[-2], perm[-1]
        return manipulation.transpose(self, perm)

    # auto-parallel annotations
    @property
    def placements(self):
        return self._placements

    @property
    def process_mesh(self):
        return self._process_mesh

    def is_dist(self):
        return self._placements is not None


def _tensor_flatten(t: Tensor):
    # aux must NOT carry per-instance auto-generated names: treedef equality
    # is the jit cache key, and unique names would force a recompile for
    # every fresh input tensor. Persistable tensors (parameters/buffers)
    # keep their stable names.
    return (t._value,), (t.stop_gradient, t.name if t.persistable else None)


def _tensor_unflatten(aux, children):
    (value,) = children
    stop_gradient, name = aux
    out = Tensor.__new__(Tensor)
    out._value = value
    out.stop_gradient = stop_gradient
    out._grad = None
    out._grad_node = None
    out._output_index = 0
    out._name = name
    out.persistable = False
    out._backward_hooks = None
    out._placements = None
    out._process_mesh = None
    out.is_parameter = False
    out.trainable = True
    out._version = 0
    return out


jax.tree_util.register_pytree_node(Tensor, _tensor_flatten, _tensor_unflatten)


class Parameter(Tensor):
    """Trainable parameter: stop_gradient defaults to False (reference:
    python/paddle/base/framework.py Parameter / EagerParamBase)."""

    __slots__ = ("optimize_attr", "regularizer", "need_clip", "init_fn")

    def __init__(self, value, dtype=None, name=None, trainable=True):
        super().__init__(value, dtype=dtype, stop_gradient=not trainable, name=name, persistable=True)
        self.is_parameter = True
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.init_fn = None


def _param_unflatten(aux, children):
    t = _tensor_unflatten(aux, children)
    p = Parameter.__new__(Parameter)
    for slot in (
        "_value", "stop_gradient", "_grad", "_grad_node", "_output_index", "_name",
        "persistable", "_backward_hooks", "_placements", "_process_mesh",
        "is_parameter", "trainable", "_version",
    ):
        setattr(p, slot, getattr(t, slot))
    p.is_parameter = True
    p.trainable = not t.stop_gradient
    p.optimize_attr = {"learning_rate": 1.0}
    p.regularizer = None
    p.need_clip = True
    p.init_fn = None
    return p


jax.tree_util.register_pytree_node(Parameter, _tensor_flatten, _param_unflatten)


def unwrap(x):
    """Tensor | array-like -> jax value."""
    return x._value if isinstance(x, Tensor) else x


def wrap(value, stop_gradient=True):
    return Tensor(value, stop_gradient=stop_gradient)
