"""Op dispatch: the bridge from functional ops to jax + the autograd tape.

Rebuild of the reference's generated ``xxx_ad_func`` layer
(/root/reference/paddle/fluid/eager/auto_code_generator/generator/eager_gen.py):
every framework op funnels through :func:`primitive`, which
- unwraps Tensor arguments to jax values,
- applies AMP autocasting when an amp state is active (reference
  paddle/fluid/imperative/amp_auto_cast.cc),
- runs the op's jax implementation (async XLA dispatch),
- when grad is required, captures a VJP closure via jax.vjp and wires a
  GradNode into the tape,
- optionally NaN/Inf-scans outputs (FLAGS_check_nan_inf, reference
  paddle/fluid/eager/nan_inf_utils.cc).

There is no KernelFactory/KernelKey here by design: on TPU, kernel selection
is XLA compilation. The op "registry" is the set of python op functions plus
OP_ATTRS metadata used by AMP lists and the profiler.
"""
from __future__ import annotations

from typing import Any, Callable, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..base import global_state
from ..base.flags import get_flag
from . import hooks
from .tensor import Tensor, unwrap


def _is_float(v) -> bool:
    try:
        return jnp.issubdtype(jnp.asarray(v).dtype if not hasattr(v, "dtype") else v.dtype, jnp.inexact)
    except Exception:
        return False


def _requires_grad(t) -> bool:
    return isinstance(t, Tensor) and not t.stop_gradient


def _check_nan_inf(name, values):
    for v in values:
        if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.inexact):
            arr = np.asarray(v)
            if not np.isfinite(arr).all():
                from ..base.enforce import PreconditionNotMetError

                raise PreconditionNotMetError(f"op '{name}' produced NaN/Inf output")



def _observe(name, out_list):
    """Post-dispatch output taps: nan/inf scan (FLAGS_check_nan_inf) and the
    amp.debugging observer (tensor checker / operator stats). Tracer outputs
    (ops dispatched inside a lax trace, e.g. static control-flow callables)
    are skipped — host-side value inspection cannot run under tracing."""
    if not get_flag("check_nan_inf") and hooks.op_observer is None:
        return
    vals = [o._value for o in out_list]
    if any(isinstance(v, jax.core.Tracer) for v in vals):
        return
    if get_flag("check_nan_inf"):
        _check_nan_inf(name, vals)
    if hooks.op_observer is not None:
        hooks.op_observer(name, vals)


def primitive(
    name: str,
    fn: Callable,
    tensor_args: Sequence[Any],
    attrs: dict | None = None,
    n_outputs: int | None = None,
):
    """Execute op ``fn(*arg_values, **attrs)`` with autograd recording.

    tensor_args may contain Tensors, jax values, numpy arrays or python
    scalars; gradients flow to Tensor args with stop_gradient=False whose
    dtype is floating.
    """
    attrs = attrs or {}
    if hooks.op_profiler is not None:
        with hooks.op_profiler(name):
            return _primitive_impl(name, fn, tensor_args, attrs)
    return _primitive_impl(name, fn, tensor_args, attrs)


def _primitive_impl(name, fn, tensor_args, attrs):
    amp = global_state.amp_state()
    if amp is not None:
        tensor_args = amp.cast_inputs(name, tensor_args)

    if hooks.discovery is not None:
        hooks.discovery.record_reads(tensor_args)

    values = [unwrap(a) for a in tensor_args]
    grad_on = global_state.grad_enabled()
    diff_idx = [
        i
        for i, a in enumerate(tensor_args)
        if grad_on and _requires_grad(a) and _is_float(values[i])
    ]

    if not diff_idx:
        out = fn(*values, **attrs)
        outs = _wrap_outputs(name, out, stop_gradient=True)
        _observe(name, outs if isinstance(outs, tuple) else (outs,))
        if hooks.static_capture is not None:
            hooks.static_capture.record(name, fn, tensor_args, attrs, outs)
        return outs

    # Partial-application: close over non-diff args, differentiate the rest.
    def partial_fn(*diff_vals):
        full = list(values)
        for i, v in zip(diff_idx, diff_vals):
            full[i] = v
        return fn(*full, **attrs)

    diff_vals = [values[i] for i in diff_idx]
    out, vjp_fn = jax.vjp(partial_fn, *diff_vals)

    outs = _wrap_outputs(name, out, stop_gradient=False)
    out_list = outs if isinstance(outs, tuple) else (outs,)

    from .autograd import GradNode

    node = GradNode(
        name=name,
        vjp_fn=vjp_fn,
        inputs=[tensor_args[i] for i in diff_idx],
        n_outputs=len(out_list),
        out_specs=[(tuple(o._value.shape), o._value.dtype) for o in out_list],
        recompute=(fn, values, attrs, diff_idx),
    )
    for i, o in enumerate(out_list):
        o._grad_node = node
        o._output_index = i

    _observe(name, out_list)
    if hooks.static_capture is not None:
        hooks.static_capture.record(name, fn, tensor_args, attrs, outs)
    return outs


def _wrap_outputs(name, out, stop_gradient):
    if isinstance(out, (tuple, list)):
        return tuple(Tensor(o, stop_gradient=stop_gradient, name=f"{name}_out{i}") for i, o in enumerate(out))
    return Tensor(out, stop_gradient=stop_gradient, name=f"{name}_out")


def passthrough(name: str, fn: Callable, tensor_args: Sequence[Any], attrs: dict | None = None):
    """Non-differentiable op (integer/bool outputs, comparisons, argmax...)."""
    attrs = attrs or {}
    if hooks.discovery is not None:
        hooks.discovery.record_reads(tensor_args)
    values = [unwrap(a) for a in tensor_args]
    out = fn(*values, **attrs)
    outs = _wrap_outputs(name, out, stop_gradient=True)
    _observe(name, outs if isinstance(outs, tuple) else (outs,))
    if hooks.static_capture is not None:
        hooks.static_capture.record(name, fn, tensor_args, attrs, outs)
    return outs
