"""Op dispatch: the bridge from functional ops to jax + the autograd tape.

Rebuild of the reference's generated ``xxx_ad_func`` layer
(/root/reference/paddle/fluid/eager/auto_code_generator/generator/eager_gen.py):
every framework op funnels through :func:`primitive`, which
- unwraps Tensor arguments to jax values,
- applies AMP autocasting when an amp state is active (reference
  paddle/fluid/imperative/amp_auto_cast.cc),
- runs the op's jax implementation (async XLA dispatch),
- when grad is required, captures a VJP for the tape — from the
  signature-keyed kernel cache (core/kernel_cache.py, the analog of the
  reference's cached ad_func fast path) on the fast path, or a fresh
  ``jax.vjp`` trace on the slow path,
- optionally NaN/Inf-scans outputs (FLAGS_check_nan_inf, reference
  paddle/fluid/eager/nan_inf_utils.cc).

Fast-path transparency contract: the kernel cache is consulted only when
the dispatch is semantically invisible — no active AMP cast insertion, no
discovery / static-capture / op-observer hooks, no tracer inputs, and a
fully hashable signature. Every skip is a counted bypass
(``kernel_cache.stats()``); ``FLAGS_eager_kernel_cache=0`` disables the
path entirely.

There is no KernelFactory/KernelKey here by design: on TPU, kernel selection
is XLA compilation. The op "registry" is the set of python op functions plus
OP_ATTRS metadata used by AMP lists and the profiler.
"""
from __future__ import annotations

from typing import Any, Callable, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..base import global_state
from ..base.flags import get_flag
from . import hooks, kernel_cache
from .tensor import Tensor, unwrap

# dtype -> is-inexact memo: `jnp.issubdtype` walks the numpy type lattice,
# far too slow to pay per argument per op call.
_DTYPE_IS_FLOAT: dict = {}
# python scalar types whose floatness is content-independent; containers
# (list/tuple) are deliberately NOT memoized — their dtype depends on content.
_SCALAR_IS_FLOAT: dict = {float: True, int: False, bool: False,
                          complex: True, str: False, bytes: False,
                          type(None): False}


def _is_float(v) -> bool:
    dt = getattr(v, "dtype", None)
    if dt is not None:
        try:
            return _DTYPE_IS_FLOAT[dt]
        except KeyError:
            r = bool(jnp.issubdtype(dt, jnp.inexact))
            _DTYPE_IS_FLOAT[dt] = r
            return r
        except TypeError:
            return bool(jnp.issubdtype(dt, jnp.inexact))
    t = type(v)
    r = _SCALAR_IS_FLOAT.get(t)
    if r is not None:
        return r
    try:
        return bool(jnp.issubdtype(jnp.asarray(v).dtype, jnp.inexact))
    except Exception:
        return False


def _requires_grad(t) -> bool:
    return isinstance(t, Tensor) and not t.stop_gradient


def _check_nan_inf(name, values):
    """One batched device read per op (not one ``np.asarray`` round-trip
    per output): every float output's ``isfinite`` collapses to a single
    scalar on device; the lone host sync is the final ``bool()``."""
    finite = [jnp.all(jnp.isfinite(v)) for v in values
              if hasattr(v, "dtype") and _is_float(v)]
    if not finite:
        return
    ok = finite[0]
    for f in finite[1:]:
        ok = jnp.logical_and(ok, f)
    if not bool(ok):
        from ..base.enforce import PreconditionNotMetError

        raise PreconditionNotMetError(f"op '{name}' produced NaN/Inf output")


def _observe(name, out_list):
    """Post-dispatch output taps: nan/inf scan (FLAGS_check_nan_inf) and the
    amp.debugging observer (tensor checker / operator stats). Tracer outputs
    (ops dispatched inside a lax trace, e.g. static control-flow callables)
    are skipped — host-side value inspection cannot run under tracing."""
    if not get_flag("check_nan_inf") and hooks.op_observer is None:
        return
    vals = [o._value for o in out_list]
    if any(isinstance(v, jax.core.Tracer) for v in vals):
        return
    if get_flag("check_nan_inf"):
        _check_nan_inf(name, vals)
    if hooks.op_observer is not None:
        hooks.op_observer(name, vals)


def primitive(
    name: str,
    fn: Callable,
    tensor_args: Sequence[Any],
    attrs: dict | None = None,
    n_outputs: int | None = None,
):
    """Execute op ``fn(*arg_values, **attrs)`` with autograd recording.

    tensor_args may contain Tensors, jax values, numpy arrays or python
    scalars; gradients flow to Tensor args with stop_gradient=False whose
    dtype is floating.
    """
    attrs = attrs or {}
    if hooks.op_profiler is not None:
        with hooks.op_profiler(name):
            return _primitive_impl(name, fn, tensor_args, attrs)
    return _primitive_impl(name, fn, tensor_args, attrs)


def _fast_path_reason(amp):
    """Transparency gate for the kernel cache: the active signature-changing
    interception point that self-disables the fast path (None = go fast)."""
    if amp is not None:
        return "amp"
    if hooks.discovery is not None:
        return "discovery"
    if hooks.static_capture is not None:
        return "static_capture"
    if hooks.op_observer is not None:
        return "observer"
    return None


def _primitive_impl(name, fn, tensor_args, attrs):
    amp = global_state.amp_state()
    if amp is not None:
        tensor_args = amp.cast_inputs(name, tensor_args)

    if hooks.discovery is not None:
        hooks.discovery.record_reads(tensor_args)

    values = [unwrap(a) for a in tensor_args]
    grad_on = global_state.grad_enabled()
    diff_idx = [
        i
        for i, a in enumerate(tensor_args)
        if grad_on and _requires_grad(a) and _is_float(values[i])
    ]

    if get_flag("eager_kernel_cache"):
        reason = _fast_path_reason(amp)
        if reason is None:
            entry = kernel_cache.lookup(name, fn, values, attrs, diff_idx)
            if entry is not None:
                try:
                    result = kernel_cache.execute(entry, values)
                except Exception:
                    if entry.staged:
                        # a proven executable failed at runtime (OOM, bad
                        # input): that error is the caller's to see, not a
                        # reason to demote the op to trace-per-call forever
                        raise
                    # the kernel refuses staging (data-dependent shapes,
                    # host ops, RNG draws): poison the key so later calls
                    # skip straight to the slow path, and serve this one
                    # eagerly below.
                    kernel_cache.poison(entry.key, name)
                else:
                    return _finish_fast(name, fn, values, attrs, diff_idx,
                                        tensor_args, entry, result)
        else:
            kernel_cache.record_bypass(name, reason)

    if not diff_idx:
        out = fn(*values, **attrs)
        outs = _wrap_outputs(name, out, stop_gradient=True)
        _observe(name, outs if isinstance(outs, tuple) else (outs,))
        if hooks.static_capture is not None:
            hooks.static_capture.record(name, fn, tensor_args, attrs, outs)
        return outs

    # Partial-application: close over non-diff args, differentiate the rest.
    def partial_fn(*diff_vals):
        full = list(values)
        for i, v in zip(diff_idx, diff_vals):
            full[i] = v
        return fn(*full, **attrs)

    diff_vals = [values[i] for i in diff_idx]
    out, vjp_fn = jax.vjp(partial_fn, *diff_vals)

    outs = _wrap_outputs(name, out, stop_gradient=False)
    out_list = outs if isinstance(outs, tuple) else (outs,)
    _record_grad_node(name, fn, values, attrs, diff_idx, tensor_args,
                      vjp_fn, out_list)
    _observe(name, out_list)
    if hooks.static_capture is not None:
        hooks.static_capture.record(name, fn, tensor_args, attrs, outs)
    return outs


def _finish_fast(name, fn, values, attrs, diff_idx, tensor_args, entry, result):
    """Wrap a cache-hit execution: identical output wrapping, tape wiring
    and observer taps as the slow path — only the trace is skipped."""
    if not entry.has_vjp:
        outs = _wrap_outputs(name, result, stop_gradient=True)
        _observe(name, outs if isinstance(outs, tuple) else (outs,))
        return outs
    out, cached_vjp = result
    outs = _wrap_outputs(name, out, stop_gradient=False)
    out_list = outs if isinstance(outs, tuple) else (outs,)
    _record_grad_node(name, fn, values, attrs, diff_idx, tensor_args,
                      cached_vjp, out_list)
    _observe(name, out_list)
    return outs


def _record_grad_node(name, fn, values, attrs, diff_idx, tensor_args,
                      vjp_fn, out_list):
    from .autograd import GradNode

    node = GradNode(
        name=name,
        vjp_fn=vjp_fn,
        inputs=[tensor_args[i] for i in diff_idx],
        n_outputs=len(out_list),
        out_specs=[(tuple(o._value.shape), o._value.dtype) for o in out_list],
        recompute=(fn, values, attrs, diff_idx),
    )
    for i, o in enumerate(out_list):
        o._grad_node = node
        o._output_index = i
    return node


# Output-name interning (hot path): one precomputed tuple per (op, arity)
# instead of an f-string allocation per output per call.
_OUT_NAMES: dict = {}


def _out_names(name: str, arity: int) -> tuple:
    key = (name, arity)
    try:
        return _OUT_NAMES[key]
    except KeyError:
        names = (tuple(f"{name}_out{i}" for i in range(arity))
                 if arity >= 0 else (f"{name}_out",))
        _OUT_NAMES[key] = names
        return names


def _wrap_outputs(name, out, stop_gradient):
    if isinstance(out, (tuple, list)):
        names = _out_names(name, len(out))
        return tuple(Tensor(o, stop_gradient=stop_gradient, name=names[i])
                     for i, o in enumerate(out))
    return Tensor(out, stop_gradient=stop_gradient, name=_out_names(name, -1)[0])


def _passthrough_bypass_reason():
    if hooks.discovery is not None:
        return "discovery"
    if hooks.static_capture is not None:
        return "static_capture"
    if hooks.op_observer is not None:
        return "observer"
    return None


def passthrough(name: str, fn: Callable, tensor_args: Sequence[Any], attrs: dict | None = None):
    """Non-differentiable op (integer/bool outputs, comparisons, argmax...).

    Served from the kernel cache on the same transparency contract as
    :func:`primitive` (no AMP gate — passthrough never autocasts): the
    comparison/argmax ops that pepper eager control flow replay compiled
    executables instead of re-tracing per call."""
    attrs = attrs or {}
    if hooks.discovery is not None:
        hooks.discovery.record_reads(tensor_args)
    values = [unwrap(a) for a in tensor_args]
    if get_flag("eager_kernel_cache"):
        reason = _passthrough_bypass_reason()
        if reason is None:
            entry = kernel_cache.lookup(name, fn, values, attrs, ())
            if entry is not None:
                try:
                    result = kernel_cache.execute(entry, values)
                except Exception:
                    if entry.staged:
                        raise
                    kernel_cache.poison(entry.key, name)
                else:
                    outs = _wrap_outputs(name, result, stop_gradient=True)
                    _observe(name, outs if isinstance(outs, tuple) else (outs,))
                    return outs
        else:
            kernel_cache.record_bypass(name, reason)
    out = fn(*values, **attrs)
    outs = _wrap_outputs(name, out, stop_gradient=True)
    _observe(name, outs if isinstance(outs, tuple) else (outs,))
    if hooks.static_capture is not None:
        hooks.static_capture.record(name, fn, tensor_args, attrs, outs)
    return outs
