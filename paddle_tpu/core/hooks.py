"""Cross-cutting interception points for the jit functionalizer.

``discovery`` is set by paddle_tpu/jit/functionalize.py during a discovery
run; the dispatcher reports Tensor reads, Tensor._replace_value reports
writes. Kept in its own module to avoid import cycles.
"""
from __future__ import annotations

discovery = None  # Optional[DiscoveryContext]

# set by paddle_tpu/profiler when a Profiler is in a RECORD state: a callable
# (op_name) -> context manager recording a host event around op dispatch
op_profiler = None
