"""Cross-cutting interception points for the jit functionalizer.

``discovery`` is set by paddle_tpu/jit/functionalize.py during a discovery
run; the dispatcher reports Tensor reads, Tensor._replace_value reports
writes. Kept in its own module to avoid import cycles.
"""
from __future__ import annotations

discovery = None  # Optional[DiscoveryContext]

# set by paddle_tpu/profiler when a Profiler is in a RECORD state: a callable
# (op_name) -> context manager recording a host event around op dispatch
op_profiler = None

# set by paddle_tpu/static/program.py while a Program is recording (static
# mode / program_guard): an object with record(name, fn, tensor_args, attrs,
# outputs) — ops execute eagerly on placeholder values AND append a replayable
# node to the program
static_capture = None

# set by the jit functionalizer around value-dependent branch capture: an
# object with on_bool(tensor) -> bool. In record mode it logs the concrete
# predicate; in replay mode (inside the jit trace) it returns the recorded
# outcome and collects the predicate tracer for the runtime guard.
branch_trace = None

# set by paddle_tpu/amp/debugging.py while a tensor checker or operator-stats
# collection is active: a callable (op_name, out_values) invoked after every
# dispatched op with the raw output values (reference analog: the per-kernel
# nan_inf_utils / low_precision_op_list hooks in paddle/fluid/eager).
op_observer = None
