"""Custom user-op extension point (reference:
paddle/fluid/framework/custom_operator.cc + python/paddle/utils/cpp_extension
— user C++/CUDA ops compiled into .so and registered into the op registry).

TPU-native plug-in surface: a custom op is (a) a jax-traceable forward
(jnp ops or a Pallas TPU kernel) plus (b) an optional backward rule. The
registration funnels through core.dispatch.primitive, so custom ops get
autograd-tape recording, AMP casting, NaN checks and profiler tags exactly
like built-ins — the python-level equivalent of registering a phi kernel.

    from paddle_tpu.core.custom_op import register_op

    @register_op("my_gelu", backward=my_gelu_grad)   # backward optional
    def my_gelu(x):                                   # jnp / pallas_call body
        return 0.5 * x * (1 + jnp.tanh(0.79788456 * (x + 0.044715 * x**3)))

    out = paddle.utils.run_custom_op("my_gelu", tensor)   # or the returned fn

Host-library ops (the reference's .so path): wrap the ctypes-loaded symbol
in a numpy-bridge forward and register it the same way — see
native/__init__.py for the loading pattern used by the framework itself.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

CUSTOM_OPS: Dict[str, dict] = {}


def register_op(name: str, forward: Optional[Callable] = None,
                backward: Optional[Callable] = None,
                n_outputs: Optional[int] = None):
    """Register a custom op. Usable as decorator or direct call.

    forward(*jax_values, **attrs) -> jax value(s)
    backward(res, *cotangents) -> input cotangents, given res = (inputs, outputs)
    """

    def _register(fwd: Callable):
        import jax

        def api(*tensors, **attrs):
            from .dispatch import primitive

            if backward is not None:
                # custom_vjp rejects **kwargs; close the attrs into a
                # positional-only wrapper built per call (trace-time only)
                @jax.custom_vjp
                def op_fn(*vals):
                    return fwd(*vals, **attrs)

                def op_fwd(*vals):
                    out = fwd(*vals, **attrs)
                    return out, (vals, out)

                def op_bwd(res, g):
                    return tuple(backward(res, g))

                op_fn.defvjp(op_fwd, op_bwd)
                impl = op_fn
            else:
                def impl(*vals):
                    return fwd(*vals, **attrs)

            return primitive(name, impl, list(tensors), n_outputs=n_outputs)

        CUSTOM_OPS[name] = {"forward": fwd, "backward": backward, "api": api}
        api.__name__ = name
        return api

    if forward is not None:
        return _register(forward)
    return _register


def run_custom_op(name: str, *tensors, **attrs):
    """Invoke a registered custom op by name (reference:
    _run_custom_op / custom op dispatch)."""
    if name not in CUSTOM_OPS:
        raise KeyError(f"custom op '{name}' is not registered")
    return CUSTOM_OPS[name]["api"](*tensors, **attrs)


def get_custom_op(name: str):
    return CUSTOM_OPS.get(name)
