"""paddle.geometric parity surface (reference: python/paddle/geometric/ —
message passing send_u_recv/send_ue_recv/send_uv, segment reductions,
sampling, reindex). All backed by jax segment ops (ops/sequence_ops.py) —
the TPU-friendly sorted-scatter path for graph aggregation.
"""
from __future__ import annotations

from ..ops.sequence_ops import (  # noqa: F401
    graph_khop_sampler,
    graph_sample_neighbors,
    reindex_graph,
    send_u_recv,
    send_ue_recv,
    send_uv,
    weighted_sample_neighbors,
)


def segment_sum(data, segment_ids, name=None):
    from ..ops.pooling import segment_pool

    return segment_pool(data, segment_ids, "SUM")


def segment_mean(data, segment_ids, name=None):
    from ..ops.pooling import segment_pool

    return segment_pool(data, segment_ids, "MEAN")


def segment_max(data, segment_ids, name=None):
    from ..ops.pooling import segment_pool

    return segment_pool(data, segment_ids, "MAX")


def segment_min(data, segment_ids, name=None):
    from ..ops.pooling import segment_pool

    return segment_pool(data, segment_ids, "MIN")


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """(reference paddle.geometric.sample_neighbors → (neighbors, count)
    or (neighbors, count, eids) with return_eids)."""
    return graph_sample_neighbors(row, colptr, input_nodes, eids=eids,
                                  sample_size=sample_size,
                                  return_eids=return_eids)


def reindex_heter_graph(x, neighbors, count, name=None):
    """Heterogeneous reindex: neighbors/count given per edge type."""
    outs = [reindex_graph(x, nb, ct) for nb, ct in zip(neighbors, count)]
    return outs


__all__ = [
    "send_u_recv", "send_ue_recv", "send_uv", "segment_sum", "segment_mean",
    "segment_max", "segment_min", "sample_neighbors", "reindex_graph",
    "reindex_heter_graph", "graph_khop_sampler", "weighted_sample_neighbors",
]
