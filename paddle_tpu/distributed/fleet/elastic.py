"""Elastic training manager (reference:
python/paddle/distributed/fleet/elastic/manager.py:125 — ElasticManager
registers nodes in etcd, heartbeats, watches the node set, and decides
HOLD/RESTART/EXIT on change; the launcher relaunches workers accordingly).

TPU-native: the registry rides the framework's own native TCPStore instead
of etcd (one fewer external service; the store already exists for
rendezvous). Each node owns a heartbeat key; `watch()` scans peers'
timestamps and reports scale-in (stale peer) or completion. The launch CLI's
--max_restarts covers single-node relaunch; multi-node orchestration reads
these statuses.
"""
from __future__ import annotations

import os
import threading
import time
from enum import Enum
from typing import Optional

from ...base.log import get_logger


class ElasticStatus(Enum):
    COMPLETED = 0
    ERROR = 1
    HOLD = 2
    RESTART = 3
    EXIT = 4


class ElasticJoinTimeout(TimeoutError):
    """The join barrier expired with ranks still missing. ``missing``
    names them — the caller (launcher / operator) learns WHICH nodes
    never registered instead of re-deriving it from a bare False."""

    def __init__(self, missing, joined: int, world_size: int,
                 timeout: float):
        self.missing = list(missing)
        self.joined = int(joined)
        self.world_size = int(world_size)
        super().__init__(
            f"elastic join barrier: {joined}/{world_size} nodes joined "
            f"within {timeout:.1f}s; missing ranks (no heartbeat): "
            f"{self.missing}")


class ElasticManager:
    def __init__(self, rank: Optional[int] = None, world_size: Optional[int] = None,
                 host: str = "127.0.0.1", port: int = 0, store=None,
                 heartbeat_interval: float = 1.0, node_timeout: float = 10.0,
                 job_id: str = "default"):
        from ...native import TCPStore

        self.rank = rank if rank is not None else int(os.environ.get("PADDLE_TRAINER_ID", 0))
        self.world_size = world_size if world_size is not None else int(
            os.environ.get("PADDLE_TRAINERS_NUM", 1))
        self.heartbeat_interval = heartbeat_interval
        self.node_timeout = node_timeout
        self.job_id = job_id
        if store is not None:
            self.store = store
        else:
            self.store = TCPStore(host, port, is_master=(self.rank == 0),
                                  world_size=self.world_size)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._completed_key = f"elastic/{job_id}/completed"

    # ------------------------------------------------------------ lifecycle
    def _hb_key(self, rank: int) -> str:
        return f"elastic/{self.job_id}/hb/{rank}"

    def start(self):
        """Register + start the heartbeat thread (reference manager.start)."""
        self._beat()
        self.store.add(f"elastic/{self.job_id}/joined", 1)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _beat(self):
        self.store.set(self._hb_key(self.rank), str(time.time()))

    def _loop(self):
        while not self._stop.is_set():
            try:
                self._beat()
            except Exception as e:
                get_logger().warning("elastic heartbeat failed: %s", e)
            self._stop.wait(self.heartbeat_interval)

    def wait_all_joined(self, timeout: float = 60.0,
                        raise_on_timeout: bool = True):
        """Barrier on node registration. On timeout the partial roster is
        caller-visible: :class:`ElasticJoinTimeout` names the ranks that
        never heartbeat (``raise_on_timeout=False`` restores the legacy
        bool and only logs them), and ``elastic.join_timeout`` ticks so
        the scrape side sees stalled bring-ups (ISSUE 14 satellite)."""
        deadline = time.time() + timeout
        joined = 0
        while time.time() < deadline:
            joined = int.from_bytes(self.store.get(f"elastic/{self.job_id}/joined")[:8],
                                    "little")
            if joined >= self.world_size:
                return True
            time.sleep(0.1)
        # name the missing ranks: a rank that registered has a heartbeat
        # key, so the gap set is exactly the never-joined set (one
        # survivors() sweep — its per-rank probe blocks up to 2s on an
        # absent key, so re-evaluating per rank would be O(world²) waits)
        live = set(self.survivors())
        missing = [r for r in range(self.world_size) if r not in live]
        try:
            from ...observability.metrics import registry

            registry.counter(
                "elastic.join_timeout",
                "elastic join barriers that expired with nodes missing "
                "(the exception names the absent ranks)").inc()
        except Exception:
            pass
        get_logger().error(
            "elastic join barrier timed out: %d/%d joined, missing ranks %s",
            joined, self.world_size, missing)
        if raise_on_timeout:
            raise ElasticJoinTimeout(missing, joined, self.world_size,
                                     timeout)
        return False

    # ---------------------------------------------------------------- watch
    def watch(self) -> ElasticStatus:
        """One scan of the node set (reference manager.watch loop body)."""
        if self._completed():
            return ElasticStatus.COMPLETED
        # hb keys only exist after registration; the store's GET blocks on
        # missing keys, so gate the scan on the join counter
        if self.store.add(f"elastic/{self.job_id}/joined", 0) < self.world_size:
            return ElasticStatus.HOLD
        now = time.time()
        stale = []
        for r in range(self.world_size):
            if r == self.rank:
                continue
            ts = float(self.store.get(self._hb_key(r)).decode())
            if now - ts > self.node_timeout:
                stale.append(r)
        if stale:
            get_logger().warning("elastic: stale nodes %s -> RESTART", stale)
            return ElasticStatus.RESTART
        return ElasticStatus.HOLD

    def survivors(self) -> list:
        """Ranks with fresh heartbeats, self included (the live node set the
        reference manager derives from etcd watch events). The per-rank GET
        uses a SHORT timeout: the store blocks on missing keys, and a rank
        that crashed before registering must read as dead in ~node_timeout,
        not stall the recovery path for the store's default 30 s each."""
        now = time.time()
        probe_timeout = min(self.node_timeout, 2.0)
        live = []
        for r in range(self.world_size):
            if r == self.rank:
                live.append(r)
                continue
            try:
                raw = self.store.get(self._hb_key(r), probe_timeout)
            except TypeError:  # store without a timeout parameter
                try:
                    raw = self.store.get(self._hb_key(r))
                except Exception:
                    continue
            except Exception:
                continue
            try:
                if now - float(raw.decode()) <= self.node_timeout:
                    live.append(r)
            except (ValueError, AttributeError):
                continue
        return live

    def replan(self, degrees=None, devices=None):
        """Scale-in/out re-plan (reference manager.py:125: the node set
        changed → compute the new world → relaunch under it). In the
        single-controller SPMD runtime this means: shrink world_size to the
        surviving node set, bump the job generation, and REBUILD the device
        mesh for the new world — the distributed checkpoint loader then
        reshards state onto the new topology on load (load-time reshard is
        structural, checkpoint/load_state_dict.py).

        degrees: optional mesh axis degrees for the new plan (defaults to
        pure dp over the surviving world); devices: optional explicit device
        list (defaults to a proportional slice of jax.devices()).
        """
        import jax

        from .. import env as env_mod

        live = self.survivors()
        old_world, new_world = self.world_size, len(live)
        self.world_size = new_world
        self.store.add(f"elastic/{self.job_id}/generation", 1)
        if devices is None:
            all_dev = list(jax.devices())
            per_node = max(len(all_dev) // max(old_world, 1), 1)
            devices = all_dev[: per_node * new_world] or all_dev[:1]
        env = env_mod.instance()
        degrees = dict(degrees or {})
        for ax in env_mod.HYBRID_AXES:
            degrees.setdefault(ax, -1 if ax == "dp" else 1)
        mesh = env.build_mesh(degrees, devices=devices)
        get_logger().warning(
            "elastic replan: world %d -> %d, mesh %s", old_world, new_world,
            dict(mesh.shape))
        return mesh

    def _completed(self) -> bool:
        try:
            # add(0) is an atomic read-or-create: unlike get, it never blocks
            # on a missing key
            done = self.store.add(self._completed_key + "/count", 0)
            return done >= self.world_size
        except Exception:
            return False

    def mark_completed(self):
        self.store.add(self._completed_key + "/count", 1)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def exit(self, completed=True):
        if completed:
            self.mark_completed()
        self.stop()
