"""Megatron-style sequence parallelism.

Reference: fleet/utils/sequence_parallel_utils.py — ScatterOp:85 / GatherOp:97
/ AllGatherOp:111 / ReduceScatterOp:127 autograd ops, and Column/Row
SequenceParallelLinear (:429/:564) that allgather activations forward and
reduce-scatter backward over the mp group.

TPU-native: "sequence parallel" means the activation's sequence dim is
sharded over the mp axis in the norm/dropout regions and the feature dim is
sharded inside the TP matmul pair. Each reference op is a sharding
constraint; GSPMD emits exactly the allgather/reduce-scatter pair (and can
overlap it with the matmuls, which the reference needed a hand-written
SPInnerOverlapLinear for).
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.dispatch import primitive
from ...core.tensor import Tensor
from ...nn.layer.layers import Layer
from .. import env as env_mod
from .mpu import ColumnParallelLinear, RowParallelLinear, _constrain, _batch_spec, _feature_spec

_SP_AXIS = "mp"  # Megatron-SP rides the mp axis; SEP has its own axis ("sep")


def _seq_spec(ndim: int, seq_dim: int = 1, axis=_SP_AXIS):
    entries = [None] * ndim
    entries[0] = "dp"
    entries[seq_dim] = axis
    return P(*entries)


def mark_as_sequence_parallel(x: Tensor, seq_dim: int = 1, axis=_SP_AXIS) -> Tensor:
    """Constrain x sequence-sharded (the ScatterOp analog)."""
    return _constrain(x, _seq_spec(x.ndim, seq_dim, axis))


class ScatterOp:
    """reference :85 — split sequence over the group. Static apply() surface."""

    @staticmethod
    def apply(x, seq_dim=1):
        return mark_as_sequence_parallel(x, seq_dim)


class GatherOp:
    """reference :97 — gather the sequence dim back to full."""

    @staticmethod
    def apply(x, seq_dim=1):
        return _constrain(x, _batch_spec(x.ndim))


class AllGatherOp:
    """reference :111 — allgather fwd / reduce-scatter bwd: the fwd boundary
    into a TP block."""

    @staticmethod
    def apply(x):
        return _constrain(x, _batch_spec(x.ndim))


class ReduceScatterOp:
    """reference :127 — reduce-scatter fwd / allgather bwd: the boundary out
    of a TP block back to sequence-sharded."""

    @staticmethod
    def apply(x, seq_dim=1):
        return mark_as_sequence_parallel(x, seq_dim)


def scatter(x, seq_dim=1):
    return ScatterOp.apply(x, seq_dim)


def all_gather(x):
    return AllGatherOp.apply(x)


def reduce_scatter(x, seq_dim=1):
    return ReduceScatterOp.apply(x, seq_dim)


class ColumnSequenceParallelLinear(ColumnParallelLinear):
    """reference :429 — column TP linear whose input arrives sequence-sharded."""

    def forward(self, x):
        x = AllGatherOp.apply(x)
        return super().forward(x)


class RowSequenceParallelLinear(RowParallelLinear):
    """reference :564 — row TP linear whose output leaves sequence-sharded."""

    def forward(self, x):
        out = super().forward(x)
        return ReduceScatterOp.apply(out)


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1, fuse=False):
    """reference :192 syncs LayerNorm params across mp ranks. Replicated
    NamedSharding layouts make those grads structurally synchronized; no hook
    is needed — kept for API parity."""
    return model
