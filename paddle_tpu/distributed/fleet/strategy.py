"""DistributedStrategy — layered config for hybrid parallel training.

Reference: fleet/base/distributed_strategy.py:284 (protobuf-backed, dozens of
toggles). Rebuild keeps the widely-used surface as plain python state; the
sections mirror the reference's field groups (amp / recompute / sharding /
hybrid_configs / gradient_merge / ...).
"""
from __future__ import annotations


class _Section(dict):
    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError as e:
            raise AttributeError(k) from e

    def __setattr__(self, k, v):
        self[k] = v


class DistributedStrategy:
    def __init__(self):
        self.amp = False
        self.amp_configs = _Section(
            init_loss_scaling=65536.0,
            use_dynamic_loss_scaling=True,
            custom_white_list=[],
            custom_black_list=[],
            use_pure_fp16=False,
            use_bf16=True,
        )
        self.recompute = False
        self.recompute_configs = _Section(checkpoints=[])
        self.sharding = False
        self.sharding_configs = _Section(stage=1, degree=1, offload=False)
        self.hybrid_configs = _Section(
            dp_degree=-1,
            mp_degree=1,
            pp_degree=1,
            sharding_degree=1,
            sep_degree=1,
            pp_configs=_Section(micro_batch_size=1, accumulate_steps=1),
        )
        self.gradient_merge = False
        self.gradient_merge_configs = _Section(k_steps=1, avg=True)
        self.lamb = False
        self.lars = False
        self.dgc = False
        self.localsgd = False
        self.gradient_scale_configs = _Section(scale_strategy="avg")
        self.pipeline = False
        self.pipeline_configs = _Section(accumulate_steps=1, micro_batch_size=1, schedule_mode="1F1B")
        self.without_graph_optimization = False
        self.fuse_all_reduce_ops = True  # XLA fuses; kept for surface compat
        self.find_unused_parameters = False
        self.tensor_parallel = False
        self.tensor_parallel_configs = _Section(tensor_parallel_degree=1)

    def to_degrees(self):
        """hybrid_configs -> mesh axis degrees (env.HYBRID_AXES)."""
        hc = self.hybrid_configs
        return {
            "dp": hc.get("dp_degree", -1),
            "mp": hc.get("mp_degree", 1),
            "pp": hc.get("pp_degree", 1),
            "sharding": hc.get("sharding_degree", 1),
            "sep": hc.get("sep_degree", 1),
        }

    def __setattr__(self, k, v):
        if k.endswith("_configs") and isinstance(v, dict) and not isinstance(v, _Section):
            base = getattr(self, k, _Section())
            merged = _Section(base)
            for kk, vv in v.items():
                merged[kk] = _Section(vv) if isinstance(vv, dict) and isinstance(base.get(kk), dict) else vv
            object.__setattr__(self, k, merged)
        else:
            object.__setattr__(self, k, v)
