"""fleet — hybrid-parallel orchestration.

Reference: python/paddle/distributed/fleet/ — fleet.init (fleet.py:218),
distributed_model (model.py:32), distributed_optimizer (fleet.py:1427),
HybridCommunicateGroup (base/topology.py:189), DistributedStrategy.

TPU-native: fleet.init builds the 5-axis global mesh from
strategy.hybrid_configs; distributed_model/optimizer attach sharding layouts
instead of wrapping with reducer/pipeline runtimes — GSPMD + the whole-step
jit do the communication scheduling.
"""
from __future__ import annotations

import os
from typing import Optional

from .. import env as env_mod
from ..parallel import DataParallel
from .strategy import DistributedStrategy
from .topology import (
    HybridCommunicateGroup,
    ParallelMode,
    get_hybrid_communicate_group,
    set_hybrid_communicate_group,
)
from . import mpu  # noqa: F401
from . import sequence_parallel  # noqa: F401
from .mpu import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
    get_rng_state_tracker,
)
from .context_parallel import ring_attention, ulysses_attention  # noqa: F401
from .recompute import no_recompute, recompute, recompute_sequential  # noqa: F401
from .pipeline import LayerDesc, PipelineLayer, SharedLayerDesc  # noqa: F401
from .pipeline_schedules import (  # noqa: F401
    PipelinedStack,
    forward_backward_pipeline_1f1b,
    forward_backward_pipeline_eager_1f1b,
    forward_backward_pipeline_interleave,
    forward_backward_pipeline_rotation,
    forward_backward_pipeline_zero_bubble,
    schedule_cost_report,
)

meta_parallel = mpu  # submodule alias: fleet.meta_parallel.* layer surface


class _Fleet:
    """The fleet singleton surface (reference fleet/base/fleet_base)."""

    def __init__(self):
        self._strategy: Optional[DistributedStrategy] = None
        self._is_initialized = False

    def init(self, role_maker=None, is_collective=True, strategy: Optional[DistributedStrategy] = None, log_level="INFO"):
        self._strategy = strategy or DistributedStrategy()
        degrees = self._strategy.to_degrees()
        env_mod.init_parallel_env(degrees)
        hcg = HybridCommunicateGroup(degrees)
        set_hybrid_communicate_group(hcg)
        self._is_initialized = True
        return self

    def is_first_worker(self):
        return env_mod.get_rank() == 0

    def worker_index(self):
        return env_mod.get_rank()

    def worker_num(self):
        return env_mod.get_world_size()

    def is_worker(self):
        return True

    def worker_endpoints(self, to_string=False):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
        return ",".join(eps) if to_string else eps

    def barrier_worker(self):
        env_mod.barrier()

    @property
    def _hcg(self):
        return get_hybrid_communicate_group()

    def get_hybrid_communicate_group(self):
        return self._hcg

    def distributed_model(self, model):
        """reference fleet/model.py:32 — pick the parallel wrapper. TP/SP/PP
        layers already carry their shardings; pure-DP gets the DataParallel
        input-sharding wrapper."""
        hcg = self._hcg
        if hcg is None:
            self.init()
            hcg = self._hcg
        mode = hcg.get_parallel_mode()
        if mode == ParallelMode.DATA_PARALLEL and hcg.get_data_parallel_world_size() > 1:
            return DataParallel(model)
        from ..parallel import replicate_layer

        # hybrid: parameters without explicit placements become replicated
        replicate_layer(model, hcg.mesh)
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        """reference fleet.py:1427 -> HybridParallelOptimizer. Sharding-stage
        configs shard the optimizer states over dp/sharding axes."""
        st = strategy or self._strategy or DistributedStrategy()
        if st.sharding or st.hybrid_configs.get("sharding_degree", 1) > 1 or (
            env_mod.instance().axis_degrees.get("sharding", 1) > 1
        ):
            from ..auto_parallel.api import (
                ShardingStage1,
                ShardingStage2,
                ShardingStage3,
                shard_optimizer,
            )

            stage = {1: ShardingStage1, 2: ShardingStage2, 3: ShardingStage3}[
                int(st.sharding_configs.get("stage", 1)) if st.sharding else 1
            ]
            axis = "sharding" if env_mod.instance().axis_degrees.get("sharding", 1) > 1 else "dp"
            shard_optimizer(optimizer, stage(axis))
        return optimizer

    # utility surface
    def set_log_level(self, level):
        from ...base.log import get_logger

        get_logger().setLevel(level)


fleet = _Fleet()
init = fleet.init
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
worker_index = fleet.worker_index
worker_num = fleet.worker_num
is_first_worker = fleet.is_first_worker
get_hybrid_communicate_group = get_hybrid_communicate_group  # noqa: PLW0127
barrier_worker = fleet.barrier_worker

__all__ = [
    "fleet",
    "init",
    "DistributedStrategy",
    "HybridCommunicateGroup",
    "ParallelMode",
    "distributed_model",
    "distributed_optimizer",
    "get_hybrid_communicate_group",
    "ColumnParallelLinear",
    "RowParallelLinear",
    "VocabParallelEmbedding",
    "ParallelCrossEntropy",
    "get_rng_state_tracker",
    "recompute",
    "PipelineLayer",
    "LayerDesc",
]
