"""Context parallelism: ring attention + Ulysses (DeepSpeed-style) all_to_all.

The reference snapshot has NO ring/Ulysses/blockwise CP (SURVEY.md §2.14 —
long sequences are handled by the SEP hybrid axis + Megatron-SP +
flashmask). This module is the TPU-idiomatic superset: the sequence is a
mesh axis (`sep`), and

- `ring_attention` runs blockwise attention with online-softmax
  accumulation while K/V blocks rotate around the ring via `ppermute`
  (one ICI hop per step, compute/comm overlapped by XLA's latency-hiding
  scheduler inside the shard_map body);
- `ulysses_attention` trades sequence sharding for head sharding with two
  `all_to_all`s and runs a fully-local attention in between (cheaper when
  num_heads >= sep degree and sequence fits per-device HBM after the swap).

Both are differentiable (ppermute/all_to_all have transpose rules; the ring
loop is rematerialized per step so backward recomputes block scores instead
of storing them — the Blockwise/RingAttention memory recipe).

Layout is [batch, seq, heads, head_dim] throughout (TPU-friendly, matching
nn.functional.flash_attention).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...base import jax_compat
from ...core.dispatch import primitive
from .. import env as env_mod

_NEG = -1e30


def _ring_body(q, k, v, *, axis: str, n: int, causal: bool, scale: float):
    """shard_map body: q,k,v are the local [B, S/n, H, D] blocks."""
    idx = jax.lax.axis_index(axis)
    chunk = q.shape[1]
    q_pos = idx * chunk + jnp.arange(chunk)  # global positions of local queries

    qf = q.astype(jnp.float32) * scale
    acc = jnp.zeros(q.shape, jnp.float32)
    m = jnp.full(q.shape[:3], _NEG, jnp.float32)  # [B, Sq, H] running max
    l = jnp.zeros(q.shape[:3], jnp.float32)  # running denom
    perm = [(i, (i + 1) % n) for i in range(n)]

    @functools.partial(jax.checkpoint, policy=jax.checkpoint_policies.nothing_saveable)
    def step(t, carry_kv, acc, m, l):
        k_t, v_t = carry_kv
        # device idx holds K/V block (idx - t) mod n at step t
        j = (idx - t) % n
        k_pos = j * chunk + jnp.arange(chunk)
        s = jnp.einsum("bqhd,bkhd->bqhk", qf, k_t.astype(jnp.float32))
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]  # [Sq, Sk]
            s = jnp.where(mask[None, :, None, :], s, _NEG)
        s_max = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, s_max)
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "bqhk,bkhd->bqhd", p, v_t.astype(jnp.float32)
        )
        return acc_new, m_new, l_new

    k_t, v_t = k, v
    for t in range(n):
        acc, m, l = step(t, (k_t, v_t), acc, m, l)
        if t + 1 < n:
            k_t = jax.lax.ppermute(k_t, axis, perm)
            v_t = jax.lax.ppermute(v_t, axis, perm)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def _ulysses_body(q, k, v, *, axis: str, causal: bool, scale: float, dropout: float):
    """shard_map body: seq-sharded -> all_to_all -> head-sharded local attn."""
    from ...nn.functional.attention import _xla_attention

    swap = functools.partial(jax.lax.all_to_all, axis_name=axis, tiled=True)
    qh = swap(q, split_axis=2, concat_axis=1)  # [B, S, H/n, D]
    kh = swap(k, split_axis=2, concat_axis=1)
    vh = swap(v, split_axis=2, concat_axis=1)
    out = _xla_attention(qh, kh, vh, causal=causal, scale=scale, dropout=dropout)
    return swap(out, split_axis=1, concat_axis=2)  # back to [B, S/n, H, D]


def _cp_call(body_builder, q, k, v, axis: str, extra_check=None):
    mesh = env_mod.get_mesh()
    n = mesh.shape.get(axis, 1)
    qv = q._value if hasattr(q, "_value") else q
    if n > 1 and qv.shape[1] % n != 0:
        raise ValueError(f"sequence length {qv.shape[1]} not divisible by {axis}={n}")
    if extra_check:
        extra_check(n, qv)

    def fn(qq, kk, vv):
        if n == 1:  # degenerate mesh: plain attention
            from ...nn.functional.attention import _xla_attention

            scale = 1.0 / math.sqrt(qq.shape[-1])
            return _xla_attention(qq, kk, vv, causal=body_builder.keywords["causal"], scale=scale)
        # Nested-manual support (pp pipeline shard_map around a cp block):
        # when tracing inside an enclosing shard_map, the inner shard_map
        # must be built on the CONTEXT's abstract mesh, and axes the outer
        # region already made Manual (pp, dp) must not appear in the specs —
        # the operands are already per-shard along them.
        from .mpu import _manual_axes

        manual = _manual_axes()
        use_mesh = jax_compat.get_abstract_mesh() if manual else mesh
        dp = mesh.shape.get("dp", 1)
        batch_axis = ("dp" if (dp > 1 and qv.shape[0] % dp == 0
                               and "dp" not in manual) else None)
        spec = P(batch_axis, axis, None, None)
        shmap = jax_compat.shard_map(
            body_builder,
            mesh=use_mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )
        if not isinstance(qq, jax.core.Tracer):
            sh = NamedSharding(mesh, spec)
            qq, kk, vv = (jax.device_put(x, sh) for x in (qq, kk, vv))
        return shmap(qq, kk, vv)

    return primitive("context_parallel_attention", fn, [q, k, v])


def ring_attention(q, k, v, causal=True, axis="sep"):
    """Ring attention over the ``axis`` mesh dimension.

    q/k/v: [B, S, H, D] with S sharded over ``axis``. Returns [B, S, H, D]
    sharded the same way. Exact (not approximate): computes full attention
    blockwise with online softmax.
    """
    qv = q._value if hasattr(q, "_value") else q
    scale = 1.0 / math.sqrt(qv.shape[-1])
    mesh = env_mod.get_mesh()
    n = mesh.shape.get(axis, 1)
    body = functools.partial(_ring_body, axis=axis, n=n, causal=causal, scale=scale)
    return _cp_call(body, q, k, v, axis)


def ulysses_attention(q, k, v, causal=True, axis="sep", dropout=0.0):
    """Ulysses/all-to-all sequence parallelism: swap seq<->head sharding,
    attend locally, swap back. Requires num_heads % axis degree == 0."""
    qv = q._value if hasattr(q, "_value") else q
    scale = 1.0 / math.sqrt(qv.shape[-1])

    def check(n, val):
        if n > 1 and val.shape[2] % n != 0:
            raise ValueError(f"num_heads {val.shape[2]} not divisible by {axis}={n}")

    body = functools.partial(_ulysses_body, axis=axis, causal=causal, scale=scale, dropout=dropout)
    return _cp_call(body, q, k, v, axis, extra_check=check)
