"""Tensor-parallel (model-parallel) building blocks.

Reference: fleet/layers/mpu/mp_layers.py — VocabParallelEmbedding (:49),
ColumnParallelLinear (:336), RowParallelLinear (:543), ParallelCrossEntropy
(:744) built from c_identity/c_split/mp_allreduce autograd ops (mpu/mp_ops.py)
over NCCL; RNGStatesTracker (mpu/random.py:34) keeps per-rank dropout seeds.

TPU-native: a TP layer is an ordinary layer whose weight carries a
NamedSharding over the `mp` mesh axis. The forward is a plain matmul/gather;
GSPMD partitions it and inserts the identity/allreduce/allgather movements the
reference hand-codes — and under whole-step jit it fuses and overlaps them.
`gather_output=False` is expressed as a sharding constraint on the output
(kept sharded on the feature dim), so chained Column->Row pairs run without
any intermediate gather, exactly like Megatron.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...base import jax_compat
from ...core.dispatch import primitive
from ...core.tensor import Tensor
from ...nn import functional as F
from ...nn.initializer import Constant, Normal, XavierNormal
from ...nn.layer.layers import Layer
from .. import env as env_mod

_MP_AXIS = "mp"


def _mesh():
    return env_mod.get_mesh()


def _place(param: Tensor, spec: P):
    """Pin a parameter's layout on the global mesh."""
    mesh = _mesh()
    param._replace_value(jax.device_put(param._value, NamedSharding(mesh, spec)))
    param._placements = spec
    return param


def _sanitize_spec(spec: P, shape, mesh) -> P:
    """Drop axis entries whose size does not divide the dim (XLA requires
    even shards for explicit layouts)."""
    entries = []
    for d, entry in enumerate(spec):
        if entry is None:
            entries.append(None)
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        n = 1
        for ax in axes:
            n *= mesh.shape.get(ax, 1)
        entries.append(entry if (d < len(shape) and n > 0 and shape[d] % n == 0) else None)
    return P(*entries)


def _manual_axes() -> frozenset:
    """Axes the enclosing shard_map (if any) already made Manual — a
    sharding constraint inside that region must not mention them (the
    operand is already per-shard along them)."""
    ctx = jax_compat.get_abstract_mesh()
    if getattr(ctx, "axis_names", None):
        from jax.sharding import AxisType

        return frozenset(n for n, t in zip(ctx.axis_names, ctx.axis_types)
                         if t == AxisType.Manual)
    return frozenset()


def _strip_manual(spec: P, manual: frozenset) -> P:
    entries = []
    for entry in spec:
        if entry is None:
            entries.append(None)
            continue
        axes = tuple(a for a in (entry if isinstance(entry, tuple) else (entry,))
                     if a not in manual)
        entries.append(axes if len(axes) > 1 else (axes[0] if axes else None))
    return P(*entries)


def _constrain(x: Tensor, spec: P) -> Tensor:
    """Sharding constraint on an activation (the c_identity/c_split analog)."""
    mesh = _mesh()
    if mesh.shape.get(_MP_AXIS, 1) == 1:
        return x
    manual = _manual_axes()
    if manual:
        spec = _strip_manual(spec, manual)
    spec = _sanitize_spec(spec, x.shape, mesh)
    sharding = NamedSharding(mesh, spec)
    if isinstance(x._value, jax.core.Tracer):
        out = primitive("sharding_constraint", lambda v: jax.lax.with_sharding_constraint(v, sharding), [x])
    else:
        out = primitive("sharding_constraint", lambda v: jax.device_put(v, sharding), [x])
    out.stop_gradient = x.stop_gradient
    return out


def _feature_spec(ndim: int, axis=_MP_AXIS):
    """last-dim sharded activation spec; batch dim rides dp."""
    entries = [None] * ndim
    entries[0] = "dp"
    entries[-1] = axis
    return P(*entries)


def _batch_spec(ndim: int):
    entries = [None] * ndim
    entries[0] = "dp"
    return P(*entries)


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over mp (reference mp_layers.py:49).

    The reference masks out-of-range ids per rank and allreduces partial
    lookups; GSPMD derives the same exchange from the [vocab/mp, hidden]
    weight layout.
    """

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr, default_initializer=Normal(0.0, 0.02)
        )
        _place(self.weight, P(_MP_AXIS, None))

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return _constrain(out, _batch_spec(out.ndim))

    def extra_repr(self):
        return f"{self.num_embeddings}, {self.embedding_dim}, vocab-sharded over '{_MP_AXIS}'"


class ColumnParallelLinear(Layer):
    """Linear with out_features sharded over mp (reference mp_layers.py:336)."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=None,
                 gather_output=True, fuse_matmul_bias=False, mp_group=None, name=None, bias_attr=None):
        super().__init__()
        self.in_features, self.out_features = in_features, out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr, default_initializer=XavierNormal()
        )
        _place(self.weight, P(None, _MP_AXIS))
        use_bias = has_bias if has_bias is not None else (bias_attr is not False)
        if use_bias:
            self.bias = self.create_parameter([out_features], attr=bias_attr, is_bias=True)
            _place(self.bias, P(_MP_AXIS))
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            return _constrain(out, _batch_spec(out.ndim))
        return _constrain(out, _feature_spec(out.ndim))

    def extra_repr(self):
        return f"in={self.in_features}, out={self.out_features} (column-sharded), gather_output={self.gather_output}"


class RowParallelLinear(Layer):
    """Linear with in_features sharded over mp (reference mp_layers.py:543).

    Consumes the feature-sharded activations a ColumnParallelLinear(
    gather_output=False) produces; the partial-sum allreduce the reference
    issues (mp_allreduce) is the psum GSPMD inserts for the contracted
    sharded dim.
    """

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None, name=None, bias_attr=None):
        super().__init__()
        self.in_features, self.out_features = in_features, out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr, default_initializer=XavierNormal()
        )
        _place(self.weight, P(_MP_AXIS, None))
        use_bias = has_bias if has_bias is not None else (bias_attr is not False)
        if use_bias:
            self.bias = self.create_parameter([out_features], attr=bias_attr, is_bias=True)
            _place(self.bias, P())
        else:
            self.bias = None

    def forward(self, x):
        if self.input_is_parallel:
            x = _constrain(x, _feature_spec(x.ndim))
        out = F.linear(x, self.weight, self.bias)
        return _constrain(out, _batch_spec(out.ndim))

    def extra_repr(self):
        return f"in={self.in_features} (row-sharded), out={self.out_features}"


class ParallelCrossEntropy(Layer):
    """Softmax cross entropy over class-sharded logits (reference
    mp_layers.py:744). The reference's two-pass max/sum allreduce is exactly
    what GSPMD emits for reductions over the sharded class dim."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        logits = _constrain(input, _feature_spec(input.ndim))
        return F.cross_entropy(logits, label, reduction="none", ignore_index=self.ignore_index)


# ----------------------------------------------------------------- RNG tracker
class RNGStatesTracker:
    """Per-scope RNG streams (reference mpu/random.py:34).

    The reference seeds each mp rank differently so dropout masks differ on
    sharded activations. Single-controller SPMD generates ONE global mask that
    is itself sharded, so cross-rank consistency is structural; the tracker
    keeps named independent streams for API parity (model_parallel_rng vs
    global seed scopes).
    """

    def __init__(self):
        self._cells = {}  # name -> Tensor holding a PRNG key (a state cell)

    def add(self, name, seed):
        import jax.random as jrandom

        if name in self._cells:
            raise ValueError(f"rng state {name} already exists")
        self._cells[name] = Tensor(jrandom.PRNGKey(seed), name=f"rng_{name}")

    def get_states_tracker(self):
        return {k: v._value for k, v in self._cells.items()}

    def set_states_tracker(self, states):
        for k, v in states.items():
            if k in self._cells:
                self._cells[k]._replace_value(v)
            else:
                self._cells[k] = Tensor(v, name=f"rng_{k}")

    def rng_state(self, name="model_parallel_rng"):
        import contextlib

        from ...base import global_state

        @contextlib.contextmanager
        def guard():
            if name not in self._cells:
                self.add(name, 2718 + len(self._cells))
            # swap the cell OBJECT: trace-safe (the stream cell becomes a
            # captured state cell under jit; no concrete keys enter traces)
            prev = global_state.swap_rng_cell(self._cells[name])
            try:
                yield
            finally:
                global_state.swap_rng_cell(prev)

        return guard()


_tracker = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _tracker


def model_parallel_random_seed(seed=None):
    global _tracker
    _tracker = RNGStatesTracker()
    _tracker.add("model_parallel_rng", seed or 2718)
