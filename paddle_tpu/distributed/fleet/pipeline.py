"""Pipeline parallelism: model surgery + schedules.

Reference: fleet/meta_parallel/parallel_layers/pp_layers.py — LayerDesc (:57),
SharedLayerDesc (:77), SegmentLayers (:93), PipelineLayer (:258); runtime
schedules in fleet/meta_parallel/pipeline_parallel.py (1F1B :575, interleave
:1174) over P2pHelper batched isend/irecv.

TPU-native design: a pipeline stage is a *mesh-axis placement*, not a process.
PipelineLayer segments the layer list and pins each segment's parameters to
its stage's slice of the `pp` axis (NamedSharding over a stage-indexed
dimension when weights stack homogeneously, or per-stage device_put
otherwise). The schedule below runs the microbatch loop at the python level:
losses/grads accumulate across microbatches inside one compiled step, giving
1F1B's arithmetic for heterogeneous stage graphs. For homogeneous stacks the
REAL stage-parallel schedules (SPMD rotation 1F1B + interleaved VPP over
shard_map + ppermute) live in
paddle_tpu.distributed.fleet.pipeline_schedules.PipelinedStack — models
embed it directly (e.g. GPTConfig.pipeline_parallel=True).
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Union

import numpy as np

from ...core.tensor import Tensor
from ...nn.layer.layers import Layer


class LayerDesc:
    """Deferred layer construction (reference pp_layers.py:57) so only the
    owning stage would materialize it in multi-controller mode."""

    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self) -> Layer:
        return self.layer_cls(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    """Weight-tied layer shared between stages (reference :77) — embedding/
    lm-head tying across first/last stage."""

    def __init__(self, key, layer_cls, *inputs, forward_func=None, shared_weight_attr="weight", **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Split N layers into num_parts segments (reference :93): 'uniform' or
    'layer' (param-count balanced)."""

    def __init__(self, layers, num_parts, method="uniform"):
        self.layers = layers
        self.num_parts = num_parts
        self.method = method

    def do_segment(self) -> List[int]:
        n = len(self.layers)
        if self.method == "uniform" or not self.method.startswith("param"):
            base = n // self.num_parts
            rem = n % self.num_parts
            bounds = [0]
            for i in range(self.num_parts):
                bounds.append(bounds[-1] + base + (1 if i < rem else 0))
            return bounds
        weights = []
        for l in self.layers:
            if isinstance(l, LayerDesc):
                weights.append(1)
            elif isinstance(l, Layer):
                weights.append(max(1, sum(int(np.prod(p.shape)) for p in l.parameters())))
            else:
                weights.append(1)
        total = sum(weights)
        target = total / self.num_parts
        bounds, acc = [0], 0
        for i, w in enumerate(weights):
            acc += w
            if acc >= target * len(bounds) and len(bounds) < self.num_parts:
                bounds.append(i + 1)
        while len(bounds) < self.num_parts + 1:
            bounds.append(len(weights))
        return bounds


class PipelineLayer(Layer):
    """Segmented model (reference pp_layers.py:258).

    In single-controller SPMD every stage's weights live on its pp-axis slice;
    the forward composes all segments (a full-graph program). The runtime
    schedule (PipelineParallel.train_batch) microbatches it.
    """

    def __init__(
        self,
        layers: Sequence[Union[Layer, LayerDesc, Callable]],
        num_stages: Optional[int] = None,
        topology=None,
        loss_fn=None,
        seg_method="uniform",
        recompute_interval=0,
        num_virtual_pipeline_stages: int = 1,
        num_microbatches: Optional[int] = None,
        **kwargs,
    ):
        super().__init__()
        from .. import env as env_mod

        self._loss_fn = loss_fn
        self._recompute_interval = recompute_interval
        degrees = env_mod.instance().axis_degrees or {}
        self._num_stages = num_stages or max(degrees.get("pp", 1), 1)
        descs = list(layers)
        self._segment_bounds = SegmentLayers(descs, self._num_stages, seg_method).do_segment()
        self._shared_layers = {}

        # Heterogeneous-stage schedule routing: the longest homogeneous run
        # of one LayerDesc class (the decoder trunk) runs under the SPMD
        # rotation schedule (PipelinedStack — real stage parallelism); the
        # pre/post edge segments (embedding / final LN / LM head, reference
        # first/last-stage placement) execute outside the rotation with their
        # params sharded over the pp axis (memory parity with placement).
        self._stack = None
        self._stack_range = (0, 0)
        mesh = env_mod.get_mesh()
        mesh_pp = mesh.shape.get("pp", 1) if mesh is not None else 1
        # the rotation schedule runs over the mesh's pp axis — route through
        # it only when that axis really carries num_stages devices; otherwise
        # keep the full-graph composition (stage placement by sharding only)
        if self._num_stages > 1 and mesh_pp == self._num_stages:
            def _same_desc(a, b):
                # identical constructor signature, not just the class: the
                # stack rebuilds every trunk layer from one desc
                return (isinstance(a, LayerDesc) and isinstance(b, LayerDesc)
                        and not isinstance(a, SharedLayerDesc)
                        and not isinstance(b, SharedLayerDesc)
                        and a.layer_cls is b.layer_cls
                        and a.inputs == b.inputs and a.kwargs == b.kwargs)

            lo_best = hi_best = 0
            lo = 0
            while lo < len(descs):
                hi = lo
                while hi < len(descs) and _same_desc(descs[hi], descs[lo]):
                    hi += 1
                if hi - lo > hi_best - lo_best:
                    lo_best, hi_best = lo, hi
                lo = max(hi, lo + 1)
            per = self._num_stages * max(num_virtual_pipeline_stages, 1)
            n_mid = (hi_best - lo_best) - (hi_best - lo_best) % per
            if n_mid >= per:
                hi_best = lo_best + n_mid
                from .pipeline_schedules import PipelinedStack

                mid = descs[lo_best]
                self._stack = PipelinedStack(
                    lambda: mid.build_layer(), n_mid,
                    num_stages=self._num_stages,
                    num_chunks=max(num_virtual_pipeline_stages, 1),
                    num_microbatches=num_microbatches)
                self._stack_range = (lo_best, hi_best)

        built: List = []
        slo, shi = self._stack_range
        for pos, item in enumerate(descs):
            if self._stack is not None and slo <= pos < shi:
                continue  # lives inside the rotation stack
            if isinstance(item, SharedLayerDesc):
                if item.layer_name in self._shared_layers:
                    src = self._shared_layers[item.layer_name]
                    built.append(_SharedForward(src, item.forward_func))
                else:
                    layer = item.build_layer()
                    self._shared_layers[item.layer_name] = layer
                    built.append(layer)
            elif isinstance(item, LayerDesc):
                built.append(item.build_layer())
            else:
                built.append(item)
            if self._stack is not None and pos == slo - 1:
                built.append(self._stack)
        if self._stack is not None and slo == 0:
            built.insert(0, self._stack)
        from ...nn.layer.container import LayerList

        self.run_function = LayerList([l for l in built if isinstance(l, Layer)])
        self._funcs = built
        self._place_stages()

    def _place_stages(self):
        """Pin each segment's params to its pp-stage slice of the mesh."""
        from .. import env as env_mod

        mesh = env_mod.get_mesh()
        if mesh is None or mesh.shape.get("pp", 1) <= 1:
            return
        import jax
        # stage-pinned placement: single-mesh GSPMD keeps arrays global; we
        # shard each stage's largest weight dim over pp when divisible so the
        # memory footprint splits across stage devices.
        from .. import env as _env

        from .pipeline_schedules import PipelinedStack

        for l in self._funcs:
            if not isinstance(l, Layer) or isinstance(l, PipelinedStack):
                continue  # the stack's params are already pp-sharded (stacked dim)
            for p in l.parameters():
                if p._placements is None:
                    p._replace_value(_env.shard_largest_dim(p._value, mesh, "pp"))

    def get_stage_from_index(self, idx) -> int:
        for si in range(self._num_stages):
            if self._segment_bounds[si] <= idx < self._segment_bounds[si + 1]:
                return si
        return self._num_stages - 1

    @property
    def parameters_in_stage(self):
        return self._segment_bounds

    def forward(self, x):
        out = x
        for i, fn in enumerate(self._funcs):
            if self._recompute_interval and isinstance(fn, Layer) and i % self._recompute_interval == 0:
                from .recompute import recompute

                out = recompute(fn, out)
            elif isinstance(fn, Layer) or callable(fn):
                out = fn(out)
        return out


class _SharedForward(Layer):
    def __init__(self, src_layer, forward_func):
        super().__init__()
        self._src = [src_layer]  # not a sublayer: weights owned by src stage
        self._forward_func = forward_func

    def forward(self, x):
        src = self._src[0]
        if self._forward_func is not None:
            return self._forward_func(src, x)
        return src(x)


class PipelineParallel(Layer):
    """Schedule runtime (reference pipeline_parallel.py:255).

    train_batch(batch, optimizer, lr_scheduler) microbatches the global batch
    (1F1B arithmetic: per-microbatch forward+backward, accumulated grads, one
    optimizer step)."""

    def __init__(self, layers: PipelineLayer, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._strategy = strategy
        cfg = getattr(strategy, "pipeline_configs", None) if strategy else None
        self._accumulate_steps = int(cfg.get("accumulate_steps", 1)) if cfg else 1

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        from ...ops import manipulation

        x, y = data
        steps = max(self._accumulate_steps, 1)
        micro_x = manipulation.split(x, steps, 0) if steps > 1 else [x]
        micro_y = manipulation.split(y, steps, 0) if steps > 1 else [y]
        total = None
        for mx, my in zip(micro_x, micro_y):
            out = self._layers(mx)
            loss = self._layers._loss_fn(out, my)
            if scaler is not None:
                scaled = scaler.scale(loss / steps)
                scaled.backward()
            else:
                (loss / steps).backward()
            total = loss if total is None else total + loss
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total / steps

    def eval_batch(self, data, compute_loss=True):
        x, y = data
        out = self._layers(x)
        return self._layers._loss_fn(out, y) if compute_loss else out
