"""Hybrid-parallel topology.

Reference: fleet/base/topology.py — CommunicateTopology (:70) and
HybridCommunicateGroup (:189) carve the world into pp/dp/sharding/sep/mp
process groups via rank arithmetic + new_group NCCL rings.

TPU-native: the topology IS the mesh. Degrees select the sizes of the five
named mesh axes (env.HYBRID_AXES); a "communication group" is a Group bound to
one axis. No rank arithmetic, no ring bootstrap — XLA routes collectives over
ICI/DCN according to the mesh layout.
"""
from __future__ import annotations

from typing import Dict, Optional

from .. import env as env_mod
from ..communication import Group, new_group


class CommunicateTopology:
    def __init__(self, hybrid_group_names=None, dims=None):
        self._names = list(hybrid_group_names or ["pipe", "data", "sharding", "sep", "model"])
        self._dims = list(dims or [1] * len(self._names))

    def get_hybrid_group_names(self):
        return list(self._names)

    def get_dim(self, name):
        return self._dims[self._names.index(name)]

    def world_size(self):
        out = 1
        for d in self._dims:
            out *= d
        return out


_name_to_axis = {"data": "dp", "pipe": "pp", "model": "mp", "sharding": "sharding", "sep": "sep"}


class HybridCommunicateGroup:
    """Axis-group view over the global mesh (reference topology.py:189)."""

    def __init__(self, degrees: Optional[Dict[str, int]] = None):
        degrees = dict(degrees or {})
        env_mod.init_parallel_env(degrees)
        self._mesh = env_mod.get_mesh()
        self._degrees = env_mod.instance().axis_degrees
        self._topo = CommunicateTopology(
            ["pipe", "data", "sharding", "sep", "model"],
            [self._degrees[a] for a in ("pp", "dp", "sharding", "sep", "mp")],
        )
        self._groups: Dict[str, Group] = {
            ax: new_group(axes=(ax,)) for ax in env_mod.HYBRID_AXES
        }
        # fused group used by sharded-dp collectives
        self._groups["dp_sharding"] = new_group(axes=("dp", "sharding"))

    @property
    def topology(self):
        return self._topo

    def get_parallel_mode(self):
        if self._degrees["pp"] > 1:
            return ParallelMode.PIPELINE_PARALLEL
        if self._degrees["mp"] > 1:
            return ParallelMode.TENSOR_PARALLEL
        if self._degrees["sharding"] > 1:
            return ParallelMode.SHARDING_PARALLEL
        if self._degrees["sep"] > 1:
            return ParallelMode.SEGMENT_PARALLEL
        return ParallelMode.DATA_PARALLEL

    # ------------------------------------------------ sizes (reference names)
    def get_data_parallel_world_size(self):
        return self._degrees["dp"]

    def get_model_parallel_world_size(self):
        return self._degrees["mp"]

    def get_pipe_parallel_world_size(self):
        return self._degrees["pp"]

    def get_sharding_parallel_world_size(self):
        return self._degrees["sharding"]

    def get_sep_parallel_world_size(self):
        return self._degrees["sep"]

    # ranks are process-level (single controller: 0); per-device ranks exist
    # inside compiled programs only.
    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_sharding_parallel_rank(self):
        return 0

    def get_sep_parallel_rank(self):
        return 0

    # ------------------------------------------------ groups
    def get_data_parallel_group(self) -> Group:
        return self._groups["dp"]

    def get_model_parallel_group(self) -> Group:
        return self._groups["mp"]

    def get_pipe_parallel_group(self) -> Group:
        return self._groups["pp"]

    def get_sharding_parallel_group(self) -> Group:
        return self._groups["sharding"]

    def get_sep_parallel_group(self) -> Group:
        return self._groups["sep"]

    def get_check_parallel_group(self, *a, **k) -> Group:
        return self._groups["mp"]

    def get_data_parallel_group_src_rank(self):
        return 0

    def get_model_parallel_group_src_rank(self):
        return 0

    @property
    def mesh(self):
        return self._mesh

    @property
    def nranks(self):
        return self._mesh.size


class ParallelMode:
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
    SEGMENT_PARALLEL = 4


_hcg: Optional[HybridCommunicateGroup] = None


def set_hybrid_communicate_group(hcg):
    global _hcg
    _hcg = hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _hcg
