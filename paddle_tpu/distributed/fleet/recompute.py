"""Activation recompute (gradient checkpointing).

Reference: distributed/fleet/recompute/recompute.py — a PyLayer that drops
activations in forward and replays the block (with RNG state restore) in
backward; recompute_hybrid.py adds mp-aware offload.

TPU-native: `jax.checkpoint` (remat) IS this feature — XLA rematerializes the
block inside the fused backward, with policy control over what to keep. RNG
replay is structural: the PRNG key consumed by the block is part of its
inputs, so the replay uses the same key. The wrapper below bridges the eager
tape: it discovers the parameters/state the block reads, forms a pure
function, and differentiates through jax.checkpoint of it.
"""
from __future__ import annotations

from typing import Callable

import jax

from ...core import hooks
from ...core.dispatch import primitive
from ...core.tensor import Tensor
from ...jit.functionalize import DiscoveryContext


def recompute(function: Callable, *args, use_reentrant=True, preserve_rng_state=True, **kwargs):
    """Run `function(*args)` so its backward recomputes instead of storing
    (reference recompute.py surface, incl. functools.partial-style usage)."""
    tensor_args = [a for a in args if isinstance(a, Tensor)]

    # discover non-arg state the block reads (parameters, buffers, RNG cell)
    ctx = DiscoveryContext()
    ctx.arg_ids = {id(t) for t in tensor_args}
    prev = hooks.discovery
    hooks.discovery = ctx
    try:
        function(*args, **kwargs)
    finally:
        hooks.discovery = prev
        ctx.rollback()
    cells = list(ctx.cells.values())

    n_args = len(tensor_args)

    def pure(*vals):
        arg_vals, cell_vals = vals[:n_args], vals[n_args:]
        saved_args = [t._value for t in tensor_args]
        saved_cells = [c._value for c in cells]
        for t, v in zip(tensor_args, arg_vals):
            t._value = v
        for c, v in zip(cells, cell_vals):
            c._value = v
        try:
            out = function(*args, **kwargs)
            return out._value if isinstance(out, Tensor) else tuple(o._value for o in out)
        finally:
            for t, v in zip(tensor_args, saved_args):
                t._value = v
            for c, v in zip(cells, saved_cells):
                c._value = v
                c._grad_node = None

    return primitive("recompute", jax.checkpoint(pure), tensor_args + cells)


def recompute_sequential(ctx, functions, *args, **kwargs):
    """reference recompute_sequential: chain recompute over segments."""
    out = args
    for fn in functions:
        out = (recompute(fn, *out, **kwargs),)
    return out[0]


def no_recompute(function, *args, **kwargs):
    """reference no_recompute: escape hatch inside a recomputed region."""
    return function(*args, **kwargs)
