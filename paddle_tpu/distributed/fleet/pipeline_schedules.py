"""Pipeline-parallel schedules: SPMD rotation (1F1B) + interleaved VPP.

Reference: fleet/meta_parallel/pipeline_parallel.py — 1F1B
`forward_backward_pipeline` (:575), interleaved virtual-pipeline variant
(:1174), FthenB (:2256) — multi-process schedules exchanging activations
over P2pHelper batched isend/irecv (pp_utils/p2p_communication.py:651).

TPU-native design — one compiled program, not N processes:

The decoder stack's weights live stacked along a leading layer dim that is
sharded over the `pp` mesh axis, so stage s's chunk of layers physically
resides on stage s's devices. Inside a `shard_map` over `pp`, a tick loop
(`lax.scan`) runs the classic rotation schedule: at tick t every stage
applies its chunk to the activation it received last tick, then `ppermute`s
the result one hop around the pp ring while stage 0 injects microbatch
t and the last stage emits finished microbatches. All p stages compute
simultaneously on different microbatches — real stage parallelism with the
canonical bubble fraction (p-1)/(m·v + p - 1):

- `num_chunks=1` — each device owns one contiguous chunk; the tick loop is
  the 1F1B/FthenB pipeline (they differ only in memory policy here, which
  `remat` controls: backward recomputes each chunk from its saved input
  instead of storing per-layer activations — 1F1B's O(in-flight) activation
  recipe).
- `num_chunks=v>1` — Megatron interleaved VPP: device d owns chunks
  {d, d+p, …, d+(v-1)p}; microbatches rotate around the ring v times,
  entering in groups of p, which cuts the bubble from (p-1)/(m+p-1) to
  (p-1)/(m·v+p-1).

Backward is jax AD through the scan+ppermute: the cotangent pipeline runs
the same rotation in reverse (ppermute transposes to the inverted ring),
so the backward pass is stage-parallel too.
"""
from __future__ import annotations

import functools
from typing import Callable, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...base import jax_compat
from ...core.dispatch import primitive
from ...core.tensor import Tensor
from ...nn.layer.layers import Layer
from .. import env as env_mod


def _dp_grad_sync(grads, batch_axis: str, mesh):
    """dp gradient sync for the pipelined schedules' accumulated weight
    grads: each leaf rides the blockwise-int8 qpsum tier when the
    quantized-comm policy engages (FLAGS_comm_quantize_dp_grads /
    amp comm_dtype, size+dtype gates in collective_opt), plain psum
    otherwise."""
    from ..collective_opt import maybe_qpsum

    n = int(dict(mesh.shape).get(batch_axis, 1))
    return [maybe_qpsum(g, batch_axis, n) for g in grads]


def chunk_permutation(num_layers: int, num_stages: int, num_chunks: int) -> List[int]:
    """Layer order for stacking so a contiguous `pp` shard of the leading dim
    holds device d's chunks {d, d+p, …, d+(v-1)p} in local slot order.

    Returns perm with perm[new_position] = original_layer_index.
    """
    p, v = num_stages, num_chunks
    k = num_layers // (p * v)
    order = []
    for d in range(p):
        for j in range(v):
            c = j * p + d
            order.extend(range(c * k, (c + 1) * k))
    return order



def _chunk_run(apply_layer, chunk_leaves, xc, key):
    """Apply one chunk's layers (lax.scan over the leading layer dim) with
    ``key`` installed as the framework RNG stream — the single RNG-cell-swap
    protocol shared by every schedule's forward/recompute path."""
    def one(xin, layer_leaves):
        return apply_layer(layer_leaves, xin), None

    def run(cl, xx):
        return jax.lax.scan(one, xx, cl)[0]

    if key is None:
        return run(chunk_leaves, xc)
    from ...base import global_state

    cell = Tensor(key, name="pp_tick_rng", stop_gradient=True)
    prev = global_state.swap_rng_cell(cell)
    try:
        return run(chunk_leaves, xc)
    finally:
        global_state.swap_rng_cell(prev)


def _solve_tick(t, d, *, p: int, v: int, m: int):
    """Which (local chunk slot j, microbatch i) is active on device d at tick
    t. Microbatch i enters chunk 0 at tick inj_i = (i//p)·v·p + i%p and moves
    one chunk per tick; chunk c lives on device c % p. At most one (j, i) is
    active per device per tick (groups of p microbatches are spaced v·p ticks
    = exactly one group's worth of per-device work)."""
    L = v * p
    cs = d + p * jnp.arange(v)  # global chunk ids of my local slots
    inj = t - cs  # required injection tick per slot
    r = inj % L
    q = inj // L
    i_cand = q * p + r
    valid = (inj >= 0) & (r < p) & (i_cand < m)
    j = jnp.argmax(valid)  # the (at most one) active slot
    c = cs[j]
    i = jnp.clip(i_cand[j], 0, m - 1)
    return j, c, i, jnp.any(valid)


def pipeline_spmd(
    apply_layer: Callable,
    stacked_leaves: Sequence,
    x,
    *,
    num_stages: int,
    num_microbatches: int,
    num_chunks: int = 1,
    mesh=None,
    axis: str = "pp",
    batch_axis: Optional[str] = None,
    remat: bool = True,
    rng_key=None,
    schedule: str = "rotation",
):
    """Run x [B, ...] through the pipelined layer stack; returns [B, ...].

    apply_layer(leaves, x_local) -> y_local applies ONE layer given its
    parameter leaves; stacked_leaves are arrays with leading dim num_layers
    in `chunk_permutation` order, sharded over `axis`.

    rng_key: optional PRNG key. When given, every (stage, tick) folds a
    distinct subkey and installs it as the framework RNG stream while the
    chunk applies — dropout inside pipelined layers draws an independent
    mask per (stage, microbatch, chunk), the SPMD analog of the reference's
    per-stage RNG state tracker (fleet/meta_parallel/mpu/random.py:34).
    Folding is deterministic, so jax.checkpoint recompute replays the exact
    masks in backward.
    """
    mesh = mesh or env_mod.get_mesh()
    p, v, m = num_stages, num_chunks, num_microbatches

    def with_tick_rng(fn, key, xc, chunk):
        """Run fn(chunk, xc) with the folded key installed as the global RNG
        stream (object-level cell swap; trace-safe per swap_rng_cell)."""
        if key is None:
            return fn(chunk, xc)
        from ...base import global_state

        cell = Tensor(key, name="pp_tick_rng", stop_gradient=True)
        prev = global_state.swap_rng_cell(cell)
        try:
            return fn(chunk, xc)
        finally:
            global_state.swap_rng_cell(prev)

    if p <= 1:
        def body(xc, scanned):
            t, leaves = scanned
            key = (jax.random.fold_in(rng_key, t) if rng_key is not None else None)
            out = with_tick_rng(apply_layer, key, xc, leaves) if key is not None \
                else apply_layer(leaves, xc)
            return out, None

        idx = jnp.arange(stacked_leaves[0].shape[0])
        return jax.lax.scan(body, x, (idx, stacked_leaves))[0]
    if m % p != 0:
        raise ValueError(f"num_microbatches {m} must divide by pp degree {p}")
    b = x.shape[0]
    if b % m != 0:
        raise ValueError(f"batch {b} must divide into {m} microbatches")

    has_rng = rng_key is not None

    if schedule in ("1f1b", "eager_1f1b", "zb", "zbh1"):
        if v != 1:
            if schedule in ("zb", "zbh1"):
                raise ValueError(
                    "ZB-H1 covers num_chunks == 1; interleaved stacks use "
                    "schedule='1f1b' (tick-interleaved VPP) or 'rotation'")
            return _pipeline_vpp_1f1b(
                apply_layer, stacked_leaves, x, p=p, v=v, m=m, mesh=mesh,
                axis=axis, batch_axis=batch_axis, rng_key=rng_key)
        return _pipeline_1f1b(
            apply_layer, stacked_leaves, x, p=p, m=m, mesh=mesh, axis=axis,
            batch_axis=batch_axis, rng_key=rng_key,
            variant="zb" if schedule in ("zb", "zbh1") else "combined")
    if schedule != "rotation":
        raise ValueError(f"unknown pipeline schedule {schedule!r}")

    def shard_body(x_mb, *args):
        if has_rng:
            rng, *leaves = args
        else:
            rng, leaves = None, list(args)
        d = jax.lax.axis_index(axis)
        n_local = leaves[0].shape[0]  # v·k layers on this device
        k = n_local // v
        local = [a.reshape((v, k) + a.shape[1:]) for a in leaves]

        def apply_chunk(chunk_leaves, xc, key):
            def one(xin, layer_leaves):
                return apply_layer(layer_leaves, xin), None

            def run(cl, xx):
                return jax.lax.scan(one, xx, cl)[0]

            return with_tick_rng(run, key, xc, chunk_leaves)

        def apply_chunk_entry(chunk_leaves, xc, key):
            return apply_chunk(chunk_leaves, xc, key)

        if remat:
            apply_chunk_entry = jax.checkpoint(
                apply_chunk_entry, policy=jax.checkpoint_policies.nothing_saveable)

        T = m * v + p - 1
        out0 = jnp.zeros(x_mb.shape, x_mb.dtype)
        cur0 = jnp.zeros(x_mb.shape[1:], x_mb.dtype)
        stage_rng = (jax.random.fold_in(rng, d) if has_rng else None)

        def tick(carry, t):
            cur, out = carry
            j, c, i, active = _solve_tick(t, d, p=p, v=v, m=m)
            chunk = [jax.lax.dynamic_index_in_dim(a, j, 0, keepdims=False)
                     for a in local]
            x_in = jnp.where(
                c == 0, jax.lax.dynamic_index_in_dim(x_mb, i, 0, keepdims=False), cur)
            key = (jax.random.fold_in(stage_rng, t) if has_rng else None)
            y = apply_chunk_entry(chunk, x_in, key)
            # emit finished microbatch (only ever true on the last stage)
            done = active & (c == v * p - 1)
            slot = jax.lax.dynamic_index_in_dim(out, i, 0, keepdims=False)
            out = jax.lax.dynamic_update_index_in_dim(
                out, jnp.where(done, y, slot), i, 0)
            # one hop around the ring; receivers only read slots their
            # schedule marks active, so inactive ticks carry harmless zeros
            nxt = jax.lax.ppermute(
                y, axis, [(s, (s + 1) % p) for s in range(p)])
            return (nxt, out), None

        (_, out), _ = jax.lax.scan(tick, (cur0, out0), jnp.arange(T))
        # outputs were written on the last stage only; psum replicates them
        # across the ring (the reference's "send outputs downstream" step)
        return jax.lax.psum(out, axis)

    mb_shape = (m, b // m) + tuple(x.shape[1:])
    x_mb = x.reshape(mb_shape)
    x_spec = P(None, batch_axis, *([None] * (len(mb_shape) - 2)))
    leaf_specs = tuple(P(axis, *([None] * (a.ndim - 1))) for a in stacked_leaves)
    rng_specs = (P(),) if has_rng else ()

    # Compiled-callable cache: eager calls reuse one jitted shard_map per
    # (apply_layer, degrees, shapes, dtypes) key instead of rebuilding (and
    # recompiling) per call. Under an outer trace the jit inlines as before.
    cache_key = (
        apply_layer, p, v, m, axis, batch_axis, remat, mesh, has_rng,
        tuple(mb_shape), str(x_mb.dtype),
        tuple((tuple(a.shape), str(a.dtype)) for a in stacked_leaves),
    )
    jitted = _COMPILED.get(cache_key)
    if jitted is not None:
        _COMPILED.move_to_end(cache_key)  # LRU touch
    if jitted is None:
        # manual only over the pp ring (+ the batch axis when microbatches
        # ride dp); other mesh axes (mp/sep) stay GSPMD-auto, so tensor-
        # parallel layers inside the pipelined template keep their sharding
        # semantics — pp×mp composes in one program
        manual = {axis} | ({batch_axis} if batch_axis else set())
        shmap = jax_compat.shard_map(
            shard_body,
            mesh=mesh,
            in_specs=(x_spec,) + rng_specs + leaf_specs,
            out_specs=x_spec,
            axis_names=frozenset(manual),
            check_vma=False,
        )
        # the remat'd scan inside shard_map requires a jit scope (harmless
        # when we are already under an outer trace — it inlines)
        jitted = jax.jit(shmap)
        _COMPILED[cache_key] = jitted
        while len(_COMPILED) > _COMPILED_MAX:
            # bounded LRU: old entries pin stacked params + executables of
            # discarded stacks; evict oldest
            _COMPILED.popitem(last=False)
    if not isinstance(x_mb, jax.core.Tracer):
        x_mb = jax.device_put(x_mb, NamedSharding(mesh, x_spec))
    rng_args = (rng_key,) if has_rng else ()
    out = jitted(x_mb, *rng_args, *stacked_leaves)
    return out.reshape(x.shape)


def _pipeline_1f1b(apply_layer, stacked_leaves, x, *, p, m, mesh, axis,
                   batch_axis, rng_key, variant="combined"):
    """True tick-interleaved 1F1B (reference:
    fleet/meta_parallel/pipeline_parallel.py:575 — in-flight microbatches
    capped per stage, unlike the rotation schedule's O(m) scan residuals).

    custom_vjp around the whole pipeline call:

    - fwd: the rotation forward scan with NO AD — nothing is stacked across
      ticks; residuals are just (x_mb, rng, leaves).
    - bwd: ONE combined scan where step u does one forward unit AND one
      backward unit per stage: F(s, i) at u = i + s, B(s, i) at
      u = i + 2(p-1) - s (the last stage turns a microbatch around in the
      same step, consuming the output cotangent g[i] directly). Forward
      chunk inputs park in a 2p-slot ring buffer until their backward tick
      recomputes the chunk under jax.vjp (same folded RNG key → identical
      dropout masks) and accumulates parameter grads in-place.

    Per-device live activation state: ≤ 2(p-1-s) saved microbatch inputs on
    stage s (≤ 2p buffer slots), independent of m — vs the rotation
    schedule's m + p - 1 stacked residuals. Cost: one extra forward stream
    inside bwd (the recompute rotation saved by storing), ≈ +25% step FLOPs
    at m ≫ p; every step does real F and B work, so SPMD predication wastes
    nothing in steady state.
    """
    b = x.shape[0]
    mb_shape = (m, b // m) + tuple(x.shape[1:])
    x_mb = x.reshape(mb_shape)
    x_spec = P(None, batch_axis, *([None] * (len(mb_shape) - 2)))
    leaf_specs = tuple(P(axis, *([None] * (a.ndim - 1))) for a in stacked_leaves)
    has_rng = rng_key is not None
    rng = rng_key if has_rng else jax.random.PRNGKey(0)

    cache_key = (
        "1f1b", variant, apply_layer, p, m, axis, batch_axis, mesh, has_rng,
        tuple(mb_shape), str(x_mb.dtype),
        tuple((tuple(a.shape), str(a.dtype)) for a in stacked_leaves),
    )
    jitted = _COMPILED.get(cache_key)
    if jitted is not None:
        _COMPILED.move_to_end(cache_key)
    if jitted is None:
        ring_fwd = [(s, (s + 1) % p) for s in range(p)]
        ring_bwd = [(s, (s - 1) % p) for s in range(p)]

        def chunk_run(leaves_chunk, xc, key):
            return _chunk_run(apply_layer, leaves_chunk, xc, key)

        def fwd_body(x_mb, rng, *leaves):
            d = jax.lax.axis_index(axis)
            leaves = list(leaves)
            stage_rng = jax.random.fold_in(rng, d) if has_rng else None
            T = m + p - 1
            out0 = jnp.zeros(x_mb.shape, x_mb.dtype)
            cur0 = jnp.zeros(x_mb.shape[1:], x_mb.dtype)

            def tick(carry, t):
                cur, out = carry
                i = t - d
                active = (i >= 0) & (i < m)
                ic = jnp.clip(i, 0, m - 1)
                x_in = jnp.where(
                    d == 0,
                    jax.lax.dynamic_index_in_dim(x_mb, ic, 0, keepdims=False),
                    cur)
                key = (jax.random.fold_in(stage_rng, ic) if has_rng else None)
                y = chunk_run(leaves, x_in, key)
                done = active & (d == p - 1)
                slot = jax.lax.dynamic_index_in_dim(out, ic, 0, keepdims=False)
                out = jax.lax.dynamic_update_index_in_dim(
                    out, jnp.where(done, y, slot), ic, 0)
                nxt = jax.lax.ppermute(y, axis, ring_fwd)
                return (nxt, out), None

            (_, out), _ = jax.lax.scan(tick, (cur0, out0), jnp.arange(T))
            return jax.lax.psum(out, axis)

        def bwd_body(g, x_mb, rng, *leaves):
            d = jax.lax.axis_index(axis)
            leaves = list(leaves)
            stage_rng = jax.random.fold_in(rng, d) if has_rng else None
            # last active tick: B(0, m-1) at u = m-1 + 2(p-1)
            T2 = m + 2 * (p - 1)
            nbuf = 2 * p
            fbuf0 = jnp.zeros((nbuf,) + x_mb.shape[1:], x_mb.dtype)
            fcur0 = jnp.zeros(x_mb.shape[1:], x_mb.dtype)
            bcur0 = jnp.zeros(x_mb.shape[1:], x_mb.dtype)
            gacc0 = [jnp.zeros_like(a) for a in leaves]
            dx0 = jnp.zeros(x_mb.shape, x_mb.dtype)

            def tick(carry, u):
                fbuf, fcur, bcur, gacc, dxout = carry
                # forward sub-tick: F(d, i_f) scheduled at u = i_f + d
                i_f = u - d
                act_f = (i_f >= 0) & (i_f < m)
                icf = jnp.clip(i_f, 0, m - 1)
                x_in = jnp.where(
                    d == 0,
                    jax.lax.dynamic_index_in_dim(x_mb, icf, 0, keepdims=False),
                    fcur)
                slot_f = jnp.mod(icf, nbuf)
                old = jax.lax.dynamic_index_in_dim(fbuf, slot_f, 0, keepdims=False)
                fbuf = jax.lax.dynamic_update_index_in_dim(
                    fbuf, jnp.where(act_f, x_in, old), slot_f, 0)
                key_f = (jax.random.fold_in(stage_rng, icf) if has_rng else None)
                y = chunk_run(leaves, x_in, key_f)
                # backward sub-tick: B(d, i_b) scheduled at u = i_b + 2(p-1) - d
                i_b = u - 2 * (p - 1) + d
                act_b = (i_b >= 0) & (i_b < m)
                icb = jnp.clip(i_b, 0, m - 1)
                ct = jnp.where(
                    d == p - 1,
                    jax.lax.dynamic_index_in_dim(g, icb, 0, keepdims=False),
                    bcur).astype(x_mb.dtype)
                x_b = jax.lax.dynamic_index_in_dim(
                    fbuf, jnp.mod(icb, nbuf), 0, keepdims=False)
                key_b = (jax.random.fold_in(stage_rng, icb) if has_rng else None)
                _, vjp_fn = jax.vjp(
                    lambda cl, xx: chunk_run(cl, xx, key_b), leaves, x_b)
                dleaves, dx = vjp_fn(ct)
                gacc = [ga + jnp.where(act_b, dl, jnp.zeros_like(dl))
                        for ga, dl in zip(gacc, dleaves)]
                cur_slot = jax.lax.dynamic_index_in_dim(dxout, icb, 0, keepdims=False)
                dxout = jax.lax.dynamic_update_index_in_dim(
                    dxout, jnp.where(act_b & (d == 0), dx, cur_slot), icb, 0)
                fcur = jax.lax.ppermute(y, axis, ring_fwd)
                bcur = jax.lax.ppermute(dx, axis, ring_bwd)
                return (fbuf, fcur, bcur, gacc, dxout), None

            (_, _, _, gacc, dxout), _ = jax.lax.scan(
                tick, (fbuf0, fcur0, bcur0, gacc0, dx0), jnp.arange(T2))
            dxout = jax.lax.psum(dxout, axis)  # only stage 0 wrote real rows
            if batch_axis:
                gacc = _dp_grad_sync(gacc, batch_axis, mesh)
            return (dxout, *gacc)

        def bwd_body_zb(g, x_mb, rng, *leaves):
            """ZB-H1 backward (reference pipeline_zero_bubble.py:66 —
            BACKWARD split into _b (input-grad, critical path) and _w
            (weight-grad, bubble filler)), re-designed for the lockstep SPMD
            tick loop. Here every traced tick costs its full body whether a
            stage is active or not, so "filling the bubble" means *shrinking
            the traced body of bubble ticks*, not reordering async jobs:

            - warmup scan (p-1 ticks): forward units only — no stage has a
              backward yet, so no vjp is traced at all (the combined 1f1b
              body pays a full predicated vjp here);
            - steady scan (m ticks): F + combined vjp, as 1f1b — dx and dW
              share one chunk recompute, which a dB/dW split would double;
            - drain scan (p-1 ticks): dx-only vjp keeps the inter-stage
              cotangent ring (the critical path) moving; the cotangents are
              parked (the chunk inputs are still in the forward ring buffer);
            - dW epilogue scan (p-1 ticks): the parked (input, cotangent)
              pairs' weight-grads — the reference's deferred _w jobs — run
              as one contiguous MXU-friendly block.

            Per-stage activation memory stays O(p) (the 2p-slot forward ring
            plus a (p-1)-slot cotangent park). Traced-unit accounting vs the
            combined schedule: schedule_cost_report()."""
            d = jax.lax.axis_index(axis)
            leaves = list(leaves)
            stage_rng = jax.random.fold_in(rng, d) if has_rng else None
            nbuf = 2 * p
            fbuf0 = jnp.zeros((nbuf,) + x_mb.shape[1:], x_mb.dtype)
            fcur0 = jnp.zeros(x_mb.shape[1:], x_mb.dtype)
            bcur0 = jnp.zeros(x_mb.shape[1:], x_mb.dtype)
            gacc0 = [jnp.zeros_like(a) for a in leaves]
            dx0 = jnp.zeros(x_mb.shape, x_mb.dtype)

            def f_subtick(fbuf, fcur, u):
                """F(d, i_f) at u = i_f + d; parks the chunk input."""
                i_f = u - d
                act_f = (i_f >= 0) & (i_f < m)
                icf = jnp.clip(i_f, 0, m - 1)
                x_in = jnp.where(
                    d == 0,
                    jax.lax.dynamic_index_in_dim(x_mb, icf, 0, keepdims=False),
                    fcur)
                slot_f = jnp.mod(icf, nbuf)
                old = jax.lax.dynamic_index_in_dim(fbuf, slot_f, 0, keepdims=False)
                fbuf = jax.lax.dynamic_update_index_in_dim(
                    fbuf, jnp.where(act_f, x_in, old), slot_f, 0)
                key_f = (jax.random.fold_in(stage_rng, icf) if has_rng else None)
                y = chunk_run(leaves, x_in, key_f)
                return fbuf, jax.lax.ppermute(y, axis, ring_fwd)

            def b_inputs(fbuf, bcur, u):
                """Cotangent + parked input for B(d, i_b) at
                u = i_b + 2(p-1) - d."""
                i_b = u - 2 * (p - 1) + d
                act_b = (i_b >= 0) & (i_b < m)
                icb = jnp.clip(i_b, 0, m - 1)
                ct = jnp.where(
                    d == p - 1,
                    jax.lax.dynamic_index_in_dim(g, icb, 0, keepdims=False),
                    bcur).astype(x_mb.dtype)
                x_b = jax.lax.dynamic_index_in_dim(
                    fbuf, jnp.mod(icb, nbuf), 0, keepdims=False)
                key_b = (jax.random.fold_in(stage_rng, icb) if has_rng else None)
                return act_b, icb, ct, x_b, key_b

            def warmup_tick(carry, u):
                fbuf, fcur = carry
                fbuf, fcur = f_subtick(fbuf, fcur, u)
                return (fbuf, fcur), None

            def steady_tick(carry, u):
                fbuf, fcur, bcur, gacc, dxout = carry
                fbuf, fcur = f_subtick(fbuf, fcur, u)
                act_b, icb, ct, x_b, key_b = b_inputs(fbuf, bcur, u)
                _, vjp_fn = jax.vjp(
                    lambda cl, xx: chunk_run(cl, xx, key_b), leaves, x_b)
                dleaves, dx = vjp_fn(ct)
                gacc = [ga + jnp.where(act_b, dl, jnp.zeros_like(dl))
                        for ga, dl in zip(gacc, dleaves)]
                cur_slot = jax.lax.dynamic_index_in_dim(dxout, icb, 0, keepdims=False)
                dxout = jax.lax.dynamic_update_index_in_dim(
                    dxout, jnp.where(act_b & (d == 0), dx, cur_slot), icb, 0)
                bcur = jax.lax.ppermute(dx, axis, ring_bwd)
                return (fbuf, fcur, bcur, gacc, dxout), None

            def drain_tick(carry, u):
                fbuf, bcur, gacc, dxout, wq_ct = carry
                act_b, icb, ct, x_b, key_b = b_inputs(fbuf, bcur, u)
                # dx-only vjp: the dW half of this microbatch's backward is
                # deferred to the epilogue (the chunk input stays parked in
                # fbuf; only the cotangent needs a slot)
                _, vjp_x = jax.vjp(lambda xx: chunk_run(leaves, xx, key_b), x_b)
                (dx,) = vjp_x(ct)
                j = u - (m + p - 1)
                old_ct = jax.lax.dynamic_index_in_dim(wq_ct, j, 0, keepdims=False)
                wq_ct = jax.lax.dynamic_update_index_in_dim(
                    wq_ct, jnp.where(act_b, ct, old_ct), j, 0)
                cur_slot = jax.lax.dynamic_index_in_dim(dxout, icb, 0, keepdims=False)
                dxout = jax.lax.dynamic_update_index_in_dim(
                    dxout, jnp.where(act_b & (d == 0), dx, cur_slot), icb, 0)
                bcur = jax.lax.ppermute(dx, axis, ring_bwd)
                return (fbuf, bcur, gacc, dxout, wq_ct), None

            def dw_tick(carry, j):
                fbuf, gacc, wq_ct = carry
                # deferred _w job j of this stage: B(d, i) drained at
                # u = m+p-1+j ⇒ i = m + j + d - (p-1); active while
                # j < p-1-d (stage p-1 deferred nothing)
                i = m + j + d - (p - 1)
                act = (i >= 0) & (i < m)
                ic = jnp.clip(i, 0, m - 1)
                x_b = jax.lax.dynamic_index_in_dim(
                    fbuf, jnp.mod(ic, nbuf), 0, keepdims=False)
                ct = jax.lax.dynamic_index_in_dim(wq_ct, j, 0, keepdims=False)
                key_b = (jax.random.fold_in(stage_rng, ic) if has_rng else None)
                _, vjp_w = jax.vjp(lambda cl: chunk_run(cl, x_b, key_b), leaves)
                (dleaves,) = vjp_w(ct)
                gacc = [ga + jnp.where(act, dl, jnp.zeros_like(dl))
                        for ga, dl in zip(gacc, dleaves)]
                return (fbuf, gacc, wq_ct), None

            wq_ct0 = jnp.zeros((max(p - 1, 1),) + x_mb.shape[1:], x_mb.dtype)
            (fbuf, fcur), _ = jax.lax.scan(
                warmup_tick, (fbuf0, fcur0), jnp.arange(p - 1))
            (fbuf, fcur, bcur, gacc, dxout), _ = jax.lax.scan(
                steady_tick, (fbuf, fcur, bcur0, gacc0, dx0),
                jnp.arange(p - 1, m + p - 1))
            (fbuf, bcur, gacc, dxout, wq_ct), _ = jax.lax.scan(
                drain_tick, (fbuf, bcur, gacc, dxout, wq_ct0),
                jnp.arange(m + p - 1, m + 2 * (p - 1)))
            (_, gacc, _), _ = jax.lax.scan(
                dw_tick, (fbuf, gacc, wq_ct), jnp.arange(p - 1))
            dxout = jax.lax.psum(dxout, axis)  # only stage 0 wrote real rows
            if batch_axis:
                gacc = _dp_grad_sync(gacc, batch_axis, mesh)
            return (dxout, *gacc)

        if variant == "zb":
            bwd_body = bwd_body_zb

        manual = {axis} | ({batch_axis} if batch_axis else set())
        fwd_shmap = jax_compat.shard_map(
            fwd_body, mesh=mesh,
            in_specs=(x_spec, P()) + leaf_specs, out_specs=x_spec,
            axis_names=frozenset(manual), check_vma=False)
        bwd_shmap = jax_compat.shard_map(
            bwd_body, mesh=mesh,
            in_specs=(x_spec, x_spec, P()) + leaf_specs,
            out_specs=(x_spec,) + leaf_specs,
            axis_names=frozenset(manual), check_vma=False)

        @jax.custom_vjp
        def call(x_mb, rng, *leaves):
            return fwd_shmap(x_mb, rng, *leaves)

        def call_fwd(x_mb, rng, *leaves):
            return fwd_shmap(x_mb, rng, *leaves), (x_mb, rng, leaves)

        def call_bwd(res, gout):
            x_mb, rng, leaves = res
            outs = bwd_shmap(gout, x_mb, rng, *leaves)
            drng = np.zeros(np.shape(rng), jax.dtypes.float0)
            return (outs[0], drng) + tuple(outs[1:])

        call.defvjp(call_fwd, call_bwd)
        jitted = jax.jit(call)
        _COMPILED[cache_key] = jitted
        while len(_COMPILED) > _COMPILED_MAX:
            _COMPILED.popitem(last=False)

    if not isinstance(x_mb, jax.core.Tracer):
        x_mb = jax.device_put(x_mb, NamedSharding(mesh, x_spec))
    out = jitted(x_mb, rng, *stacked_leaves)
    return out.reshape(x.shape)


def _pipeline_vpp_1f1b(apply_layer, stacked_leaves, x, *, p, v, m, mesh,
                       axis, batch_axis, rng_key):
    """Tick-interleaved 1F1B for the INTERLEAVED (virtual pipeline) stack
    (reference pipeline_vpp.py — Megatron VPP is 1F1B-interleaved). Closes
    the rotation schedule's O(m·v) activation residency for v > 1:

    custom_vjp around the whole pipelined call, like _pipeline_1f1b:

    - fwd: the rotation scan with NO AD (residuals: x_mb, rng, leaves).
    - bwd: ONE combined scan. With L = v·p global chunks and the rotation
      injection inj(i) = (i//p)·L + i%p, the sub-tick schedule is
          F(chunk c, mb i) at u = inj(i) + c
          B(chunk c, mb i) at u = inj(i) + 2L − 1 − c
      so B(L−1, i) turns a microbatch around one tick after its last F,
      dx hops the reverse ring once per tick (chunk c lives on device
      c % p), and F work fills the backward's warmup exactly as in the
      flat 1F1B. Chunk inputs park in a per-local-slot ring buffer until
      the backward tick recomputes the chunk under jax.vjp (same folded
      key → identical dropout masks) and accumulates parameter grads into
      the stacked leaves at the slot's row block.

    Per-device live activations: ≤ 4p microbatch inputs per local slot
    (v slots) — O(v·p), INDEPENDENT of m, vs the rotation schedule's
    m·v + p − 1 stacked residuals. Ticks: m·v + v·p + p − 1 per direction
    — the canonical interleaved bubble (p−1)/(m·v + p − 1) plus the drain.
    """
    b = x.shape[0]
    L = v * p
    mb_shape = (m, b // m) + tuple(x.shape[1:])
    x_mb = x.reshape(mb_shape)
    x_spec = P(None, batch_axis, *([None] * (len(mb_shape) - 2)))
    leaf_specs = tuple(P(axis, *([None] * (a.ndim - 1))) for a in stacked_leaves)
    has_rng = rng_key is not None
    rng = rng_key if has_rng else jax.random.PRNGKey(0)

    cache_key = (
        "vpp1f1b", apply_layer, p, v, m, axis, batch_axis, mesh, has_rng,
        tuple(mb_shape), str(x_mb.dtype),
        tuple((tuple(a.shape), str(a.dtype)) for a in stacked_leaves),
    )
    jitted = _COMPILED.get(cache_key)
    if jitted is not None:
        _COMPILED.move_to_end(cache_key)
    if jitted is None:
        ring_fwd = [(s, (s + 1) % p) for s in range(p)]
        ring_bwd = [(s, (s - 1) % p) for s in range(p)]

        def chunk_run(chunk_leaves, xc, key):
            return _chunk_run(apply_layer, chunk_leaves, xc, key)

        def slot_chunk(local, j):
            """local: leaves reshaped (v, k, ...); pick slot j's (k, ...)."""
            return [jax.lax.dynamic_index_in_dim(a, j, 0, keepdims=False)
                    for a in local]

        def fwd_body(x_mb, rng, *leaves):
            d = jax.lax.axis_index(axis)
            leaves = list(leaves)
            k = leaves[0].shape[0] // v
            local = [a.reshape((v, k) + a.shape[1:]) for a in leaves]
            stage_rng = jax.random.fold_in(rng, d) if has_rng else None
            T = m * v + p - 1
            out0 = jnp.zeros(x_mb.shape, x_mb.dtype)
            cur0 = jnp.zeros(x_mb.shape[1:], x_mb.dtype)

            def tick(carry, t):
                cur, out = carry
                j, c, i, active = _solve_tick(t, d, p=p, v=v, m=m)
                chunk = slot_chunk(local, j)
                x_in = jnp.where(
                    c == 0,
                    jax.lax.dynamic_index_in_dim(x_mb, i, 0, keepdims=False),
                    cur)
                key = (jax.random.fold_in(stage_rng, t) if has_rng else None)
                y = chunk_run(chunk, x_in, key)
                done = active & (c == L - 1)
                slot = jax.lax.dynamic_index_in_dim(out, i, 0, keepdims=False)
                out = jax.lax.dynamic_update_index_in_dim(
                    out, jnp.where(done, y, slot), i, 0)
                nxt = jax.lax.ppermute(y, axis, ring_fwd)
                return (nxt, out), None

            (_, out), _ = jax.lax.scan(tick, (cur0, out0), jnp.arange(T))
            return jax.lax.psum(out, axis)

        def _solve_b(u, d):
            """Which (slot j, chunk c, mb i) has its BACKWARD on device d at
            tick u: B(c, i) at u = inj(i) + 2L − 1 − c, c ∈ {d, d+p, ...}."""
            cs = d + p * jnp.arange(v)
            inj = u - (2 * L - 1) + cs
            r = jnp.mod(inj, L)
            q = inj // L
            i_cand = q * p + r
            valid = (inj >= 0) & (r < p) & (i_cand < m)
            j = jnp.argmax(valid)
            c = cs[j]
            i = jnp.clip(i_cand[j], 0, m - 1)
            return j, c, i, jnp.any(valid)

        def bwd_body(g, x_mb, rng, *leaves):
            d = jax.lax.axis_index(axis)
            leaves = list(leaves)
            k = leaves[0].shape[0] // v
            local = [a.reshape((v, k) + a.shape[1:]) for a in leaves]
            stage_rng = jax.random.fold_in(rng, d) if has_rng else None
            T2 = m * v + v * p + p - 1
            nbuf = 4 * p
            # per-slot parked chunk inputs: [v, nbuf, ...]
            fbuf0 = jnp.zeros((v, nbuf) + x_mb.shape[1:], x_mb.dtype)
            fcur0 = jnp.zeros(x_mb.shape[1:], x_mb.dtype)
            bcur0 = jnp.zeros(x_mb.shape[1:], x_mb.dtype)
            gacc0 = [jnp.zeros_like(a) for a in local]  # (v, k, ...)
            dx0 = jnp.zeros(x_mb.shape, x_mb.dtype)

            def tick(carry, u):
                fbuf, fcur, bcur, gacc, dxout = carry
                # ---- forward sub-tick: F(c_f, i_f) at u = inj(i_f) + c_f
                jf, cf, i_f, act_f = _solve_tick(u, d, p=p, v=v, m=m)
                x_in = jnp.where(
                    cf == 0,
                    jax.lax.dynamic_index_in_dim(x_mb, i_f, 0, keepdims=False),
                    fcur)
                slot_f = jnp.mod(i_f, nbuf)
                old = fbuf[jf, slot_f]
                fbuf = fbuf.at[jf, slot_f].set(jnp.where(act_f, x_in, old))
                key_f = (jax.random.fold_in(stage_rng, u) if has_rng else None)
                y = chunk_run(slot_chunk(local, jf), x_in, key_f)
                # ---- backward sub-tick: B(c_b, i_b) mirrored
                jb, cb, i_b, act_b = _solve_b(u, d)
                ct = jnp.where(
                    cb == L - 1,
                    jax.lax.dynamic_index_in_dim(g, i_b, 0, keepdims=False),
                    bcur).astype(x_mb.dtype)
                x_b = fbuf[jb, jnp.mod(i_b, nbuf)]
                # refold the key F(c_b, i_b) used: its forward tick
                u_f = u - 2 * (L - 1 - cb) - 1
                key_b = (jax.random.fold_in(stage_rng, u_f) if has_rng
                         else None)
                _, vjp_fn = jax.vjp(
                    lambda cl, xx: chunk_run(cl, xx, key_b),
                    slot_chunk(local, jb), x_b)
                dchunk, dx = vjp_fn(ct)
                gacc = [ga.at[jb].add(jnp.where(act_b, dl, jnp.zeros_like(dl)))
                        for ga, dl in zip(gacc, dchunk)]
                cur_slot = jax.lax.dynamic_index_in_dim(
                    dxout, i_b, 0, keepdims=False)
                dxout = jax.lax.dynamic_update_index_in_dim(
                    dxout, jnp.where(act_b & (cb == 0), dx, cur_slot), i_b, 0)
                fcur = jax.lax.ppermute(y, axis, ring_fwd)
                bcur = jax.lax.ppermute(dx, axis, ring_bwd)
                return (fbuf, fcur, bcur, gacc, dxout), None

            (_, _, _, gacc, dxout), _ = jax.lax.scan(
                tick, (fbuf0, fcur0, bcur0, gacc0, dx0), jnp.arange(T2))
            dxout = jax.lax.psum(dxout, axis)  # only chunk 0's device wrote
            gout = [ga.reshape((v * k,) + ga.shape[2:]) for ga in gacc]
            if batch_axis:
                gout = _dp_grad_sync(gout, batch_axis, mesh)
            return (dxout, *gout)

        manual = {axis} | ({batch_axis} if batch_axis else set())
        fwd_shmap = jax_compat.shard_map(
            fwd_body, mesh=mesh,
            in_specs=(x_spec, P()) + leaf_specs, out_specs=x_spec,
            axis_names=frozenset(manual), check_vma=False)
        bwd_shmap = jax_compat.shard_map(
            bwd_body, mesh=mesh,
            in_specs=(x_spec, x_spec, P()) + leaf_specs,
            out_specs=(x_spec,) + leaf_specs,
            axis_names=frozenset(manual), check_vma=False)

        @jax.custom_vjp
        def call(x_mb, rng, *leaves):
            return fwd_shmap(x_mb, rng, *leaves)

        def call_fwd(x_mb, rng, *leaves):
            return fwd_shmap(x_mb, rng, *leaves), (x_mb, rng, leaves)

        def call_bwd(res, gout):
            x_mb, rng, leaves = res
            outs = bwd_shmap(gout, x_mb, rng, *leaves)
            drng = np.zeros(np.shape(rng), jax.dtypes.float0)
            return (outs[0], drng) + tuple(outs[1:])

        call.defvjp(call_fwd, call_bwd)
        jitted = jax.jit(call)
        _COMPILED[cache_key] = jitted
        while len(_COMPILED) > _COMPILED_MAX:
            _COMPILED.popitem(last=False)

    if not isinstance(x_mb, jax.core.Tracer):
        x_mb = jax.device_put(x_mb, NamedSharding(mesh, x_spec))
    out = jitted(x_mb, rng, *stacked_leaves)
    return out.reshape(x.shape)


def schedule_cost_report(p: int, m: int, schedule: str) -> dict:
    """Traced-unit accounting for one train step of the tick-interleaved
    schedules (the SPMD analog of the reference's per-stage job-list bubble
    accounting). Unit model, with per-chunk remat: F = 1 unit,
    combined vjp = 3 (recompute + dx + dW), dx-only vjp = 2, dW-only
    vjp = 2. In the lockstep tick loop every traced tick costs its full
    body on every stage, active or not, so wasted = total − useful is the
    bubble — the quantity ZB-H1 shrinks by giving warmup ticks an F-only
    body and bubble-filling the deferred dW jobs.
    """
    useful = 4 * m  # per stage: m forwards + m combined backwards
    if schedule in ("1f1b", "eager_1f1b"):
        total = (m + 2 * (p - 1)) * 4  # every tick: F + combined vjp
    elif schedule in ("zb", "zbh1"):
        total = ((p - 1) * 1          # warmup: F only
                 + m * 4              # steady: F + combined vjp
                 + (p - 1) * 2        # drain: dx-only vjp
                 + (p - 1) * 2)       # epilogue: deferred dW block
    else:
        raise ValueError(f"no cost model for schedule {schedule!r}")
    return {
        "schedule": schedule, "p": p, "m": m,
        "total_units": total, "useful_units": useful,
        "wasted_units": total - useful,
        "bubble_fraction": (total - useful) / total,
    }


import collections

_COMPILED: "collections.OrderedDict" = collections.OrderedDict()
_COMPILED_MAX = 32


class PipelinedStack(Layer):
    """A stack of homogeneous layers executed with the SPMD pipeline schedule
    (the TPU analog of PipelineLayer's segment-per-stage + the reference's
    1F1B/interleave runtime, pipeline_parallel.py:575/:1174).

    Parameters are stored STACKED: one Parameter per template weight with a
    leading num_layers dim in `chunk_permutation` order, sharded over `pp`.
    The template layer instance is used purely as a tracing shell (its
    forward defines the per-layer computation; dropout/stateful buffers are
    not supported inside the stack — matches the reference's constraint that
    pp stage boundaries carry activations only).
    """

    def __init__(self, layer_factory: Callable[[], Layer], num_layers: int,
                 num_stages: Optional[int] = None, num_chunks: int = 1,
                 num_microbatches: Optional[int] = None, remat: bool = True,
                 schedule: str = "rotation"):
        super().__init__()
        degrees = env_mod.instance().axis_degrees or {}
        self.num_stages = num_stages or max(degrees.get("pp", 1), 1)
        self.num_chunks = num_chunks
        self.num_layers = num_layers
        self.remat = remat
        if schedule not in ("rotation", "1f1b", "eager_1f1b", "zb", "zbh1"):
            raise ValueError(f"unknown pipeline schedule {schedule!r}")
        if schedule in ("zb", "zbh1") and num_chunks != 1:
            raise ValueError(
                "ZB-H1 covers num_chunks == 1; interleaved stacks use "
                "schedule='1f1b' (tick-interleaved VPP) or 'rotation'")
        self.schedule = schedule
        if num_layers % (self.num_stages * num_chunks) != 0:
            raise ValueError(
                f"num_layers {num_layers} must divide by "
                f"num_stages*num_chunks {self.num_stages * num_chunks}")
        self.num_microbatches = num_microbatches or 2 * self.num_stages

        self.template = layer_factory()
        self._param_names = [n for n, _ in self.template.named_parameters()]
        perm = chunk_permutation(num_layers, self.num_stages, self.num_chunks)
        # independent per-layer inits, stacked in permuted order → exact
        # numeric parity with a serial LayerList of the same factory
        inits = [self.template] + [layer_factory() for _ in range(num_layers - 1)]
        mesh = env_mod.get_mesh()
        for name in self._param_names:
            vals = [dict(l.named_parameters())[name]._value for l in inits]
            stacked = jnp.stack([vals[orig] for orig in perm], 0)
            if self.num_stages > 1 and mesh is not None and mesh.shape.get("pp", 1) == self.num_stages:
                spec = P("pp", *([None] * (stacked.ndim - 1)))
                stacked = jax.device_put(stacked, NamedSharding(mesh, spec))
            pname = "stack_" + name.replace(".", "__")
            param = self.create_parameter(
                shape=list(stacked.shape), dtype=str(stacked.dtype))
            param._replace_value(stacked)
            setattr(self, pname, param)
        self._stacked_names = ["stack_" + n.replace(".", "__") for n in self._param_names]

    def _template_params(self):
        named = dict(self.template.named_parameters())
        return [named[n] for n in self._param_names]

    def _apply_layer(self, leaves, xv):
        """Functional application of the template with given leaf values —
        runs the eager layer on tracers with the framework tape off (jax AD
        differentiates through it; the tape sees only the outer primitive)."""
        from ...base import global_state

        tparams = self._template_params()
        saved = [tp._value for tp in tparams]
        for tp, lv in zip(tparams, leaves):
            tp._value = lv
        try:
            with global_state.no_grad_guard():
                out = self.template(Tensor(xv, stop_gradient=True))
            return out._value if hasattr(out, "_value") else out
        finally:
            for tp, sv in zip(tparams, saved):
                tp._value = sv

    def forward(self, x):
        stacked = [getattr(self, n) for n in self._stacked_names]
        mesh = env_mod.get_mesh()
        xv0 = x._value if hasattr(x, "_value") else x

        # training mode: thread a PRNG key so dropout inside the stack folds
        # per (stage, tick) — see pipeline_spmd's rng_key contract
        rng_key = None
        if self.training:
            from ...base import global_state

            rng_key = global_state.default_generator.split()

        # adapt the microbatch count to the incoming batch: largest m ≤ the
        # configured one with m % p == 0 and batch % m == 0; a batch that
        # cannot even split into p microbatches runs the serial scan path
        # (correct, no stage parallelism — the reference errors out here
        # instead; degrading keeps small-batch eval/debug usable)
        p = self.num_stages
        batch = xv0.shape[0]
        m_eff = 0
        m = (self.num_microbatches // p) * p
        while m >= p:
            if batch % m == 0:
                m_eff = m
                break
            m -= p
        stages_eff = p if m_eff else 1
        if not m_eff and self.num_chunks > 1:
            # serial fallback would replay the chunk-permuted stacking order;
            # interleaved stacks keep the strict divisibility contract
            raise ValueError(
                f"batch {batch} cannot split into ≥{p} microbatches for the "
                f"interleaved pipeline (num_chunks={self.num_chunks})")
        m_eff = m_eff or 1

        # dp sharding decision must follow the EFFECTIVE microbatch split
        dp = mesh.shape.get("dp", 1) if mesh is not None else 1
        mb = batch // m_eff
        batch_axis = "dp" if (dp > 1 and stages_eff > 1 and mb % dp == 0) else None

        def fn(xv, *leaf_vals):
            return pipeline_spmd(
                self._apply_layer, list(leaf_vals), xv,
                num_stages=stages_eff,
                num_microbatches=m_eff,
                num_chunks=self.num_chunks,
                batch_axis=batch_axis,
                remat=self.remat,
                rng_key=rng_key,
                schedule=self.schedule if stages_eff > 1 else "rotation",
            )

        return primitive("pipelined_stack", fn, [x, *stacked])

    def layer_state_dict(self, idx: int):
        """Un-permuted single-layer weights (for export / parity checks)."""
        perm = chunk_permutation(self.num_layers, self.num_stages, self.num_chunks)
        pos = perm.index(idx)
        return {
            n: getattr(self, sn)._value[pos]
            for n, sn in zip(self._param_names, self._stacked_names)
        }


def forward_backward_pipeline_rotation(stack: PipelinedStack, x):
    """Rotation schedule, one chunk per stage — schedule-wise a rotation
    GPipe: all-forward ticks, then jax-AD-reversed backward with per-chunk
    remat. In-flight activation memory is O(m·v) per device (each stage's
    saved chunk inputs); prefer schedule='1f1b' at m ≫ p."""
    assert stack.num_chunks == 1
    return stack(x)


def forward_backward_pipeline_1f1b(stack: PipelinedStack, x):
    """True tick-interleaved 1F1B (reference pipeline_parallel.py:575):
    in-flight microbatches capped per stage at ≤ 2(p-1-s) instead of the
    rotation schedule's m + p - 1 stacked residuals. Runs the stack's
    forward with the 1f1b schedule regardless of its configured default."""
    assert stack.num_chunks == 1
    prev, stack.schedule = stack.schedule, "1f1b"
    try:
        return stack(x)
    finally:
        stack.schedule = prev


def forward_backward_pipeline_zero_bubble(stack: PipelinedStack, x):
    """ZB-H1 (reference pipeline_zero_bubble.py:66): backward split into
    dB (input-grad, kept on the inter-stage critical path) and dW
    (weight-grad, deferred into the drain bubble as a batched epilogue).
    See bwd_body_zb for the lockstep-SPMD redesign; schedule_cost_report
    quantifies the traced-unit saving vs the combined 1F1B body."""
    assert stack.num_chunks == 1
    prev, stack.schedule = stack.schedule, "zb"
    try:
        return stack(x)
    finally:
        stack.schedule = prev


def forward_backward_pipeline_eager_1f1b(stack: PipelinedStack, x):
    """Eager 1F1B (reference pipeline_eager_1f1b.py:36: warmup runs
    2(p−s)−1 forwards instead of p−s, trading in-flight activations for
    overlap). In the lockstep SPMD tick loop F(s, i) already runs at the
    earliest dependency-feasible tick u = i + s and each stage parks
    ≤ 2(p−1−s) inputs — exactly the eager profile — so this IS the 1f1b
    tick mapping; the lazy/standard variant would park the same-sized
    tensors one hop later with zero memory or tick difference here."""
    assert stack.num_chunks == 1
    prev, stack.schedule = stack.schedule, "eager_1f1b"
    try:
        return stack(x)
    finally:
        stack.schedule = prev


def forward_backward_pipeline_interleave(stack: PipelinedStack, x):
    """Reference-named entry (pipeline_parallel.py:1174): interleaved VPP
    chunk placement (device d owns chunks {d, d+p, ...}); same rotation tick
    loop, bubble (p-1)/(m·v+p-1)."""
    assert stack.num_chunks > 1
    return stack(x)


