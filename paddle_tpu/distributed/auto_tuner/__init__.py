"""paddle.distributed.auto_tuner parity (reference:
python/paddle/distributed/auto_tuner/ — candidate grid search over
dp/mp/pp/micro-batch configs with pruning (prune.py) and a launch-measure
loop (tuner.py)).

TPU-native: candidate generation + pruning reuse the planner's rules
(auto_parallel/planner.py); measurement runs the user's train step per
surviving config on this process's mesh (single-controller — no relaunch
needed, the mesh is rebuilt in place), keeping the reference's
best-config-by-throughput contract.
"""
from __future__ import annotations

import time
from typing import Callable, List, Optional

from ..auto_parallel.planner import ModelSpec, Plan, choose_plan, estimate_per_device_bytes, feasible


class AutoTuner:
    """Grid search with pruning + in-place measurement (reference tuner.py)."""

    def __init__(self, spec: ModelSpec, n_devices: int, batch_size: int,
                 hbm_bytes: int = 16 << 30, max_candidates: int = 8):
        self.spec = spec
        self.n_devices = n_devices
        self.batch_size = batch_size
        self.hbm_bytes = hbm_bytes
        self.max_candidates = max_candidates
        self.history: List[dict] = []

    def candidates(self) -> List[Plan]:
        """Pruned candidate list, best-first by the greedy heuristic. The
        grid covers (dp, mp, pp) × ZeRO sharding ∈ {1, dp} (the reference
        tuner's sharding_stage dimension); prunes are RECORDED in history —
        divisibility prunes as 'infeasible', memory-model prunes as 'oom'
        with the estimate (reference prune.py's audit trail)."""
        from ..auto_parallel.planner import _factorizations

        # fresh audit per call: tune() re-enumerates, so stale prune records
        # from an earlier candidates() call must not duplicate
        self.history = [h for h in self.history if "pruned" not in h]
        out = []
        for dp, mp, pp, sep in _factorizations(self.n_devices):
            if sep != 1:
                continue
            if not feasible(self.spec, self.batch_size, dp, mp, pp, sep):
                self.history.append({
                    "plan": {"dp_degree": dp, "mp_degree": mp,
                             "pp_degree": pp, "sep_degree": sep},
                    "pruned": "infeasible"})
                continue
            for sharding in ({1, dp} if dp > 1 else {1}):
                mem = estimate_per_device_bytes(
                    self.spec, self.batch_size, dp, mp, pp, sep,
                    sharding=sharding)
                plan = Plan(dp, mp, pp, sep, sharding=sharding,
                            per_device_bytes=mem)
                if mem > self.hbm_bytes:
                    self.history.append({
                        "plan": plan.describe,
                        "pruned": f"oom: est {mem / 2**30:.2f} GiB "
                                  f"> {self.hbm_bytes / 2**30:.2f} GiB"})
                    continue
                out.append(plan)
        # prefer plain dp, then fewer pipeline stages, then smaller mp,
        # then lower memory (sharding enters via the memory term)
        out.sort(key=lambda p: (-p.dp, p.pp, p.mp, p.per_device_bytes))
        return out[: self.max_candidates]

    def tune(self, build_and_step: Callable[[Plan], Callable[[], None]],
             steps: int = 3, warmup: int = 1) -> Plan:
        """Measure each candidate: build_and_step(plan) returns a zero-arg
        step callable under that plan's mesh; best wall-clock wins. When
        the step exposes a TrainStep (``step.train_step``), the history
        also records the plan's estimated-vs-actual compiled memory
        (VERDICT r3 #9: the pruning thresholds stay honest)."""
        best: Optional[Plan] = None
        best_dt = float("inf")
        for plan in self.candidates():
            try:
                step = build_and_step(plan)
                for _ in range(warmup):
                    step()
                t0 = time.perf_counter()
                for _ in range(steps):
                    step()
                dt = (time.perf_counter() - t0) / steps
            except Exception as e:  # candidate failed to build/run: prune it
                self.history.append({"plan": plan.describe, "error": repr(e)})
                continue
            record = {"plan": plan.describe, "step_seconds": dt}
            train_step = getattr(step, "train_step", None)
            if train_step is not None:
                try:
                    from ..auto_parallel.planner import calibrate_against_compiled

                    record["memory"] = calibrate_against_compiled(
                        train_step, self.spec, self.batch_size, plan.describe)
                except Exception as e:
                    record["memory_error"] = repr(e)
            self.history.append(record)
            if dt < best_dt:
                best, best_dt = plan, dt
        if best is None:
            # nothing measured — fall back to the static chooser
            return choose_plan(self.spec, self.n_devices, self.batch_size,
                               hbm_bytes=self.hbm_bytes)
        measured = sum(1 for h in self.history if "step_seconds" in h)
        best.reason = f"measured {best_dt * 1e3:.1f} ms/step over {measured} candidates"
        return best


__all__ = ["AutoTuner", "ModelSpec", "Plan"]
