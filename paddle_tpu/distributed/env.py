"""Distributed environment: the TPU-native rebuild of the reference's process
bootstrap + communicator stack.

Reference (SURVEY.md §2.14):
- `init_parallel_env` (python/paddle/distributed/parallel.py:978) creates a
  TCPStore and NCCL communicators per ring;
- `HybridCommunicateGroup` (fleet/base/topology.py:189) splits the world into
  pp/mp/sep/sharding/dp process groups.

TPU-native design: there is ONE fabric object — a `jax.sharding.Mesh` over all
devices, with named axes for each parallelism dimension. "Process groups"
become mesh axes; NCCL rings become XLA collectives over ICI/DCN; the TCPStore
rendezvous becomes the JAX coordination service (`jax.distributed.initialize`).
A single python controller drives every device (SPMD), so `rank` at the python
level is the *process* index (multi-host), while per-device rank only exists
inside compiled programs (shard_map regions / GSPMD partitioning).
"""
from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh

from ..base.log import get_logger

# canonical hybrid axis order, outermost first. Matches the reference's 5-D
# topology order pp->dp->sharding->sep->mp (fleet/base/topology.py:72) with
# dp outermost-adjacent so that dp+sharding ride the slower links and mp/sep
# (heaviest traffic) ride the innermost ICI.
HYBRID_AXES = ("pp", "dp", "sharding", "sep", "mp")


class ParallelEnv:
    """Singleton world description: devices, mesh, axis degrees.

    Also mirrors the reference's `ParallelEnv` (python/paddle/distributed/
    parallel.py) env-var surface: rank/world_size/device_id.
    """

    _instance: Optional["ParallelEnv"] = None

    def __init__(self):
        self.initialized = False
        self.mesh: Optional[Mesh] = None
        self.axis_degrees: Dict[str, int] = {}
        self.device_kind = "unknown"

    # ---------------------------------------------------------------- process
    @property
    def rank(self) -> int:
        return jax.process_index() if self.initialized else int(os.environ.get("PADDLE_TRAINER_ID", 0))

    @property
    def world_size(self) -> int:
        return jax.process_count() if self.initialized else int(os.environ.get("PADDLE_TRAINERS_NUM", 1))

    @property
    def local_rank(self) -> int:
        return 0

    @property
    def nranks(self) -> int:
        return self.world_size

    @property
    def device_id(self) -> int:
        return 0

    # ---------------------------------------------------------------- mesh
    def build_mesh(self, degrees: Optional[Dict[str, int]] = None, devices=None) -> Mesh:
        """Create the global device mesh.

        degrees: dict axis->size over HYBRID_AXES (missing axes get 1; one
        unspecified axis may be -1 to absorb the remaining devices; by default
        `dp` absorbs everything).
        """
        devices = list(devices if devices is not None else jax.devices())
        n = len(devices)
        degrees = dict(degrees or {})
        for ax in HYBRID_AXES:
            degrees.setdefault(ax, -1 if ax == "dp" and -1 not in degrees.values() else 1)
        fixed = int(np.prod([d for d in degrees.values() if d != -1]))
        if any(d == -1 for d in degrees.values()):
            if n % fixed != 0:
                raise ValueError(f"device count {n} not divisible by fixed degrees {degrees}")
            fill = n // fixed
            degrees = {k: (fill if v == -1 else v) for k, v in degrees.items()}
        total = int(np.prod(list(degrees.values())))
        if total != n:
            raise ValueError(f"mesh degrees {degrees} product {total} != device count {n}")
        shape = tuple(degrees[ax] for ax in HYBRID_AXES)
        arr = np.array(devices).reshape(shape)
        self.mesh = Mesh(arr, HYBRID_AXES)
        self.axis_degrees = degrees
        self.device_kind = devices[0].platform
        return self.mesh


def instance() -> ParallelEnv:
    if ParallelEnv._instance is None:
        ParallelEnv._instance = ParallelEnv()
    return ParallelEnv._instance


def init_parallel_env(degrees: Optional[Dict[str, int]] = None) -> ParallelEnv:
    """Initialize the distributed fabric (reference: parallel.py:978).

    Multi-host: wires `jax.distributed.initialize` from the same env contract
    the reference launcher sets (PADDLE_MASTER / PADDLE_TRAINER_ID /
    PADDLE_TRAINERS_NUM), then builds the global mesh over all hosts' devices.
    Single-host: just builds the mesh over local devices.
    """
    env = instance()
    if env.initialized:
        if degrees:
            env.build_mesh(degrees)
        return env
    master = os.environ.get("PADDLE_MASTER") or os.environ.get("MASTER_ADDR")
    nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", os.environ.get("WORLD_SIZE", 1)))
    if master and nprocs > 1 and jax.process_count() == 1:
        port = os.environ.get("MASTER_PORT")
        addr = master if (":" in master or not port) else f"{master}:{port}"
        pid = int(os.environ.get("PADDLE_TRAINER_ID", os.environ.get("RANK", 0)))
        get_logger().info("jax.distributed.initialize(%s, %d, %d)", addr, nprocs, pid)
        jax.distributed.initialize(coordinator_address=addr, num_processes=nprocs, process_id=pid)
    env.initialized = True
    env.build_mesh(degrees)
    return env


def get_mesh() -> Mesh:
    env = instance()
    if env.mesh is None:
        env.build_mesh()
    return env.mesh


def set_mesh(mesh: Mesh):
    env = instance()
    env.mesh = mesh
    env.axis_degrees = {ax: mesh.shape[ax] for ax in mesh.axis_names}


def get_rank() -> int:
    return instance().rank


def get_world_size() -> int:
    return instance().world_size


def is_initialized() -> bool:
    return instance().initialized


def barrier(group=None):
    """Block until all processes' outstanding work completes.

    Single-controller SPMD needs no explicit device barrier; multi-host sync
    rides the coordination service via a tiny psum.
    """
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("paddle_tpu_barrier")
    else:
        jax.effects_barrier()


def shard_largest_dim(value, jmesh: Mesh, axis_name: str):
    """Place `value` with its largest axis-size-divisible dim sharded over
    ``axis_name`` (replicated when no dim divides). Shared by ZeRO param/state
    sharding and pipeline stage placement."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = jmesh.shape.get(axis_name, 1)
    shape = value.shape
    best = None
    for d in range(len(shape)):
        if shape[d] % n == 0 and shape[d] >= n:
            if best is None or shape[d] > shape[best]:
                best = d
    if best is None:
        return jax.device_put(value, NamedSharding(jmesh, P()))
    spec = [None] * len(shape)
    spec[best] = axis_name
    return jax.device_put(value, NamedSharding(jmesh, P(*spec)))
