"""paddle_tpu.distributed.collective_opt — comm-efficient collectives.

Two tiers over the comm hot paths (ISSUE 10; EQuARX arxiv 2506.17615,
memory-efficient redistribution arxiv 2112.01075):

- :mod:`qpsum` — the blockwise-int8 quantized allreduce: ``qpsum_lax``
  (explicit wire path for shard_map/pmap regions), ``dp_sync_gspmd``
  (the GSPMD sharding-constraint tier TrainStep's dp grad-sync stage
  uses), ``qpsum_reference`` (single-device oracle) and the payload
  accounting (``wire_report``) the bench/cost model cross-check.
- :mod:`reshard` — portable resharding: ``plan_route`` /
  ``apply_route`` compose placement transitions from
  all_to_all/slice/all_gather sequences with O(shard) peak residency;
  ``partial_to_shard`` / ``partial_to_replicate`` are the lax-tier
  kernels for spmd-region code.

This module owns the *engagement policy* — who rides the quantized tier
(``FLAGS_comm_quantize_dp_grads``, ``amp.auto_cast(comm_dtype="int8")``,
per-call ``all_reduce(quantized=...)``), the min-bytes / dtype gates —
plus the ``comm.*`` telemetry counters and the per-axis wire-dtype
record the QZ8xx lint family audits.
"""
from __future__ import annotations

from typing import Optional

from .qpsum import (dequantize_blockwise, dp_sync_gspmd, qpsum_lax,
                    qpsum_reference, quantize_blockwise, tensor_wire_bytes,
                    wire_report)
from .reshard import (ReshardRoute, apply_route, partial_to_replicate,
                      partial_to_shard, plan_route)

__all__ = [
    "ReshardRoute", "apply_route", "dequantize_blockwise", "dp_sync_gspmd",
    "engaged_comm_dtype", "maybe_qpsum", "partial_to_replicate",
    "partial_to_shard", "plan_route", "qpsum_lax", "qpsum_reference",
    "note_wire_dtype", "quantize_blockwise", "quantize_decision", "stats",
    "axis_wire_dtypes", "tensor_wire_bytes", "wire_report",
    "gspmd_sync_axis", "reset_comm_records",
]


def _flag(name, default):
    try:
        from ...base.flags import get_flag

        return get_flag(name)
    except Exception:
        return default


# ------------------------------------------------------------- telemetry
def _counter(name: str, help: str = ""):
    from ...observability import registry

    return registry.counter(name, help)


def _tick(name: str, value: float = 1.0, **labels):
    try:
        _counter("comm." + name).inc(value, **labels)
    except Exception:
        pass


# per-axis record of the wire dtypes engaged syncs actually used — the
# QZ803 feed. Only *engaged, size/dtype-eligible* syncs record: a dense
# entry next to int8 on one axis means some engaged syncs structurally
# could not take the quantized route (multi-axis group, unresolvable
# axis size) — mixed comm dtypes across one mesh axis.
_axis_wire_dtypes: dict = {}


def _note_wire_dtype(axis: str, wire_dtype: str) -> None:
    _axis_wire_dtypes.setdefault(str(axis), set()).add(str(wire_dtype))


def note_wire_dtype(axis: str, wire_dtype: str) -> None:
    """Record one engaged sync's wire dtype on a mesh axis (the QZ803
    mixed-dtype feed) — for comm tiers outside this package (the zero1
    quantized weight all-gather)."""
    _note_wire_dtype(axis, wire_dtype)


def axis_wire_dtypes() -> dict:
    return {ax: sorted(s) for ax, s in _axis_wire_dtypes.items()}


def reset_comm_records() -> None:
    """Clear the per-axis wire-dtype record (test isolation)."""
    _axis_wire_dtypes.clear()


def stats() -> dict:
    """The ``comm.*`` view for debugging/tests: the wire-dtype record
    (counters live in ``observability.snapshot()``)."""
    return {"axis_wire_dtypes": axis_wire_dtypes()}


# ------------------------------------------------------------ engagement
def engaged_comm_dtype(explicit: Optional[bool] = None) -> Optional[str]:
    """Resolve the comm dtype for a gradient-sync collective: explicit
    per-call override > active AMP state's ``comm_dtype`` >
    ``FLAGS_comm_quantize_dp_grads``. Returns ``"int8"`` or ``None``."""
    if explicit is not None:
        return "int8" if explicit else None
    try:
        from ...base import global_state

        state = global_state.amp_state()
    except Exception:
        state = None
    if state is not None and getattr(state, "comm_dtype", None):
        return str(state.comm_dtype)
    return "int8" if _flag("comm_quantize_dp_grads", False) else None


class QuantizeDecision:
    """Outcome of the per-collective tier choice (see
    :func:`quantize_decision`)."""

    __slots__ = ("quantize", "reason", "axis", "axis_size", "block")

    def __init__(self, quantize, reason, axis="", axis_size=1, block=256):
        self.quantize = bool(quantize)
        self.reason = reason
        self.axis = axis
        self.axis_size = int(axis_size)
        self.block = int(block)


def quantize_decision(value, *, is_sum: bool, axes,
                      explicit: Optional[bool] = None,
                      axis_size: Optional[int] = None) -> QuantizeDecision:
    """Decide whether one in-region allreduce rides the quantized tier.
    ``value`` is the (possibly traced) local operand; ``axes`` the mesh
    axes the collective reduces over. Callers that know the collective's
    mesh pass ``axis_size`` (pipeline schedules do — their mesh need not
    be the installed env mesh); otherwise it resolves from the mesh
    *already installed* in the env (never building one as a side effect
    mid-trace). Fallback reasons are counted (``comm.qpsum_fallback``)
    and structural ones land in the per-axis wire-dtype record (the
    QZ803 feed)."""
    import jax.numpy as jnp

    block = int(_flag("comm_quantize_block", 256))
    if engaged_comm_dtype(explicit) != "int8":
        return QuantizeDecision(False, "disengaged", block=block)
    if not is_sum:
        _tick("qpsum_fallback", reason="non_sum")
        return QuantizeDecision(False, "non_sum", block=block)
    dtype = getattr(value, "dtype", None)
    if dtype is None or not jnp.issubdtype(dtype, jnp.floating):
        _tick("qpsum_fallback", reason="non_float")
        return QuantizeDecision(False, "non_float", block=block)
    min_bytes = int(_flag("comm_quantize_min_bytes", 2048))
    nbytes = 1
    for d in getattr(value, "shape", ()):
        nbytes *= int(d)
    nbytes *= int(getattr(dtype, "itemsize", 4))
    if 0 < min_bytes > nbytes:
        _tick("qpsum_fallback", reason="below_min_bytes")
        return QuantizeDecision(False, "below_min_bytes", block=block)
    axes = tuple(axes)
    if len(axes) != 1:
        _tick("qpsum_fallback", reason="multi_axis")
        for ax in axes:
            _note_wire_dtype(ax, str(dtype))
        return QuantizeDecision(False, "multi_axis", block=block)
    ax = axes[0]
    if axis_size is None:
        try:
            from .. import env as env_mod

            mesh = env_mod.instance().mesh
            axis_size = int(dict(mesh.shape)[ax]) if mesh is not None else None
        except Exception:
            axis_size = None
    if axis_size is None:
        _tick("qpsum_fallback", reason="axis_size_unknown")
        _note_wire_dtype(ax, str(dtype))
        return QuantizeDecision(False, "axis_size_unknown", axis=ax,
                                block=block)
    if axis_size <= 1:
        return QuantizeDecision(False, "axis_size_1", axis=ax,
                                axis_size=axis_size, block=block)
    _note_wire_dtype(ax, "int8")
    _tick("qpsum_calls")
    row = tensor_wire_bytes(nbytes // int(getattr(dtype, "itemsize", 4)),
                            int(getattr(dtype, "itemsize", 4)),
                            axis_size, block)
    _tick("qpsum_bytes_dense", row["dense_bytes"])
    _tick("qpsum_bytes_wire", row["wire_bytes"])
    return QuantizeDecision(True, "quantized", axis=ax,
                            axis_size=axis_size, block=block)


def maybe_qpsum(x, axis_name: str, axis_size: int,
                explicit: Optional[bool] = None):
    """Tiered dp gradient sync for explicit-collective sites (pipeline
    schedules' ``batch_axis`` grad accumulation, spmd-region helpers):
    qpsum when the tier engages and the tensor passes the gates, plain
    ``lax.psum`` otherwise."""
    from jax import lax

    decision = quantize_decision(x, is_sum=True, axes=(axis_name,),
                                 explicit=explicit, axis_size=axis_size)
    if not decision.quantize:
        return lax.psum(x, axis_name)
    return qpsum_lax(x, axis_name, axis_size, decision.block)


# ------------------------------------------------------- TrainStep facing
def gspmd_sync_axis(axis: str = "dp") -> Optional[tuple]:
    """(mesh, axis, size) when the GSPMD quantized dp sync should engage
    for the current process: the tier is on, a mesh has been installed
    (never build one as a side effect of a train step) and the dp axis
    is real. None disengages the stage."""
    if engaged_comm_dtype() != "int8":
        return None
    from .. import env as env_mod

    mesh = env_mod.instance().mesh
    if mesh is None:
        return None
    n = int(dict(mesh.shape).get(axis, 1))
    if n <= 1:
        return None
    return mesh, axis, n


def sync_gspmd_grads(params, mesh, axis: str, block: Optional[int] = None):
    """Route every eligible parameter gradient through the GSPMD
    quantized sync tier (TrainStep's dp grad-sync stage; runs inside the
    whole-step trace, between backward and the optimizer update).
    Returns the number of grads that took the quantized route."""
    import jax.numpy as jnp

    min_bytes = int(_flag("comm_quantize_min_bytes", 2048))
    n = int(dict(mesh.shape).get(axis, 1))
    synced = 0
    for p in params:
        g = getattr(p, "_grad", None)
        if g is None:
            continue
        val = g._value
        dtype = getattr(val, "dtype", None)
        if dtype is None or not jnp.issubdtype(dtype, jnp.floating):
            continue
        nbytes = val.size * int(getattr(dtype, "itemsize", 4))
        if 0 < min_bytes > nbytes:
            continue
        g._replace_value(dp_sync_gspmd(val, mesh, axis, block))
        synced += 1
        row = tensor_wire_bytes(int(val.size),
                                int(getattr(dtype, "itemsize", 4)), n)
        # the GSPMD tier quantizes the gather half only: fp32
        # reduce-scatter + int8 all-gather
        _tick("qpsum_bytes_dense", row["dense_bytes"])
        _tick("qpsum_bytes_wire",
              row["dense_bytes"] / 2.0 + row["wire_bytes"] / 2.0)
    if synced:
        _note_wire_dtype(axis, "int8")
        _tick("qpsum_calls", synced)
    return synced
