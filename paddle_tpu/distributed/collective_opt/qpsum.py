"""qpsum — blockwise-int8 quantized allreduce (EQuARX-style tier).

The dp gradient allreduce is the biggest single line in a data-parallel
step's bandwidth bill, and gradients tolerate low-precision *transport*
far better than low-precision *math*. This module moves the sync payload
as int8 blocks + one fp32 scale per block while every reduction stays in
fp32:

wire path (:func:`qpsum_lax`, usable inside any shard_map/pmap region
over a named axis of static size ``n``):

1. pad the flat tensor to ``n·block`` granularity and split it into
   ``n`` equal chunks of whole blocks;
2. quantize each chunk blockwise: ``scale = max|x|/127`` per block,
   ``q = round(x/scale)`` int8 (zero blocks take scale 1 so 0 -> 0);
3. ``all_to_all`` the int8 chunks + fp32 scales — replica ``j`` receives
   every replica's chunk ``j``;
4. dequantize and sum the ``n`` received chunks in fp32, **in replica
   index order** (a fixed array-axis reduction, not an arrival race);
5. requantize the reduced chunk with fresh scales and ``all_gather``
   int8 chunks + scales;
6. dequantize the gathered wire data into the full result.

Per-device wire bytes: ``2(n-1)·(chunk + 4·chunk/block)`` vs the fp32
ring's ``2(n-1)/n · nbytes`` — a ~``4/(1+4/block)``x payload cut
(3.94x at block=256). Every replica dequantizes the *same* gathered
bytes through the same program, so results are replica-identical, and
nothing depends on run order or wall clock, so two identical runs are
bit-identical (:func:`qpsum_reference` replays the exact math over a
stacked replica axis — the single-device oracle the tests and the lint
demo compare against).

GSPMD tier (:func:`dp_sync_gspmd`, used by ``TrainStep``'s dp grad-sync
stage): under single-controller whole-step jit the dp psum is implicit
in XLA's partitioning, so the quantized tier is expressed as sharding
constraints — partial grads reduce-scatter (fp32, XLA-inserted) onto the
dp axis, the *shard* is quantized locally, and int8 blocks + scales
all-gather back to replicated. Only the gather half rides the quantized
wire there (~1.6x payload cut); the full 4x needs the explicit-collective
paths (dist.spmd / pipeline schedules / communication.all_reduce).
"""
from __future__ import annotations

import math
from typing import Optional

__all__ = [
    "quantize_blockwise", "dequantize_blockwise", "qpsum_lax",
    "qpsum_reference", "dp_sync_gspmd", "wire_report", "tensor_wire_bytes",
]


def _flag(name, default):
    try:
        from ...base.flags import get_flag

        return get_flag(name)
    except Exception:
        return default


def _block_size(block: Optional[int]) -> int:
    b = block if block is not None else int(_flag("comm_quantize_block", 256))
    return max(int(b), 8)


# --------------------------------------------------------------- quantize
def quantize_blockwise(flat, block: int):
    """Blockwise symmetric int8 quantization of a flat fp array whose
    length is a multiple of ``block``. Returns ``(q int8 [nb, block],
    scales fp32 [nb])``; all-zero blocks take scale 1 so they round-trip
    exactly. Deterministic: scale math and rounding are pure elementwise
    XLA ops."""
    import jax.numpy as jnp

    x = flat.astype(jnp.float32).reshape(-1, block)
    amax = jnp.max(jnp.abs(x), axis=-1)
    scales = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(x / scales[:, None]), -127, 127).astype(jnp.int8)
    return q, scales


def dequantize_blockwise(q, scales):
    """Inverse of :func:`quantize_blockwise` (fp32, flat)."""
    import jax.numpy as jnp

    return (q.astype(jnp.float32) * scales[..., None]).reshape(-1)


def _chunk_blocks(numel: int, n: int, block: int) -> int:
    """Blocks per replica chunk so n·chunk covers the flat tensor."""
    return max(int(math.ceil(numel / float(n * block))), 1)


# --------------------------------------------------------------- wire path
def qpsum_lax(x, axis_name: str, axis_size: int, block: Optional[int] = None):
    """Quantized psum over one named mesh axis — the explicit wire path
    for shard_map/pmap regions. ``axis_size`` must be the static size of
    ``axis_name`` (mesh axes are a runtime property inside the trace).
    Result dtype follows the input; all arithmetic is fp32."""
    import jax.numpy as jnp
    from jax import lax

    n = int(axis_size)
    if n <= 1:
        return x
    block = _block_size(block)
    shape, dtype = x.shape, x.dtype
    numel = 1
    for d in shape:
        numel *= int(d)
    cb = _chunk_blocks(numel, n, block)
    chunk = cb * block

    flat = jnp.ravel(x).astype(jnp.float32)
    flat = jnp.pad(flat, (0, n * chunk - numel))
    q, s = quantize_blockwise(flat, block)          # (n*cb, block), (n*cb)
    q = q.reshape(n, cb, block)
    s = s.reshape(n, cb)

    # replica j ends up holding every replica's chunk j (+ its scales)
    q_recv = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                            tiled=False)
    s_recv = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0,
                            tiled=False)
    # fp32 reduce in replica-index order: a fixed array-axis sum, so the
    # result is bit-stable run to run and identical on every replica
    part = jnp.sum(q_recv.astype(jnp.float32) * s_recv[..., None], axis=0)

    q2, s2 = quantize_blockwise(part.reshape(-1), block)   # (cb, block), (cb)
    q_full = lax.all_gather(q2, axis_name, axis=0, tiled=False)  # (n, cb, blk)
    s_full = lax.all_gather(s2, axis_name, axis=0, tiled=False)  # (n, cb)
    out = dequantize_blockwise(q_full, s_full)[:numel].reshape(shape)
    return out.astype(dtype)


def qpsum_reference(stacked, block: Optional[int] = None):
    """The exact :func:`qpsum_lax` math replayed over a stacked replica
    axis (``stacked`` is ``[n, ...]`` — replica r's local tensor at
    ``stacked[r]``) with the collectives replaced by array indexing.
    Single-device oracle: used by tests, the lint demo and the bench when
    no multi-device mesh exists. Returns the (replica-identical) summed
    tensor of shape ``stacked.shape[1:]``."""
    import jax.numpy as jnp

    n = int(stacked.shape[0])
    block = _block_size(block)
    shape = stacked.shape[1:]
    numel = 1
    for d in shape:
        numel *= int(d)
    if n <= 1:
        return stacked.reshape(shape)
    cb = _chunk_blocks(numel, n, block)
    chunk = cb * block

    flats = stacked.reshape(n, -1).astype(jnp.float32)
    flats = jnp.pad(flats, ((0, 0), (0, n * chunk - numel)))
    q, s = quantize_blockwise(flats.reshape(-1), block)
    q = q.reshape(n, n, cb, block)     # [replica r, chunk j, ...]
    s = s.reshape(n, n, cb)

    # "all_to_all": chunk j gathered across replicas = q[:, j]
    part = jnp.sum(q.astype(jnp.float32) * s[..., None], axis=0)  # (n, cb, blk)
    q2, s2 = quantize_blockwise(part.reshape(-1), block)
    q2 = q2.reshape(n, cb, block)
    s2 = s2.reshape(n, cb)
    # "all_gather" is a no-op here: every chunk is already present
    out = dequantize_blockwise(q2, s2)[:numel].reshape(shape)
    out = out.astype(stacked.dtype)
    # NaN/Inf + range sentinel on the dequantized sum (one bool read
    # when the numerics witness is dark; skipped under a trace)
    from ...observability import numerics

    numerics.watch("comm.qpsum", out)
    return out


# --------------------------------------------------------------- GSPMD tier
def dp_sync_gspmd(value, jmesh, axis: str = "dp",
                  block: Optional[int] = None):
    """Quantized dp gradient sync for the single-controller GSPMD path
    (TrainStep): the partial grad reduce-scatters onto the dp axis (fp32,
    XLA-inserted by the sharding constraint), each device quantizes its
    *shard* blockwise, and int8 blocks + fp32 scales all-gather back to
    replicated. Replica-identical (everyone dequantizes the same gathered
    bytes); only the gather half rides the quantized wire."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = int(dict(jmesh.shape).get(axis, 1))
    if n <= 1:
        return value
    block = _block_size(block)
    shape, dtype = value.shape, value.dtype
    numel = 1
    for d in shape:
        numel *= int(d)
    cb = _chunk_blocks(numel, n, block)
    chunk = cb * block

    flat = jnp.ravel(value).astype(jnp.float32)
    flat = jnp.pad(flat, (0, n * chunk - numel)).reshape(n, cb, block)
    # partial -> shard: GSPMD lowers this constraint to a reduce-scatter
    # (or all-reduce+slice on backends without it) — the fp32 half
    shard = jax.lax.with_sharding_constraint(
        flat, NamedSharding(jmesh, P(axis)))
    amax = jnp.max(jnp.abs(shard), axis=-1)
    scales = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(shard / scales[..., None]), -127, 127
                 ).astype(jnp.int8)
    # shard -> replicated: the all-gather half moves int8 + scales
    q = jax.lax.with_sharding_constraint(q, NamedSharding(jmesh, P()))
    scales = jax.lax.with_sharding_constraint(
        scales, NamedSharding(jmesh, P()))
    out = (q.astype(jnp.float32) * scales[..., None]).reshape(-1)
    out = out[:numel].reshape(shape).astype(dtype)
    # inside a compiled TrainStep this is a tracer and the witness skips
    # it; the site still observes eager/oracle-driven syncs when lit
    from ...observability import numerics

    numerics.watch("comm.dp_sync", out)
    return out


# --------------------------------------------------------------- accounting
def tensor_wire_bytes(numel: int, itemsize: int, axis_size: int,
                      block: Optional[int] = None) -> dict:
    """Per-device payload bytes of one allreduce of ``numel`` elements
    over ``axis_size`` replicas: the dense ring (``2(n-1)/n·nbytes``) vs
    the quantized wire (int8 chunks + fp32 scales through the
    all_to_all + all_gather pair). Pure arithmetic — shared by the
    telemetry counters, the bench and the cost-model cross-check."""
    n = max(int(axis_size), 1)
    block = _block_size(block)
    dense = 2.0 * (n - 1) / n * numel * itemsize
    if n <= 1:
        return {"dense_bytes": 0.0, "wire_bytes": 0.0}
    cb = _chunk_blocks(numel, n, block)
    chunk = cb * block
    wire = 2.0 * (n - 1) * (chunk * 1 + cb * 4)
    return {"dense_bytes": dense, "wire_bytes": wire}


def wire_report(specs, axis_size: int, block: Optional[int] = None,
                min_bytes: Optional[int] = None) -> dict:
    """Aggregate payload accounting over a list of ``(numel, itemsize,
    is_float)`` specs (e.g. one per gradient tensor): dense ring bytes vs
    the bytes the tiered sync actually moves (quantized wire for eligible
    tensors, dense for the min-bytes / non-float fallbacks)."""
    if min_bytes is None:
        min_bytes = int(_flag("comm_quantize_min_bytes", 2048))
    total_dense = total_tiered = 0.0
    n_quantized = n_fallback = 0
    for numel, itemsize, is_float in specs:
        row = tensor_wire_bytes(numel, itemsize, axis_size, block)
        total_dense += row["dense_bytes"]
        eligible = is_float and (min_bytes <= 0
                                 or numel * itemsize >= min_bytes)
        if eligible:
            total_tiered += row["wire_bytes"]
            n_quantized += 1
        else:
            total_tiered += row["dense_bytes"]
            n_fallback += 1
    return {
        "dense_bytes": total_dense,
        "wire_bytes": total_tiered,
        "saved_ratio": (total_dense / total_tiered) if total_tiered else 1.0,
        "n_quantized": n_quantized,
        "n_fallback": n_fallback,
        "axis_size": int(axis_size),
        "block": _block_size(block),
        "min_bytes": int(min_bytes),
    }
