"""Portable resharding — placement transitions as composed collectives.

``auto_parallel.api.reshard`` used to materialize every placement change
as one sharding-changing ``device_put`` and let XLA pick the movement;
for the common transitions that lowering is gather-shaped: the full
array materializes per device before the target layout is sliced back
out (arxiv 2112.01075's motivating failure). This module rewrites the
supported transitions as explicit collective sequences that keep peak
per-device residency at O(shard):

=============  ==========================  ==========================
transition     route                       per-device comm / peak
=============  ==========================  ==========================
s_to_s (i→j)   one tiled ``all_to_all``    (n-1)/n · shard  /  2·shard
r_to_s         local ``dynamic_slice``     0  /  input + shard
s_to_r         one tiled ``all_gather``    (n-1)/n · full  /  full
p_to_s (lax)   ``psum_scatter``            (n-1)/n · full  /  shard
p_to_r (lax)   ``psum``                    2(n-1)/n · full /  full
=============  ==========================  ==========================

:func:`plan_route` is the pure planner: it inspects (src placements,
dst placements, mesh, shape) and returns a :class:`ReshardRoute` with
the chosen kind plus predicted comm volume and peak residency for BOTH
the portable route and the legacy gather path — the numbers
``planner.estimate_step_cost`` and the bench rank strategies on.
:func:`apply_route` executes it through one shard_map program (memoized
per signature). Unsupported transitions (multi-dim changes, indivisible
shards, Partial sources at the eager api tier) fall back to the legacy
path with the reason recorded — ``FLAGS_comm_portable_reshard=0``
forces the legacy path for everything. The partial→shard /
partial→replicate kernels are exposed at the lax tier
(:func:`partial_to_shard`, :func:`partial_to_replicate`) for
spmd-region code, where partial values actually exist per device.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

__all__ = [
    "ReshardRoute", "plan_route", "apply_route",
    "partial_to_shard", "partial_to_replicate",
]


@dataclasses.dataclass
class ReshardRoute:
    """One planned placement transition (see module docstring)."""

    kind: str                      # noop|slice|all_gather|all_to_all|fallback
    reason: str = ""               # fallback reason, "" otherwise
    axis: str = ""                 # mesh axis the transition moves over
    axis_size: int = 1
    src_dim: int = -1              # tensor dim sharded at the source
    dst_dim: int = -1              # tensor dim sharded at the target
    comm_bytes_new: float = 0.0    # per-device, portable route
    comm_bytes_old: float = 0.0    # per-device, legacy gather path
    peak_bytes_new: float = 0.0    # per-device residency, portable route
    peak_bytes_old: float = 0.0

    @property
    def supported(self) -> bool:
        return self.kind not in ("fallback",)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _numel(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def plan_route(src_placements: Sequence, dst_placements: Sequence,
               mesh, shape, itemsize: int = 4) -> ReshardRoute:
    """Plan one placement transition on ``mesh`` (a ProcessMesh or any
    object with ``dim_names`` and per-axis sizes via ``shape``/
    ``get_dim_size``). Pure — no jax calls, safe in the planner."""
    from ..auto_parallel.placement_type import Partial, Replicate, Shard

    dim_names = list(getattr(mesh, "dim_names",
                             getattr(mesh, "axis_names", ())))
    full = float(_numel(shape) * itemsize)
    mesh_shape = mesh.shape  # list (ProcessMesh) or name->size (jax Mesh)
    sizes = ([mesh_shape[n] for n in dim_names]
             if isinstance(mesh_shape, dict) else list(mesh_shape))

    def axis_len(idx):
        return int(sizes[idx])

    diffs = [i for i, (s, d) in enumerate(zip(src_placements, dst_placements))
             if s != d]
    if not diffs:
        return ReshardRoute("noop")
    if len(diffs) > 1:
        return ReshardRoute("fallback", reason="multi_dim_transition")
    md = diffs[0]
    src, dst = src_placements[md], dst_placements[md]
    ax = dim_names[md] if md < len(dim_names) else str(md)
    n = axis_len(md)
    if n <= 1:
        return ReshardRoute("noop", axis=ax, axis_size=n)
    shard = full / n
    if isinstance(src, Partial):
        return ReshardRoute("fallback", reason="partial_source", axis=ax,
                            axis_size=n)
    if isinstance(dst, Partial):
        return ReshardRoute("fallback", reason="partial_target", axis=ax,
                            axis_size=n)
    ring = (n - 1) / n

    if isinstance(src, Replicate) and isinstance(dst, Shard):
        d = dst.get_dim()
        if int(shape[d]) % n != 0:
            return ReshardRoute("fallback", reason="indivisible_dim",
                                axis=ax, axis_size=n)
        return ReshardRoute(
            "slice", axis=ax, axis_size=n, dst_dim=d,
            comm_bytes_new=0.0, comm_bytes_old=0.0,
            peak_bytes_new=full + shard, peak_bytes_old=full + shard)
    if isinstance(src, Shard) and isinstance(dst, Replicate):
        i = src.get_dim()
        return ReshardRoute(
            "all_gather", axis=ax, axis_size=n, src_dim=i,
            comm_bytes_new=ring * full, comm_bytes_old=ring * full,
            peak_bytes_new=shard + full, peak_bytes_old=shard + full)
    if isinstance(src, Shard) and isinstance(dst, Shard):
        i, j = src.get_dim(), dst.get_dim()
        if i == j:
            return ReshardRoute("noop", axis=ax, axis_size=n)
        if int(shape[i]) % n != 0 or int(shape[j]) % n != 0:
            return ReshardRoute("fallback", reason="indivisible_dim",
                                axis=ax, axis_size=n)
        # portable: one tiled all_to_all over O(shard) blocks; legacy:
        # the gather path materializes the full array per device first
        return ReshardRoute(
            "all_to_all", axis=ax, axis_size=n, src_dim=i, dst_dim=j,
            comm_bytes_new=ring * shard, comm_bytes_old=ring * full,
            peak_bytes_new=2.0 * shard, peak_bytes_old=full + shard)
    return ReshardRoute("fallback", reason="unsupported_transition",
                        axis=ax, axis_size=n)


# ------------------------------------------------------------------ apply
_PROGRAMS: dict = {}
_PROGRAMS_MAX = 128


def _route_program(route: ReshardRoute, jmesh, src_spec, dst_spec,
                   shape, dtype):
    """Build (memoized) the jitted shard_map program for one route
    signature."""
    import jax
    from jax import lax

    from ...base.jax_compat import shard_map

    try:
        key = (route.kind, route.axis, route.src_dim, route.dst_dim,
               jmesh, src_spec, dst_spec, tuple(shape), str(dtype))
        cached = _PROGRAMS.get(key)
    except TypeError:  # unhashable mesh/spec: build uncached
        key, cached = None, None
    if cached is not None:
        return cached

    ax, n = route.axis, route.axis_size

    if route.kind == "slice":
        d, chunk = route.dst_dim, int(shape[route.dst_dim]) // n

        def body(x):
            idx = lax.axis_index(ax)
            return lax.dynamic_slice_in_dim(x, idx * chunk, chunk, axis=d)
    elif route.kind == "all_gather":

        def body(x):
            return lax.all_gather(x, ax, axis=route.src_dim, tiled=True)
    elif route.kind == "all_to_all":

        def body(x):
            return lax.all_to_all(x, ax, split_axis=route.dst_dim,
                                  concat_axis=route.src_dim, tiled=True)
    else:  # pragma: no cover - planner never hands these to apply
        raise ValueError(f"route kind {route.kind!r} has no program")

    prog = jax.jit(shard_map(body, mesh=jmesh, in_specs=src_spec,
                             out_specs=dst_spec, check_vma=False))
    if key is not None:
        _PROGRAMS[key] = prog
        while len(_PROGRAMS) > _PROGRAMS_MAX:
            _PROGRAMS.pop(next(iter(_PROGRAMS)))
    return prog


def apply_route(value, jmesh, route: ReshardRoute, src_spec, dst_spec):
    """Execute a planned portable route on one jax array (eager tier).
    ``src_spec``/``dst_spec`` are the PartitionSpecs of the source and
    target placements over ``jmesh``."""
    prog = _route_program(route, jmesh, src_spec, dst_spec,
                          value.shape, value.dtype)
    return prog(value)


# ---------------------------------------------------------------- lax tier
def partial_to_shard(x, axis_name: str, scatter_dim: int = 0):
    """partial → shard inside an spmd region: one ``psum_scatter``
    ((n-1)/n volume) instead of psum + slice (2(n-1)/n + a dead full
    buffer). The caller's local ``x`` holds its partial term."""
    from jax import lax

    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dim,
                            tiled=True)


def partial_to_replicate(x, axis_name: str):
    """partial → replicate inside an spmd region (one psum)."""
    from jax import lax

    return lax.psum(x, axis_name)
