"""PS-mode streaming data pipeline (VERDICT r4 missing #7).

Reference: paddle/fluid/framework/data_feed.cc (MultiSlotDataFeed — the
slot-format text parser) + data_set.cc (InMemoryDataset/QueueDataset — the
file-list driven feeders behind fleet PS training) and their python surface
python/paddle/distributed/fleet/dataset/dataset.py.

TPU-native shape: instead of C++ channel threads pushing LoDTensors into a
scope, the feeders parse the same MultiSlot text format into numpy batches
— sparse slots as padded [batch, max_len] int64 id matrices with a
[batch, max_len] mask (static shapes for XLA; the reference's LoD ragged
rows become pad+mask), dense slots as [batch, dim] float32 — and stream
them through a bounded queue so file IO/parsing overlaps device steps.

MultiSlot text format (one sample per line, reference data_feed.cc):
    <n> v1 ... vn  <m> v1 ... vm  ...     (one group per configured slot)
"""
from __future__ import annotations

import os
import queue
import threading
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np


class Slot:
    """One slot's schema: uint64 sparse ids or float dense values."""

    def __init__(self, name: str, dtype: str = "uint64", dim: int = 1):
        if dtype not in ("uint64", "float"):
            raise ValueError(f"slot dtype {dtype!r} (uint64|float)")
        self.name, self.dtype, self.dim = name, dtype, dim

    @property
    def is_sparse(self) -> bool:
        return self.dtype == "uint64"


def _parse_line(line: str, slots: Sequence[Slot]):
    toks = line.split()
    pos = 0
    out = []
    for slot in slots:
        if pos >= len(toks):
            raise ValueError(f"line ended before slot {slot.name!r}")
        n = int(toks[pos])
        pos += 1
        vals = toks[pos:pos + n]
        if len(vals) != n:
            raise ValueError(f"slot {slot.name!r} declared {n} values, "
                             f"line has {len(vals)}")
        pos += n
        if slot.is_sparse:
            # ids are 64-bit feature hashes: parse the full uint64 range,
            # stored as the bit-equivalent int64 (embedding tables key on
            # the 64-bit pattern; int(v) into int64 would overflow on any
            # hash with the top bit set)
            out.append(np.array([np.uint64(v) for v in vals],
                                np.uint64).view(np.int64))
        else:
            arr = np.array([float(v) for v in vals], np.float32)
            if arr.size != slot.dim:
                raise ValueError(
                    f"dense slot {slot.name!r} expects {slot.dim} values, "
                    f"got {arr.size}")
            out.append(arr)
    return out


def _collate(samples: List[list], slots: Sequence[Slot]) -> Dict[str, object]:
    """Batch per-sample slot values: sparse → (ids [B, L] padded with 0,
    mask [B, L] float32), dense → [B, dim]."""
    batch: Dict[str, object] = {}
    for i, slot in enumerate(slots):
        col = [s[i] for s in samples]
        if slot.is_sparse:
            L = max((len(c) for c in col), default=1) or 1
            ids = np.zeros((len(col), L), np.int64)
            mask = np.zeros((len(col), L), np.float32)
            for r, c in enumerate(col):
                ids[r, : len(c)] = c
                mask[r, : len(c)] = 1.0
            batch[slot.name] = (ids, mask)
        else:
            batch[slot.name] = np.stack(col)
    return batch


class DatasetBase:
    """Shared surface of InMemoryDataset/QueueDataset (reference
    dataset.py::DatasetBase): slot schema + file list + batch size."""

    def __init__(self):
        self.slots: List[Slot] = []
        self.filelist: List[str] = []
        self.batch_size = 1
        self.drop_last = False

    def init(self, batch_size: int = 1, use_var: Optional[Sequence] = None,
             **kwargs):
        self.batch_size = int(batch_size)
        return self

    def set_use_slots(self, slots: Sequence[Slot]):
        self.slots = list(slots)

    def set_filelist(self, filelist: Sequence[str]):
        missing = [f for f in filelist if not os.path.exists(f)]
        if missing:
            raise FileNotFoundError(f"dataset files missing: {missing}")
        self.filelist = list(filelist)

    def _read_samples(self) -> Iterator[list]:
        for path in self.filelist:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        yield _parse_line(line, self.slots)


class InMemoryDataset(DatasetBase):
    """reference dataset.py::InMemoryDataset — load_into_memory +
    local_shuffle, then batched iteration."""

    def __init__(self):
        super().__init__()
        self._samples: List[list] = []

    def load_into_memory(self):
        self._samples = list(self._read_samples())

    def get_memory_data_size(self) -> int:
        return len(self._samples)

    def local_shuffle(self, seed: Optional[int] = None):
        rs = np.random.RandomState(seed)
        rs.shuffle(self._samples)

    def release_memory(self):
        self._samples = []

    def __iter__(self):
        for i in range(0, len(self._samples), self.batch_size):
            chunk = self._samples[i:i + self.batch_size]
            if self.drop_last and len(chunk) < self.batch_size:
                return
            yield _collate(chunk, self.slots)


class QueueDataset(DatasetBase):
    """reference dataset.py::QueueDataset — streaming: a reader thread
    parses the file list into a bounded queue while training consumes, so
    host parsing overlaps device steps (the data_feed.cc channel, one
    python thread instead of C++ readers)."""

    def __init__(self, queue_capacity: int = 16):
        super().__init__()
        self.capacity = queue_capacity

    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self.capacity)
        DONE = object()
        err: List[BaseException] = []
        stop = threading.Event()

        def put(item) -> bool:
            # bounded put that stays responsive to consumer shutdown — a
            # plain q.put would block forever if the consumer stopped
            # iterating with the queue full (leaked thread + open file)
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def reader():
            try:
                chunk: List[list] = []
                for sample in self._read_samples():
                    chunk.append(sample)
                    if len(chunk) == self.batch_size:
                        if not put(_collate(chunk, self.slots)):
                            return
                        chunk = []
                if chunk and not self.drop_last:
                    put(_collate(chunk, self.slots))
            except BaseException as e:  # surfaced on the consumer side
                err.append(e)
            finally:
                put(DONE)

        th = threading.Thread(target=reader, daemon=True)
        th.start()
        try:
            while True:
                item = q.get()
                if item is DONE:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            stop.set()
            th.join()


def embedding_lookup(ps_embedding, ids: np.ndarray, mask: np.ndarray,
                     combiner: str = "sum"):
    """Pull a padded sparse slot through a PS SparseEmbedding and combine
    per sample (reference: the pull_sparse + sequence-pool the PS feeder
    drives): [B, L] ids + mask → [B, dim]."""
    import paddle_tpu as paddle

    B, L = ids.shape
    flat = ps_embedding(paddle.to_tensor(ids.reshape(-1)))
    dim = flat.shape[-1]
    vecs = flat.reshape([B, L, dim])
    m = paddle.to_tensor(mask.reshape(B, L, 1))
    summed = paddle.sum(vecs * m, axis=1)
    if combiner == "sum":
        return summed
    if combiner == "mean":
        denom = paddle.clip(paddle.to_tensor(
            mask.sum(-1, keepdims=True).astype(np.float32)), min=1.0)
        return summed / denom
    raise ValueError(f"combiner {combiner!r}")
