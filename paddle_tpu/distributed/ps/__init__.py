"""Parameter-server tier: host-RAM sparse embedding service.

Reference: paddle/fluid/distributed/ps/ (35k C++ — brpc client/server,
memory/ssd hash tables with accessors and optimizers-on-table) plus the
python wiring in the_one_ps.py. SURVEY §7 scoped the TPU rebuild to "a
CPU-host embedding service": dense compute belongs on the chip, while the
recommendation-style workloads the reference PS exists for keep their
huge sparse tables in host RAM.

This module delivers that scope as real code (VERDICT r3 #7):

- ``SparseTable``  — id-hashed rows (arbitrary int64 ids, lazily
  initialized like the reference memory sparse table) with
  optimizer-on-table updates (sgd / adagrad / adam accessors).
- ``PsServer``     — hosts the shard ``id % num_servers``; serves
  pull/push/save/load/stat over the native TCPStore transport (the same
  server that backs rendezvous, elastic and rpc — no second RPC stack).
- ``PsClient``     — scatters requests to shards, reassembles rows.
- ``SparseEmbedding`` — an nn.Layer whose forward pulls rows and whose
  backward pushes aggregated gradients to the service, so an embedding
  model trains against the PS exactly like the reference's
  ``fluid.layers.embedding(..., is_sparse=True)`` path.
"""
from __future__ import annotations

import pickle
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ...base.log import get_logger
from ...observability.locks import named_lock


class TableOptimizer:
    """Optimizer-on-table accessors (reference ps/table/sparse_sgd_rule.cc
    family): each update touches only the pushed rows."""

    def __init__(self, kind: str = "sgd", lr: float = 0.1, beta1: float = 0.9,
                 beta2: float = 0.999, eps: float = 1e-8):
        if kind not in ("sgd", "adagrad", "adam"):
            raise ValueError(f"unknown table optimizer {kind!r}")
        self.kind, self.lr = kind, lr
        self.beta1, self.beta2, self.eps = beta1, beta2, eps

    def slots(self, dim: int) -> Dict[str, np.ndarray]:
        if self.kind == "adagrad":
            return {"g2": np.zeros(dim, np.float32)}
        if self.kind == "adam":
            return {"m": np.zeros(dim, np.float32),
                    "v": np.zeros(dim, np.float32),
                    "t": np.zeros(1, np.float32)}
        return {}

    def apply(self, row: np.ndarray, grad: np.ndarray,
              slots: Dict[str, np.ndarray]) -> None:
        if self.kind == "sgd":
            row -= self.lr * grad
        elif self.kind == "adagrad":
            slots["g2"] += grad * grad
            row -= self.lr * grad / (np.sqrt(slots["g2"]) + self.eps)
        else:  # adam
            slots["t"][0] += 1.0
            t = slots["t"][0]
            slots["m"][:] = self.beta1 * slots["m"] + (1 - self.beta1) * grad
            slots["v"][:] = self.beta2 * slots["v"] + (1 - self.beta2) * grad * grad
            mhat = slots["m"] / (1 - self.beta1 ** t)
            vhat = slots["v"] / (1 - self.beta2 ** t)
            row -= self.lr * mhat / (np.sqrt(vhat) + self.eps)


class SparseTable:
    """One shard of a sparse embedding table: dict of int64 id → row."""

    def __init__(self, dim: int, optimizer: Optional[TableOptimizer] = None,
                 init_std: float = 0.01, seed: int = 0):
        self.dim = int(dim)
        self.opt = optimizer or TableOptimizer()
        self.init_std = init_std
        self._rs = np.random.RandomState(seed)
        self.rows: Dict[int, np.ndarray] = {}
        self.slots: Dict[int, Dict[str, np.ndarray]] = {}
        self._lock = named_lock("distributed.ps")

    def _row(self, i: int) -> np.ndarray:
        r = self.rows.get(i)
        if r is None:
            r = (self._rs.randn(self.dim) * self.init_std).astype(np.float32)
            self.rows[i] = r
            self.slots[i] = self.opt.slots(self.dim)
        return r

    def pull(self, ids: np.ndarray) -> np.ndarray:
        with self._lock:
            return np.stack([self._row(int(i)) for i in ids]) if len(ids) \
                else np.zeros((0, self.dim), np.float32)

    def push(self, ids: np.ndarray, grads: np.ndarray) -> None:
        """Aggregate duplicate ids then apply the table optimizer once per
        unique id (the reference accessor contract)."""
        with self._lock:
            uniq, inv = np.unique(ids, return_inverse=True)
            agg = np.zeros((len(uniq), self.dim), np.float32)
            np.add.at(agg, inv, grads)
            for j, i in enumerate(uniq):
                i = int(i)
                self.opt.apply(self._row(i), agg[j], self.slots[i])

    def state_dict(self) -> dict:
        with self._lock:
            return {"dim": self.dim, "rows": dict(self.rows),
                    "slots": dict(self.slots)}

    def load_state_dict(self, state: dict) -> None:
        with self._lock:
            self.rows = dict(state["rows"])
            self.slots = dict(state["slots"])

    def __len__(self):
        return len(self.rows)


class _Channel:
    """Request/response message channel over the native TCPStore (mirrors
    distributed.rpc's inbox/seq/result key scheme, under a ps/ prefix)."""

    def __init__(self, endpoint: str, is_master: bool):
        from ...native import TCPStore

        host, _, port = endpoint.rpartition(":")
        self.store = TCPStore(host or "127.0.0.1", int(port),
                              is_master=is_master, world_size=1)

    def post(self, shard: int, payload: dict) -> str:
        import uuid

        req_id = uuid.uuid4().hex
        payload = dict(payload, id=req_id)
        seq = self.store.add(f"ps/seq/{shard}", 1) - 1
        self.store.set(f"ps/inbox/{shard}/{seq}", pickle.dumps(payload))
        return req_id

    def result(self, req_id: str, timeout: float = 60.0):
        raw = self.store.get(f"ps/result/{req_id}", timeout=timeout)
        status, value = pickle.loads(raw)
        if status == "err":
            raise RuntimeError(f"ps server error: {value}")
        return value

    def close(self):
        self.store.close()


class PsServer:
    """One PS shard process/thread (reference brpc_ps_server.cc analog)."""

    def __init__(self, server_id: int, num_servers: int, endpoint: str,
                 is_master: Optional[bool] = None):
        self.server_id = int(server_id)
        self.num_servers = int(num_servers)
        self.tables: Dict[str, SparseTable] = {}
        self._stop = threading.Event()
        self._chan = _Channel(endpoint,
                              is_master=(server_id == 0 if is_master is None
                                         else is_master))
        self._thread: Optional[threading.Thread] = None

    def create_table(self, name: str, dim: int, optimizer: str = "sgd",
                     lr: float = 0.1, seed: int = 0) -> None:
        self.tables[name] = SparseTable(
            dim, TableOptimizer(optimizer, lr=lr), seed=seed + self.server_id)

    def _handle(self, req: dict):
        op = req["op"]
        if op == "pull":
            return self.tables[req["table"]].pull(req["ids"])
        if op == "push":
            self.tables[req["table"]].push(req["ids"], req["grads"])
            return True
        if op == "create":
            self.create_table(req["table"], req["dim"], req["optimizer"],
                              req["lr"], req.get("seed", 0))
            return True
        if op == "save":
            return {n: t.state_dict() for n, t in self.tables.items()}
        if op == "load":
            for n, state in req["state"].items():
                if n not in self.tables:
                    self.tables[n] = SparseTable(state["dim"])
                self.tables[n].load_state_dict(state)
            return True
        if op == "stat":
            return {n: len(t) for n, t in self.tables.items()}
        if op == "stop":
            self._stop.set()
            return True
        raise ValueError(f"unknown ps op {op!r}")

    def _serve(self):
        seq = 0
        while not self._stop.is_set():
            key = f"ps/inbox/{self.server_id}/{seq}"
            try:
                raw = self._chan.store.get(key, timeout=0.5)
            except Exception:
                continue
            seq += 1
            try:
                req = pickle.loads(raw)
                try:
                    result = ("ok", self._handle(req))
                except Exception as e:
                    result = ("err", repr(e))
                self._chan.store.set(f"ps/result/{req['id']}",
                                     pickle.dumps(result))
            except Exception as e:
                get_logger().warning("ps server %d error: %s", self.server_id, e)

    def start(self) -> "PsServer":
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        return self

    def run(self):
        """Blocking serve loop (for dedicated server processes)."""
        self._serve()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._chan.close()


class PsClient:
    """Shard-aware client (reference brpc_ps_client.cc analog): ids hash to
    shard ``id % num_servers``; pull reassembles rows in request order."""

    def __init__(self, num_servers: int, endpoint: str):
        self.num_servers = int(num_servers)
        self._chan = _Channel(endpoint, is_master=False)

    def create_table(self, name: str, dim: int, optimizer: str = "sgd",
                     lr: float = 0.1, seed: int = 0) -> None:
        reqs = [self._chan.post(s, {"op": "create", "table": name, "dim": dim,
                                    "optimizer": optimizer, "lr": lr,
                                    "seed": seed})
                for s in range(self.num_servers)]
        for r in reqs:
            self._chan.result(r)

    def _shard(self, ids: np.ndarray) -> np.ndarray:
        return ids % self.num_servers

    def pull_sparse(self, table: str, ids) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        shards = self._shard(ids)
        reqs, orders = [], []
        for s in range(self.num_servers):
            sel = np.nonzero(shards == s)[0]
            if len(sel) == 0:
                continue
            reqs.append((self._chan.post(s, {"op": "pull", "table": table,
                                             "ids": ids[sel]}), sel))
        dim = None
        out = None
        for req_id, sel in reqs:
            rows = self._chan.result(req_id)
            if out is None:
                dim = rows.shape[1] if rows.ndim == 2 else 0
                out = np.zeros((len(ids), dim), np.float32)
            out[sel] = rows
        if out is None:
            raise ValueError("pull_sparse with empty ids")
        return out

    def push_sparse(self, table: str, ids, grads) -> None:
        ids = np.asarray(ids, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(ids), -1)
        shards = self._shard(ids)
        reqs = []
        for s in range(self.num_servers):
            sel = np.nonzero(shards == s)[0]
            if len(sel) == 0:
                continue
            reqs.append(self._chan.post(s, {"op": "push", "table": table,
                                            "ids": ids[sel],
                                            "grads": grads[sel]}))
        for r in reqs:
            self._chan.result(r)

    def save(self, table_stats_only: bool = False) -> List[dict]:
        op = "stat" if table_stats_only else "save"
        reqs = [self._chan.post(s, {"op": op}) for s in range(self.num_servers)]
        return [self._chan.result(r) for r in reqs]

    def load(self, states: List[dict]) -> None:
        reqs = [self._chan.post(s, {"op": "load", "state": st})
                for s, st in enumerate(states)]
        for r in reqs:
            self._chan.result(r)

    def stop_servers(self) -> None:
        reqs = [self._chan.post(s, {"op": "stop"})
                for s in range(self.num_servers)]
        for r in reqs:
            try:
                self._chan.result(r, timeout=5.0)
            except Exception:
                pass

    def close(self):
        self._chan.close()


class SparseEmbedding:
    """Embedding layer backed by the PS (reference
    fluid.layers.embedding(is_sparse=True) over the_one_ps): forward pulls
    rows for the batch's ids; backward pushes the aggregated row gradients
    through the table optimizer."""

    def __init__(self, client: PsClient, table: str, dim: int):
        self.client = client
        self.table = table
        self.dim = int(dim)
        self.training = True

    def __call__(self, ids):
        from ...core.tensor import Tensor, unwrap
        from ...autograd.py_layer import PyLayer

        ids_np = np.asarray(unwrap(ids)).astype(np.int64)
        flat = ids_np.reshape(-1)
        rows = self.client.pull_sparse(self.table, flat)
        client, table = self.client, self.table

        class _PsEmbed(PyLayer):
            @staticmethod
            def forward(ctx, rows_t):
                return rows_t.reshape(list(ids_np.shape) + [rows.shape[-1]])

            @staticmethod
            def backward(ctx, grad_out):
                g = np.asarray(unwrap(grad_out)).reshape(len(flat), -1)
                client.push_sparse(table, flat, g)
                return grad_out.reshape([len(flat), g.shape[-1]])

        rows_t = Tensor(rows, stop_gradient=False)
        return _PsEmbed.apply(rows_t)
