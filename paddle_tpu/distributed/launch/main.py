"""Distributed launcher CLI (reference: python/paddle/distributed/launch/
main.py:23 — Context -> collective controller spawning N local procs with
PADDLE_TRAINER_* env; Master KV rendezvous; watcher; elastic relaunch).

TPU-native: one *process per host* (single-controller SPMD drives all local
chips), so `--nproc_per_node` defaults to 1 and exists for CPU-mesh
simulation/testing. Rendezvous is the JAX coordination service — the
launcher only distributes the env contract (PADDLE_MASTER /
PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM) that
`paddle_tpu.distributed.init_parallel_env` feeds to
`jax.distributed.initialize`. `--max_restarts` gives launch-level fault
recovery (the reference's elastic relaunch loop, minus etcd).

Every relaunch (worker restart or elastic re-form) exports
`PADDLE_RESTART_GEN` with the bumped generation; `Model.fit` reads it
(ISSUE 15) so a restarted worker with `snapshot_dir=` armed resumes
from its snapshot cursor automatically — the relaunch path passes
`resume=` through without the training script changing.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="launch a distributed training job",
    )
    p.add_argument("--master", default=None,
                   help="coordinator addr host:port (default: this host)")
    p.add_argument("--nnodes", type=int, default=1, help="number of nodes")
    p.add_argument("--rank", type=int, default=0, help="this node's rank")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per node (1 for TPU SPMD; >1 for CPU-mesh simulation)")
    p.add_argument("--log_dir", default=None, help="per-rank log directory")
    p.add_argument("--max_restarts", type=int, default=0,
                   help="relaunch failed workers up to N times")
    p.add_argument("--devices", default=None, help="visible device selection")
    p.add_argument("--elastic_level", type=int, default=0,
                   help="0: off; >=1: run the Master KV rendezvous + elastic "
                        "manager; worker relaunch is driven by its decisions")
    p.add_argument("--job_id", default="default", help="elastic job id")
    p.add_argument("training_script", help="script to run")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _spawn(args, local_rank: int, generation: int = 0):
    world = args.nnodes * args.nproc_per_node
    rank = args.rank * args.nproc_per_node + local_rank
    env = dict(os.environ)
    master = args.master or "127.0.0.1:49178"
    env.update(
        PADDLE_MASTER=master,
        MASTER_ADDR=master.rsplit(":", 1)[0],
        MASTER_PORT=master.rsplit(":", 1)[1] if ":" in master else "49178",
        PADDLE_TRAINER_ID=str(rank),
        RANK=str(rank),
        PADDLE_TRAINERS_NUM=str(world),
        WORLD_SIZE=str(world),
        PADDLE_LOCAL_RANK=str(local_rank),
        PADDLE_NNODES=str(args.nnodes),
        PADDLE_NODE_RANK=str(args.rank),
        PADDLE_RESTART_GEN=str(generation),
        PADDLE_JOB_ID=str(getattr(args, "job_id", "default")),
    )
    if args.devices:
        env["JAX_VISIBLE_DEVICES"] = args.devices
    cmd = [sys.executable, args.training_script] + list(args.training_script_args)
    stdout = stderr = None
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
        logf = open(os.path.join(args.log_dir, f"worker.{rank}.log"), "ab")
        stdout = stderr = logf
    return subprocess.Popen(cmd, env=env, stdout=stdout, stderr=stderr)


def launch(argv=None) -> int:
    args = _parse_args(argv)
    restarts = {i: 0 for i in range(args.nproc_per_node)}

    # elastic mode: the node launcher joins the Master KV service (rank 0
    # hosts the store one port above the trainer master port) and runs an
    # ElasticManager whose HOLD/RESTART/EXIT decisions drive this loop —
    # the reference's manager→launcher wiring (elastic/manager.py:125)
    master = None
    elastic = None
    generation = 0
    if args.elastic_level > 0:
        from ..fleet.elastic import ElasticManager, ElasticStatus
        from .master import Master

        ep = args.master or "127.0.0.1:49178"
        host, _, port = ep.rpartition(":")
        store_ep = f"{host or '127.0.0.1'}:{int(port) + 1}"
        master = Master(store_ep, args.rank, args.nnodes, job_id=args.job_id)
        master.register(ep, args.nproc_per_node)
        master.sync_peers(timeout=60.0)
        generation = master.generation()
        elastic = ElasticManager(rank=args.rank, world_size=args.nnodes,
                                 store=master.store, job_id=args.job_id)
        elastic.start()

    procs = {i: _spawn(args, i, generation) for i in range(args.nproc_per_node)}

    def _terminate_all():
        for p in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + 10
        for p in procs.values():
            try:
                p.wait(max(deadline - time.time(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()

    def _restart_worker(i, code):
        nonlocal generation
        restarts[i] += 1
        if master is not None:
            generation = master.bump_generation()
        print(f"[launch] worker {i} exited {code}; RESTART "
              f"{restarts[i]}/{args.max_restarts} (gen {generation})",
              file=sys.stderr)
        procs[i] = _spawn(args, i, generation)

    try:
        while True:
            alive = False
            for i, p in list(procs.items()):
                code = p.poll()
                if code is None:
                    alive = True
                elif code != 0:
                    if restarts[i] < args.max_restarts:
                        _restart_worker(i, code)
                        alive = True
                    else:
                        print(f"[launch] worker {i} failed with code {code}; "
                              "terminating job", file=sys.stderr)
                        if elastic is not None:
                            elastic.exit(completed=False)
                        _terminate_all()
                        return code
            # elastic membership scan: a peer NODE going stale is a RESTART
            # decision — re-form the job at a new generation so workers
            # re-rendezvous and resume from the dist checkpoint
            if elastic is not None and alive:
                status = elastic.watch()
                if status == ElasticStatus.RESTART:
                    cur = master.generation()
                    if cur == generation:
                        generation = master.bump_generation()
                    else:
                        generation = cur
                    print(f"[launch] elastic RESTART -> generation "
                          f"{generation}", file=sys.stderr)
                    _terminate_all()
                    procs.update({i: _spawn(args, i, generation)
                                  for i in range(args.nproc_per_node)})
                elif status == ElasticStatus.COMPLETED:
                    pass  # workers will exit 0 on their own
            if not alive:
                if elastic is not None:
                    elastic.exit(completed=True)
                return 0
            time.sleep(0.2)
    except KeyboardInterrupt:
        if elastic is not None:
            elastic.exit(completed=False)
        _terminate_all()
        return 130


if __name__ == "__main__":
    sys.exit(launch())
