"""Master rendezvous service for multi-node launch (reference:
python/paddle/distributed/launch/controllers/master.py:73 HTTPMaster /
:186 ETCDMaster — nodes sync peer lists through a KV service and heartbeat
for elastic membership).

TPU-native: the KV service is the framework's own native TCPStore
(native/tcp_store.cc) — the same store that backs fleet.elastic — so one
socket server covers rendezvous, elastic heartbeats and user KV. The node
with rank 0 hosts it; every node's launcher connects as a client.

Protocol (all keys under ``rdzv/<job>/``):
- ``peers/<rank>``  — node endpoint + nproc, set at register time
- ``joined``        — atomic join counter; ``sync_peers`` blocks until it
                      reaches nnodes, then returns the sorted peer list
- ``gen``           — restart generation; bumped on elastic RESTART so
                      re-joining workers agree on a fresh rendezvous round
"""
from __future__ import annotations

import json
import os
import time
from typing import List, Optional, Tuple

from ...base.log import get_logger
from ...native import TCPStore


class Master:
    """KV rendezvous over the native TCPStore."""

    def __init__(self, endpoint: str, rank: int, nnodes: int,
                 job_id: str = "default", is_master: Optional[bool] = None):
        host, _, port = endpoint.rpartition(":")
        self.endpoint = endpoint
        self.rank = rank
        self.nnodes = nnodes
        self.job_id = job_id
        self.is_master = (rank == 0) if is_master is None else is_master
        self.store = TCPStore(host or "127.0.0.1", int(port),
                              is_master=self.is_master, world_size=nnodes)

    def _k(self, key: str) -> str:
        return f"rdzv/{self.job_id}/{key}"

    # ------------------------------------------------------------ rendezvous
    def register(self, node_endpoint: str, nproc: int) -> None:
        info = json.dumps({"endpoint": node_endpoint, "nproc": nproc,
                           "rank": self.rank})
        self.store.set(self._k(f"peers/{self.rank}"), info)
        self.store.add(self._k("joined"), 1)

    def sync_peers(self, timeout: float = 120.0) -> List[dict]:
        """Block until all nnodes registered; return peers sorted by rank
        (reference master.sync_peers)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.store.add(self._k("joined"), 0) >= self.nnodes:
                peers = []
                for r in range(self.nnodes):
                    raw = self.store.get(self._k(f"peers/{r}"), timeout=10.0)
                    peers.append(json.loads(raw.decode()))
                return sorted(peers, key=lambda p: p["rank"])
            time.sleep(0.2)
        raise TimeoutError(
            f"rendezvous: {self.store.add(self._k('joined'), 0)}/{self.nnodes} "
            f"nodes joined within {timeout}s")

    # ---------------------------------------------------------- generations
    def generation(self) -> int:
        return self.store.add(self._k("gen"), 0)

    def bump_generation(self) -> int:
        """Start a new rendezvous round after an elastic RESTART decision."""
        return self.store.add(self._k("gen"), 1)

    def wait_generation(self, current: int, timeout: float = 60.0) -> int:
        deadline = time.time() + timeout
        while time.time() < deadline:
            g = self.generation()
            if g > current:
                return g
            time.sleep(0.2)
        return current

    # ------------------------------------------------------------------- kv
    def set(self, key: str, value) -> None:
        self.store.set(self._k(key), value)

    def get(self, key: str, timeout: Optional[float] = None) -> bytes:
        return self.store.get(self._k(key), timeout=timeout)

    def add(self, key: str, amount: int = 1) -> int:
        return self.store.add(self._k(key), amount)

    def close(self):
        self.store.close()


def master_from_env(job_id: str = "default") -> Optional[Master]:
    """Build a Master client from the PADDLE_* env contract the launcher
    distributes (PADDLE_MASTER, PADDLE_NNODES, node rank)."""
    endpoint = os.environ.get("PADDLE_MASTER")
    if not endpoint:
        return None
    nnodes = int(os.environ.get("PADDLE_NNODES", "1"))
    rank = int(os.environ.get("PADDLE_NODE_RANK",
                              os.environ.get("PADDLE_TRAINER_ID", "0")))
    return Master(endpoint, rank, nnodes, job_id=job_id)
