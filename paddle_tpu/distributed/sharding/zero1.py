"""ZeRO-1 cross-replica sharded optimizer states and weight update.

Per PAPERS "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (arxiv 2004.13336): in data-parallel training
every replica holds the full optimizer state and repeats the identical
weight update — the states are the largest redundant allocation in the
step. The zero1 strategy shards them across the dp (or dedicated
``sharding``) mesh axis:

1. **reduce-scatter(grads)** — each flattened gradient is padded to
   ``axis_size · block`` granularity and constrained onto the axis, so
   GSPMD lowers the dp partial-sum directly to a reduce-scatter (or
   all-reduce + slice on backends without one — same numerics);
2. **per-shard update** — every replica owns one contiguous
   ``1/axis_size`` slice of the flattened param/moment space; the
   optimizer's own ``_apply_one`` rule runs on flat *shard-space*
   proxies, so every optimizer (SGD/Adam/AdamW/Lamb/...) shards without
   a rewritten update rule, and the moments/master cells persist as
   genuinely sharded arrays (~``1/axis_size`` bytes per device);
3. **all-gather(updated weights)** — the updated shard gathers back to
   the replicated parameter; optionally as int8 blocks + fp32 scales
   (the same blockwise-scale wire math as ``collective_opt.qpsum``'s
   gather half), in which case a persistent fp32 **master shard** keeps
   exact updates (int8 weights would otherwise swallow sub-quantum
   steps in the rounding dead zone).

Engagement (all three key the TrainStep compile cache, so flips
retrace instead of replaying the other tier's program):

- ``group_sharded_parallel(level="os"|"os_g")`` attaches the strategy;
- ``FLAGS_sharding_stage="zero1"`` engages it process-wide;
- ``TrainStep(sharding="zero1")`` / ``sharding="replicated"`` overrides
  both per step program.

The quantized gather tier rides the comm engagement policy
(``FLAGS_comm_quantize_dp_grads`` / ``amp.auto_cast(comm_dtype="int8")``).

Pure accounting (:func:`plan_shards`, :func:`zero1_wire_report`,
:func:`opt_state_report`) is shared by the planner's step-cost pricing,
the QZ804/QZ805 lint gates and ``bench.py extras.zero1``.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional

__all__ = [
    "ShardRow", "plan_shards", "step_spec", "ensure_strategy", "attached",
    "Zero1Strategy", "zero1_wire_report", "opt_state_report",
    "save_sharded_optimizer_state", "load_sharded_optimizer_state",
]


def _flag(name, default):
    try:
        from ...base.flags import get_flag

        return get_flag(name)
    except Exception:
        return default


def _block() -> int:
    return max(int(_flag("comm_quantize_block", 256)), 8)


# ------------------------------------------------------------------ planning
@dataclasses.dataclass
class ShardRow:
    """Shard-space layout of one tensor: flattened, padded to
    ``axis_size · shard_elems`` so each replica owns one contiguous,
    block-aligned slice. ``sharded`` is False when sharding would not
    shrink the per-replica bytes (tiny tensors: one padded block per
    shard would exceed the whole tensor) — those stay on the replicated
    update path."""

    name: str
    numel: int
    itemsize: int = 4
    axis_size: int = 1
    block: int = 256
    sharded: bool = False
    shard_elems: int = 0       # per-replica elements (cb · block)
    padded: int = 0            # axis_size · shard_elems

    @property
    def pad_per_shard(self) -> float:
        """Average padding elements carried per replica shard."""
        return (self.padded - self.numel) / max(self.axis_size, 1)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["pad_per_shard"] = self.pad_per_shard
        return d


def plan_row(name: str, numel: int, itemsize: int, axis_size: int,
             block: Optional[int] = None) -> ShardRow:
    block = block or _block()
    n = max(int(axis_size), 1)
    cb = max(int(math.ceil(numel / float(n * block))), 1)
    shard = cb * block
    # shard only when the per-replica slice is strictly smaller than the
    # whole tensor — otherwise block padding would *grow* per-replica
    # state (QZ805's invariant)
    if n <= 1 or shard >= numel:
        return ShardRow(name, int(numel), int(itemsize), n, block)
    return ShardRow(name, int(numel), int(itemsize), n, block,
                    sharded=True, shard_elems=shard, padded=n * shard)


def plan_shards(specs, axis_size: int,
                block: Optional[int] = None) -> List[ShardRow]:
    """Shard-space plan over ``(name, numel, itemsize)`` specs — pure
    arithmetic, shared by the strategy, the planner pricing, the QZ805
    audit and the bench."""
    return [plan_row(name, numel, itemsize, axis_size, block)
            for name, numel, itemsize in specs]


def zero1_wire_report(specs, axis_size: int, quantize: bool = False,
                      block: Optional[int] = None) -> dict:
    """Per-device wire bytes of one zero1 step over ``(name, numel,
    itemsize)`` specs: the reduce-scatter half (always fp32) plus the
    all-gather half (fp32, or int8 blocks + one fp32 scale per block
    when ``quantize``), against the replicated baseline's all-reduce
    ring (``2(n-1)/n · bytes``). Tensors the plan leaves replicated
    keep their all-reduce cost on both sides."""
    block = block or _block()
    n = max(int(axis_size), 1)
    ring = (n - 1) / n if n > 1 else 0.0
    rs = ag = baseline = 0.0
    n_sharded = 0
    for row in plan_shards(specs, n, block):
        dense = row.numel * row.itemsize
        baseline += 2.0 * ring * dense
        if not row.sharded:
            rs += 2.0 * ring * dense  # stays a plain all-reduce
            continue
        n_sharded += 1
        padded_bytes = row.padded * row.itemsize
        rs += ring * padded_bytes
        if quantize:
            ag += ring * (row.padded * 1 + (row.padded // row.block) * 4)
        else:
            ag += ring * padded_bytes
    return {
        "reduce_scatter_bytes": rs,
        "all_gather_bytes": ag,
        "wire_bytes": rs + ag,
        "allreduce_bytes": baseline,
        "n_sharded": n_sharded,
        "axis_size": n,
        "block": block,
        "quantized_gather": bool(quantize),
    }


# --------------------------------------------------------------- engagement
def step_spec(optimizer, explicit: object = "__unset__"):
    """``(mesh, axis, axis_size)`` when the zero1 sharded update should
    engage for this optimizer's next step, else ``None``. Resolution
    order: explicit per-step override (``TrainStep(sharding=...)`` via
    ``optimizer._sharding_override``) > ``FLAGS_sharding_stage`` >
    a strategy attached by ``group_sharded_parallel``. A mesh must
    already be installed (never built as a side effect of a step) and
    the axis must be real (size > 1)."""
    if explicit == "__unset__":
        explicit = getattr(optimizer, "_sharding_override", None)
    if explicit == "replicated":
        return None
    requested = explicit == "zero1"
    if not requested:
        requested = _flag("sharding_stage", "") == "zero1"
    if not requested:
        st = getattr(optimizer, "_zero1_strategy", None)
        requested = st is not None and st.requested
    if not requested:
        return None
    from .. import env as env_mod

    inst = env_mod.instance()
    mesh = inst.mesh
    if mesh is None:
        return None
    axis = "sharding" if inst.axis_degrees.get("sharding", 1) > 1 else "dp"
    n = int(dict(mesh.shape).get(axis, 1))
    if n <= 1:
        return None
    return mesh, axis, n


def attached(optimizer) -> Optional["Zero1Strategy"]:
    return getattr(optimizer, "_zero1_strategy", None)


def ensure_strategy(optimizer, requested: bool = False) -> "Zero1Strategy":
    """The optimizer's strategy, attached on first use. ``requested``
    marks a deliberate ``group_sharded_parallel`` opt-in (sticky
    engagement); lazily attached strategies engage only while the flag
    or an explicit override asks."""
    st = getattr(optimizer, "_zero1_strategy", None)
    if st is None:
        st = Zero1Strategy(optimizer, requested=requested)
        optimizer._zero1_strategy = st
    elif requested:
        st.requested = True
    return st


# ---------------------------------------------------------------- telemetry
def _tick(name: str, value: float = 1.0, **labels):
    try:
        from ...observability import registry

        registry.counter("comm." + name).inc(value, **labels)
    except Exception:
        pass


# ----------------------------------------------------------------- strategy
class _ShardView:
    """Set lazily to the no-discovery-hook Parameter subclass (avoids a
    module-import cycle with core.tensor)."""


def _shard_view_cls():
    from ...core.tensor import Parameter

    global _ShardView
    if isinstance(_ShardView, type) and issubclass(_ShardView, Parameter):
        return _ShardView

    class ShardView(Parameter):
        """Flat shard-space view of one parameter. Its value is DERIVED
        from the live parameter every step (or aliases the master
        shard), so writes bypass the jit discovery hook — the view must
        not be captured as a state cell of the compiled step."""

        __slots__ = ()

        def _replace_value(self, new_value):
            self._value = new_value

    _ShardView = ShardView
    return ShardView


class Zero1Strategy:
    """Per-optimizer zero1 state: shard plans, shard-space proxies, the
    optional fp32 master shards, and the in-trace update. One strategy
    serves both the eager path (``optimizer.step()``) and the compiled
    ``TrainStep`` program (the same python runs under discovery and
    trace — exactly like the rest of the framework)."""

    def __init__(self, optimizer, requested: bool = False):
        self.optimizer = optimizer
        self.requested = bool(requested)
        self._rows: Dict[int, ShardRow] = {}
        self._proxies: Dict[int, object] = {}
        self._grad_views: Dict[int, object] = {}
        self._masters: Dict[int, object] = {}
        self._acc_wrapped = False

    # ------------------------------------------------------------- layout
    def row(self, p, axis_size: int) -> ShardRow:
        key = id(p)
        row = self._rows.get(key)
        if row is None or row.axis_size != axis_size:
            import numpy as np

            numel = int(np.prod(p._value.shape)) if p._value.shape else 1
            # moments/master update in fp32 regardless of param dtype
            row = plan_row(p.name, numel, 4, axis_size)
            self._rows[key] = row
        return row

    def proxy_for(self, p, row: Optional[ShardRow] = None):
        """The persistent flat shard-space Parameter proxy for ``p`` —
        accumulators are keyed on its id, so it must live as long as
        the strategy."""
        view = self._proxies.get(id(p))
        if view is None:
            import jax.numpy as jnp

            cls = _shard_view_cls()
            view = cls(jnp.zeros((), jnp.float32), name=p.name)
            view.optimize_attr = p.optimize_attr
            view.regularizer = getattr(p, "regularizer", None)
            view.stop_gradient = True
            self._proxies[id(p)] = view
        return view

    def _grad_view(self, p):
        g = self._grad_views.get(id(p))
        if g is None:
            from ...core.tensor import Tensor

            g = Tensor(0.0, stop_gradient=True, name=f"{p.name}_zero1_grad")
            self._grad_views[id(p)] = g
        return g

    def _wrap_accumulators(self, placement):
        """Fresh accumulators created against a shard-space proxy are
        placed sharded from birth (eager path + discovery run), so the
        per-replica bytes drop from the first step — donated through
        the compiled program, they then stay sharded."""
        self._placement = placement
        if self._acc_wrapped:
            return
        self._acc_wrapped = True
        opt = self.optimizer
        orig = opt._get_accumulator
        proxies = self._proxies

        def sharded_get_accumulator(name, param, fill=0.0, dtype=None):
            import jax

            store = opt._accumulators[name]
            fresh = id(param) not in store
            acc = orig(name, param, fill, dtype)
            if (fresh and any(v is param for v in proxies.values())
                    and not isinstance(acc._value, jax.core.Tracer)):
                acc._value = jax.device_put(acc._value, self._placement)
            return acc

        opt._get_accumulator = sharded_get_accumulator

    def prime_proxy(self, p, spec):
        """The cell owner accumulator *priming* should target for ``p``
        (``Optimizer._prime_accumulators`` before the first step — the
        GradScaler snapshot path): the shard-space proxy, pre-shaped to
        its flat padded layout and placed sharded, so primed cells are
        born with the shapes and placement the sharded update will use.
        Unsharded rows prime against the param itself."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh, axis, n = spec
        row = self.row(p, n)
        if not row.sharded:
            return p
        placement = NamedSharding(mesh, P(axis))
        self._wrap_accumulators(placement)
        proxy = self.proxy_for(p, row)
        if tuple(proxy._value.shape) != (row.padded,):
            proxy._value = jax.device_put(
                jnp.zeros((row.padded,), jnp.float32), placement)
        return proxy

    def master_for(self, p, row: ShardRow, placement):
        """The persistent fp32 master shard backing the int8 gather
        tier: exact updates accumulate here; the gathered int8 weights
        are only the forward-pass representation."""
        m = self._masters.get(id(p))
        if m is None:
            import jax
            import jax.numpy as jnp

            from ...core.tensor import Tensor

            flat = jnp.pad(jnp.ravel(p._value).astype(jnp.float32),
                           (0, row.padded - row.numel))
            flat = jax.lax.with_sharding_constraint(flat, placement)
            m = Tensor(flat, stop_gradient=True,
                       name=f"{p.name}_zero1_master")
            self._masters[id(p)] = m
        return m

    # ------------------------------------------------------------- update
    def apply_one(self, opt, p, g, lr, weight_decay, spec):
        """One parameter's sharded update: reduce-scatter the grad,
        run ``opt._apply_one`` in flat shard space, all-gather the
        updated weights (optionally int8-quantized). Falls back to the
        replicated rule for tensors the plan leaves unsharded."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh, axis, n = spec
        row = self.row(p, n)
        if not row.sharded:
            opt._apply_one(p, g, lr, weight_decay)
            return
        from .. import collective_opt as copt

        gather_dtype = copt.engaged_comm_dtype() or "fp32"
        shard_sp = NamedSharding(mesh, P(axis))
        rep_sp = NamedSharding(mesh, P())
        pad = row.padded - row.numel

        # 1. reduce-scatter: the dp-partial grad, flattened + padded,
        # constrained onto the axis — GSPMD emits the reduce-scatter
        gv = jnp.pad(jnp.ravel(g._value).astype(jnp.float32), (0, pad))
        g_view = self._grad_view(p)
        g_view._value = jax.lax.with_sharding_constraint(gv, shard_sp)

        proxy = self.proxy_for(p, row)
        master = None
        if gather_dtype == "int8":
            master = self.master_for(p, row, shard_sp)
            proxy._value = master._value
        else:
            pv = jnp.pad(jnp.ravel(p._value).astype(jnp.float32), (0, pad))
            # replicated param -> owned slice: comm-free under GSPMD
            proxy._value = jax.lax.with_sharding_constraint(pv, shard_sp)

        # 2. the optimizer's own update rule, in flat shard space
        self._wrap_accumulators(shard_sp)
        opt._apply_one(proxy, g_view, lr, weight_decay)
        new_shard = jax.lax.with_sharding_constraint(proxy._value, shard_sp)
        for store in opt._accumulators.values():
            cell = store.get(id(proxy))
            if cell is not None and not isinstance(cell._value, (int, float)):
                cell._value = jax.lax.with_sharding_constraint(
                    cell._value, shard_sp)

        # 3. all-gather the updated weights back to replicated — the
        # int8 tier is qpsum's gather half verbatim: quantize the shard
        # blockwise, gather int8 blocks + fp32 scales, dequantize
        if master is not None:
            master._replace_value(new_shard)
            q, scales = copt.quantize_blockwise(new_shard, row.block)
            q = jax.lax.with_sharding_constraint(q, rep_sp)
            scales = jax.lax.with_sharding_constraint(scales, rep_sp)
            full = copt.dequantize_blockwise(q, scales)
            copt.note_wire_dtype(axis, "int8")
        else:
            full = jax.lax.with_sharding_constraint(new_shard, rep_sp)
        out = full[:row.numel].reshape(p._value.shape)
        p._replace_value(out.astype(p._value.dtype))
        # NaN/Inf + range sentinel on the gathered update (one bool read
        # when dark; inside the compiled TrainStep the value is a tracer
        # and the lit witness skips it — eager optimizer paths observe)
        from ...observability import numerics

        numerics.watch("zero1.update", p._value)

        _tick("zero1_params")
        ring = (n - 1) / n
        _tick("zero1_bytes_rs", ring * row.padded * 4)
        if master is not None:
            _tick("zero1_bytes_ag",
                  ring * (row.padded + row.padded // row.block * 4))
        else:
            _tick("zero1_bytes_ag", ring * row.padded * 4)

    # ----------------------------------------------------------- state map
    def cell_for(self, store: dict, p):
        """The accumulator cell for ``p`` inside one store: the
        shard-space proxy's cell when the sharded update owns one (it
        wins over a stale full-shape cell a pre-step priming pass may
        have left keyed on the param), else the param's own."""
        view = self._proxies.get(id(p))
        if view is not None:
            cell = store.get(id(view))
            if cell is not None:
                return cell
        return store.get(id(p))

    def extra_state_cells(self) -> list:
        return list(self._masters.values())

    def restore_masters(self, opt, state: dict) -> None:
        """Restore ``{p.name}_zero1_master`` entries from a plain
        state_dict (the counterpart of ``state_dict`` emitting them):
        into the existing master cell when one lives, else created
        fresh against the installed mesh. Without a mesh the entries
        are skipped with a warning — the next int8-gather step would
        rebuild masters from the dequantized weights, losing the
        accumulated sub-quantum residual."""
        import numpy as np

        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        for p in opt._parameter_list:
            src = state.get(f"{p.name}_zero1_master")
            if src is None:
                continue
            arr = src.numpy() if hasattr(src, "numpy") else np.asarray(src)
            m = self._masters.get(id(p))
            if m is not None:
                m.set_value(arr)
                continue
            spec = step_spec(opt, explicit="zero1")
            if spec is None:
                from ...base.log import get_logger

                get_logger().warning(
                    "set_state_dict: dropping zero1 master shard for %r — "
                    "no installed mesh with a real dp/sharding axis to "
                    "re-scatter onto (dist.init_parallel_env first to keep "
                    "exact int8-gather updates)", p.name)
                continue
            mesh, axis, n = spec
            row = self.row(p, n)
            placement = NamedSharding(mesh, P(axis))
            m = self.master_for(p, row, placement)
            m._value = jax.device_put(arr.reshape(-1), placement)

    def shard_entries(self, optimizer) -> list:
        """Every sharded optimizer-state cell as ``(param_name,
        state_name, cell, row)`` — the unit the sharded checkpoint
        saves/loads."""
        out = []
        for p in optimizer._parameter_list:
            view = self._proxies.get(id(p))
            row = self._rows.get(id(p))
            if view is None or row is None or not row.sharded:
                continue
            for name, store in optimizer._accumulators.items():
                cell = store.get(id(view))
                if cell is not None:
                    out.append((p.name, name, cell, row))
            m = self._masters.get(id(p))
            if m is not None:
                out.append((p.name, "zero1_master", m, row))
        return out


# --------------------------------------------------------------- accounting
def _per_replica_bytes(value) -> int:
    """Max bytes any one replica holds for ``value`` (its shard for
    sharded arrays, everything for replicated/uncommitted ones). The
    shard fraction comes from the cost model's ``value_divisor`` — one
    implementation serves both the residency accounting here and the
    sharding-aware liveness walk."""
    from ...analysis.cost_model import value_divisor

    return int(round(int(getattr(value, "nbytes", 0))
                     / value_divisor(value)))


def opt_state_report(optimizer) -> dict:
    """Measured optimizer-state residency: for every accumulator / aux /
    master cell, the bytes one replica actually holds (via the array's
    committed sharding) vs the bytes the replicated layout would hold.
    ``ratio`` is the headline the bench trends
    (``zero1.opt_state_bytes_ratio``)."""
    st = attached(optimizer)
    rows = []

    def add(key, cell, logical_bytes=None):
        v = cell._value
        per = _per_replica_bytes(v)
        logical = int(logical_bytes if logical_bytes is not None
                      else getattr(v, "nbytes", 0))
        rows.append({"key": key, "logical_bytes": logical,
                     "per_replica_bytes": per,
                     "sharded": per < int(getattr(v, "nbytes", 0))})

    seen = set()
    for name, store in optimizer._accumulators.items():
        for p in optimizer._parameter_list:
            cell, row = None, None
            if st is not None:
                view = st._proxies.get(id(p))
                if view is not None:
                    cell = store.get(id(view))
                    row = st._rows.get(id(p))
            if cell is None:
                cell, row = store.get(id(p)), None
            if cell is None or id(cell) in seen:
                continue
            seen.add(id(cell))
            # replicated-layout baseline: one fp32 moment per param
            # element (the proxy cell's padded length overstates it)
            logical = (row.numel * 4) if row is not None else None
            add(f"{p.name}_{name}", cell, logical)
    if st is not None:
        for m in st._masters.values():
            if id(m) not in seen:
                seen.add(id(m))
                # masters have no replicated counterpart: pure overhead
                # of the int8 gather tier
                add(m.name, m, 0)
    replicated = sum(r["logical_bytes"] for r in rows)
    per_replica = sum(r["per_replica_bytes"] for r in rows)
    return {
        "rows": rows,
        "replicated_bytes": int(replicated),
        "per_replica_bytes": int(per_replica),
        "ratio": (replicated / per_replica) if per_replica else 1.0,
        "n_cells": len(rows),
    }


# ------------------------------------------------------------- checkpointing
_SHARD_FORMAT = "zero1-shard-v1"


def _host_key_map(optimizer) -> dict:
    """state_dict key -> position-stable key for the host-side save
    (``{p.name}_{accum}`` embeds the instance's auto-generated tensor
    names; ``__param{i}__:{accum}`` survives a fresh twin)."""
    out = {}
    for i, p in enumerate(optimizer._parameter_list):
        for name in optimizer._accum_names:
            out[f"{p.name}_{name}"] = f"__param{i}__:{name}"
    return out


def _shard_pieces(value):
    """This process's addressable ``(offset, numpy)`` pieces of one flat
    sharded array, deduplicated (replication over other mesh axes aside,
    each offset appears once)."""
    import numpy as np

    pieces = {}
    for s in value.addressable_shards:
        idx = s.index[0] if s.index else slice(None)
        off = int(idx.start or 0) if isinstance(idx, slice) else 0
        if off not in pieces:
            pieces[off] = np.asarray(s.data)
    return sorted(pieces.items())


def save_sharded_optimizer_state(optimizer, path_prefix: str) -> dict:
    """Write the zero1 optimizer state as ``{path}.pdopt`` (host-side
    state: step counter, aux cells, LR scheduler, unsharded
    accumulators) plus ``{path}.pdopt.shard{rank}of{world}`` holding
    ONLY this process's addressable shard pieces — no full-tensor
    gather, O(shard) host memory. Returns the shard manifest."""
    from ...framework.io import save
    from .. import env as env_mod

    st = attached(optimizer)
    entries = st.shard_entries(optimizer) if st is not None else []
    sharded_cells = {id(c) for _, _, c, _ in entries}

    # host-side remainder keyed by param POSITION (auto-generated tensor
    # names differ between model instances; positions don't)
    key_map = _host_key_map(optimizer)
    host_state = {}
    for key, val in optimizer.state_dict().items():
        if not (hasattr(val, "_value") and id(val) in sharded_cells):
            host_state[key_map.get(key, key)] = val
    save(host_state, path_prefix + ".pdopt")

    rank = env_mod.get_rank()
    world = max(env_mod.get_world_size(), 1)
    manifest = {"format": _SHARD_FORMAT, "rank": int(rank),
                "world": int(world), "entries": []}
    # entries key on the param's POSITION in _parameter_list: auto-
    # generated tensor names differ between model instances, positions
    # don't (the name is kept for diagnostics)
    index_of = {p.name: i
                for i, p in enumerate(optimizer._parameter_list)}
    for pname, sname, cell, row in entries:
        manifest["entries"].append({
            "param": pname, "param_index": index_of.get(pname, -1),
            "state": sname,
            "numel": row.numel, "padded": row.padded,
            "shard_elems": row.shard_elems, "axis_size": row.axis_size,
            "dtype": str(cell._value.dtype),
            "pieces": _shard_pieces(cell._value),
        })
    save(manifest, f"{path_prefix}.pdopt.shard{rank}of{world}")
    return manifest


def _reslice_piece(by_off: dict, start: int, length: int, entry: dict,
                   pname: str, sname: str):
    """One target shard slice ``[start, start+length)`` of the flat
    padded space, assembled from saved pieces of a DIFFERENT layout.
    Copies only the overlapping ranges (O(shard) residency — the full
    tensor never materializes); target elements past the old padded span
    are new-layout shard padding and stay zero. Real data
    (``[0, numel)``) must be fully covered by saved pieces — a gap there
    is an incomplete shard-file set and fails loudly."""
    import numpy as np

    sample = next(iter(by_off.values()))
    out = np.zeros(length, dtype=sample.dtype)
    end = start + length
    covered = np.zeros(length, dtype=bool)
    for off, arr in by_off.items():
        lo = max(start, int(off))
        hi = min(end, int(off) + arr.shape[0])
        if lo >= hi:
            continue
        out[lo - start: hi - start] = arr[lo - off: hi - off]
        covered[lo - start: hi - start] = True
    real_end = min(end, int(entry["numel"]))
    if real_end > start and not covered[: real_end - start].all():
        raise ValueError(
            f"sharded state {pname}/{sname}: saved pieces "
            f"(axis_size={entry['axis_size']}) do not cover "
            f"[{start}, {real_end}) of the flat value — shard file set "
            "incomplete; cannot re-slice onto the new topology")
    return out


def load_sharded_optimizer_state(optimizer, path_prefix: str) -> int:
    """Round-trip of :func:`save_sharded_optimizer_state`: host state
    restores through ``set_state_dict``; each shard file re-scatters its
    pieces straight to the owning devices (``device_put`` per piece +
    ``make_array_from_single_device_arrays`` — the full tensor never
    materializes on host). A checkpoint saved under a DIFFERENT dp/
    sharding degree (dp=8 pieces onto dp=4 and vice versa) re-slices the
    pieces onto the new shard grid at load (:func:`_reslice_piece`)
    instead of rejecting the layout. Returns the number of sharded cells
    restored."""
    import glob
    import os

    import numpy as np

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ...core.tensor import Tensor
    from ...framework.io import load
    from .. import env as env_mod

    host_state = load(path_prefix + ".pdopt")
    inverse = {v: k for k, v in _host_key_map(optimizer).items()}
    optimizer.set_state_dict(
        {inverse.get(k, k): v for k, v in host_state.items()})
    shard_files = sorted(glob.glob(path_prefix + ".pdopt.shard*of*"))
    if not shard_files:
        return 0
    spec = step_spec(optimizer, explicit="zero1")
    if spec is None:
        raise RuntimeError(
            "load_sharded_optimizer_state needs an installed mesh with a "
            "real dp/sharding axis to re-scatter onto "
            "(dist.init_parallel_env first)")
    mesh, axis, n = spec
    st = ensure_strategy(optimizer)
    sharding = NamedSharding(mesh, P(axis))
    params = list(optimizer._parameter_list)

    # merge pieces across every shard file this process can read (single
    # host: all of them; multi-host: at least its own rank's)
    merged: Dict[tuple, dict] = {}
    for f in shard_files:
        manifest = load(f, return_numpy=True)
        if manifest.get("format") != _SHARD_FORMAT:
            raise ValueError(f"{os.path.basename(f)}: not a "
                             f"{_SHARD_FORMAT} shard file")
        for e in manifest["entries"]:
            key = (e.get("param_index", -1), e["state"])
            row = merged.setdefault(key, dict(e, pieces=[]))
            row["pieces"].extend(e["pieces"])

    restored = 0
    for (pidx, sname), e in merged.items():
        p = params[pidx] if 0 <= pidx < len(params) else None
        if p is None:
            continue
        pname = p.name
        row = st.row(p, n)
        resliced = e["padded"] != row.padded or e["axis_size"] != n
        if resliced:
            # CHANGED topology (e.g. a dp=8 checkpoint onto dp=4): the
            # logical flat value is identical, only the shard grid moved —
            # re-slice the saved pieces onto the new offsets instead of
            # rejecting the layout. O(shard) per target slice: each new
            # piece copies only the old-piece ranges overlapping it
            # (regions past the old padded span are shard padding, zeros
            # by construction).
            from ...base.log import get_logger

            get_logger().info(
                "load_sharded_optimizer_state: re-slicing %s/%s from "
                "axis_size=%d (padded=%d) onto axis_size=%d (padded=%d)",
                pname, sname, e["axis_size"], e["padded"], n, row.padded)
        by_off = {off: np.asarray(arr) for off, arr in e["pieces"]}
        idx_map = sharding.addressable_devices_indices_map((row.padded,))
        arrays = []
        for dev, idx in idx_map.items():
            off = int(idx[0].start or 0)
            if resliced:
                piece = _reslice_piece(by_off, off, row.shard_elems, e,
                                       pname, sname)
            else:
                piece = by_off.get(off)
                if piece is None:
                    raise ValueError(
                        f"sharded state {pname}/{sname}: no saved piece "
                        f"for offset {off} — shard file set incomplete")
            arrays.append(jax.device_put(piece, dev))
        value = jax.make_array_from_single_device_arrays(
            (row.padded,), sharding, arrays)
        view = st.proxy_for(p, row)
        if sname == "zero1_master":
            m = st.master_for(p, row, sharding)
            m._value = value
        else:
            optimizer._accumulators[sname][id(view)] = Tensor(
                value, stop_gradient=True, name=f"{pname}_{sname}")
        restored += 1
    return restored
