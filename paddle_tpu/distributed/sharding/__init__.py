"""ZeRO sharded-data-parallel user entry.

Reference: paddle.distributed.sharding.group_sharded_parallel
(distributed/sharding/group_sharded.py) -> GroupShardedStage2/3 wrappers +
GroupShardedOptimizerStage2 (fleet/meta_parallel/sharding/*).

TPU-native: ZeRO is a *layout*, not a runtime. Stage1/2 shard the optimizer
states (and thus the update computation) over the dp/sharding axis; stage3
additionally shards the parameters. GSPMD partitions the optimizer update and
inserts the gather/scatter collectives the reference implements by hand
(SURVEY.md §7 translation table).
"""
from __future__ import annotations

from ..auto_parallel.api import (
    ShardingStage1,
    ShardingStage2,
    ShardingStage3,
    shard_optimizer,
)


def group_sharded_parallel(model, optimizer, level="os_g", scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size=None,
                           segment_size=None, sync_comm=False):
    """reference group_sharded.py: level in {'os', 'os_g', 'p_g_os'}."""
    from .. import env as env_mod

    axis = "sharding" if env_mod.instance().axis_degrees.get("sharding", 1) > 1 else "dp"
    stage = {"os": ShardingStage1, "os_g": ShardingStage2, "p_g_os": ShardingStage3}[level]
    shard_optimizer(optimizer, stage(axis))
    if level == "p_g_os":
        from ..auto_parallel.api import _shard_over_axis
        from ..auto_parallel.process_mesh import get_mesh_from_jax

        mesh = get_mesh_from_jax(env_mod.get_mesh())
        for p in model.parameters():
            p._replace_value(_shard_over_axis(p._value, mesh, axis))
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    from ...framework.io import save

    save(model.state_dict(), output + ".pdparams")
    if optimizer is not None:
        save(optimizer.state_dict(), output + ".pdopt")
