"""ZeRO sharded-data-parallel user entry.

Reference: paddle.distributed.sharding.group_sharded_parallel
(distributed/sharding/group_sharded.py) -> GroupShardedStage2/3 wrappers +
GroupShardedOptimizerStage2 (fleet/meta_parallel/sharding/*).

TPU-native: stages 1/2 ("os" / "os_g") engage the :mod:`zero1` strategy —
reduce-scatter(grads) → per-shard optimizer update (each replica owns a
contiguous 1/dp slice of the flattened param/moment space) → all-gather
(updated weights), with the optimizer states persisting as genuinely
sharded arrays. Stage 3 ("p_g_os") additionally shards the parameters
themselves over the axis (GSPMD partitions the forward/backward
accordingly). ``save_group_sharded_model`` round-trips the sharded
optimizer state: each process saves only its addressable shard pieces,
and load re-scatters them onto the owning devices.
"""
from __future__ import annotations

from . import zero1
from .zero1 import (Zero1Strategy, load_sharded_optimizer_state,
                    opt_state_report, plan_shards,
                    save_sharded_optimizer_state, zero1_wire_report)

__all__ = [
    "group_sharded_parallel", "save_group_sharded_model",
    "load_group_sharded_model", "zero1", "Zero1Strategy", "plan_shards",
    "opt_state_report", "zero1_wire_report",
    "save_sharded_optimizer_state", "load_sharded_optimizer_state",
]


def group_sharded_parallel(model, optimizer, level="os_g", scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size=None,
                           segment_size=None, sync_comm=False):
    """reference group_sharded.py: level in {'os', 'os_g', 'p_g_os'}.

    'os' and 'os_g' attach the zero1 strategy (optimizer states + weight
    update sharded over dp/sharding; gradients reduce-scatter as part of
    the update, so stage 2 is subsumed); 'p_g_os' additionally shards the
    parameters. Engagement is sticky for this optimizer — TrainStep
    detects it and keys its compile cache on the sharded-update tier.
    """
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError(f"unknown group_sharded level {level!r} "
                         "(os|os_g|p_g_os)")
    zero1.ensure_strategy(optimizer, requested=True)
    if level == "p_g_os":
        from .. import env as env_mod
        from ..auto_parallel.api import _shard_over_axis
        from ..auto_parallel.process_mesh import get_mesh_from_jax

        axis = "sharding" if env_mod.instance().axis_degrees.get(
            "sharding", 1) > 1 else "dp"
        mesh = get_mesh_from_jax(env_mod.get_mesh())
        for p in model.parameters():
            p._replace_value(_shard_over_axis(p._value, mesh, axis))
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """Save a group-sharded model + optimizer. Model parameters are
    replicated (stages 1/2) and save whole; zero1 optimizer state saves
    SHARDED — each process writes only its addressable shard pieces to
    ``output + ".pdopt.shard{rank}of{world}"`` (plus the host-side
    remainder in ``output + ".pdopt"``), no full-tensor gather. Without
    sharded state this degrades to the legacy whole-state save."""
    from ...framework.io import save

    save(model.state_dict(), output + ".pdparams")
    if optimizer is None:
        return
    st = zero1.attached(optimizer)
    if st is not None and st.shard_entries(optimizer):
        save_sharded_optimizer_state(optimizer, output)
    else:
        save(optimizer.state_dict(), output + ".pdopt")


def load_group_sharded_model(model, output, optimizer=None):
    """Round-trip of :func:`save_group_sharded_model`: parameters load
    whole; sharded optimizer state re-scatters each saved shard piece
    straight onto its owning device."""
    import glob
    import os

    from ...framework.io import load

    model.set_state_dict(load(output + ".pdparams"))
    if optimizer is None:
        return
    if glob.glob(output + ".pdopt.shard*of*"):
        load_sharded_optimizer_state(optimizer, output)
    elif os.path.exists(output + ".pdopt"):
        optimizer.set_state_dict(load(output + ".pdopt"))
