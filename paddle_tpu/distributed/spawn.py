"""paddle.distributed.spawn (reference distributed/spawn.py): multiprocessing
launcher alternative to the CLI. In single-controller SPMD one process drives
all local devices, so nprocs defaults to 1 per host; multi-host spawning goes
through paddle_tpu.distributed.launch.
"""
from __future__ import annotations

import multiprocessing as mp
import os


def _worker(fn, rank, nprocs, args, env):
    os.environ.update(env)
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    fn(*args)


def spawn(func, args=(), nprocs=1, join=True, daemon=False, **options):
    if nprocs <= 1:
        func(*args)
        return None
    ctx = mp.get_context("spawn")
    procs = []
    env = {k: v for k, v in os.environ.items() if k.startswith(("PADDLE_", "MASTER_", "JAX_", "XLA_"))}
    for rank in range(nprocs):
        p = ctx.Process(target=_worker, args=(func, rank, nprocs, args, env), daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        for p in procs:
            if p.exitcode != 0:
                raise RuntimeError(f"spawned rank exited with {p.exitcode}")
    return procs
