"""Distributed checkpoint load with cross-topology reshard.

Reference: distributed/checkpoint/load_state_dict.py — reads the metadata
index, fetches the shards overlapping this rank's slices, reassembles.

TPU-native: the stored format is the global array; "reshard on load" is just
device_put onto whatever sharding the destination tensor currently carries
(different mesh shape/axes/world size all included).
"""
from __future__ import annotations

import json
import os
from typing import Dict

import jax
import numpy as np

from ...core.tensor import Tensor
from .save_state_dict import _flatten_state


def load_state_dict(state_dict: Dict, path: str, process_group=None, coordinator_rank: int = 0) -> None:
    """In-place: fills `state_dict`'s tensors with values from `path`,
    resharding to each tensor's current placement.

    Format auto-detection (ISSUE 15): a directory carrying a sharded
    manifest (``distributed.checkpoint.sharded`` — one piece file per
    (tensor, shard), sha256 per piece, O(shard) load) restores through
    the sharded engine; the legacy metadata.json + npz layout keeps its
    chunk-reassembly path below."""
    from .sharded import is_sharded_checkpoint, load_sharded_into

    if is_sharded_checkpoint(path):
        load_sharded_into(state_dict, path)
        return
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    # Resolution is metadata-driven: chunk keys are save-nonce-qualified
    # (collision-free across saves, so merge order is irrelevant for them);
    # PLAIN keys — written only by the save's coordinator — resolve
    # EXCLUSIVELY from the committed metadata's coordinator shard, so a
    # stale uncollected shard (even one left by a save with a different
    # coordinator rank) can never shadow the committed values.
    shards = {}
    coord = meta.get("coordinator_shard")
    for fname in sorted(os.listdir(path)):
        if fname.startswith("shard_") and fname.endswith(".npz"):
            shards.update(np.load(os.path.join(path, fname)))
    if coord and os.path.exists(os.path.join(path, coord)):
        plain = dict(np.load(os.path.join(path, coord)))
    else:  # pre-coordinator_shard checkpoints: merged view (legacy)
        plain = shards
    flat = _flatten_state(state_dict)
    entries = meta.get("entries", {})
    missing = [k for k in flat if k not in plain and not entries.get(k, {}).get("chunks")]
    if missing:
        raise KeyError(f"checkpoint at {path} is missing keys: {missing[:5]}{'...' if len(missing) > 5 else ''}")
    for k, t in flat.items():
        entry = entries.get(k, {})
        if entry.get("chunks"):  # multi-host chunked entry: reassemble
            # loud failure on a partial piece set (ISSUE 14 satellite):
            # committed metadata references every chunk by key, so a key
            # the shard files cannot serve means a shard file is missing
            # or torn — name the gap instead of KeyError-ing on one chunk
            absent = [ck["key"] for ck in entry["chunks"]
                      if ck["key"] not in shards]
            if absent:
                raise RuntimeError(
                    f"checkpoint at {path} is INCOMPLETE for {k!r}: "
                    f"{len(absent)}/{len(entry['chunks'])} chunk(s) "
                    f"missing from the shard files (first: {absent[:3]}). "
                    "A rank's shard file is absent or torn — restore from "
                    "a complete checkpoint; refusing a partial load")
            host = np.empty(entry["shape"], dtype=np.dtype(entry["dtype"]))
            for ck in entry["chunks"]:
                idx = tuple(slice(a, b) for a, b in ck["index"])
                host[idx] = shards[ck["key"]]
        else:
            host = plain[k]
        if list(host.shape) != list(t.shape):
            raise ValueError(f"{k}: checkpoint shape {host.shape} != target {t.shape}")
        try:
            sharding = t._value.sharding  # reshard to the destination layout
            val = jax.device_put(jax.numpy.asarray(host, dtype=t._value.dtype), sharding)
        except Exception:
            val = jax.numpy.asarray(host, dtype=t._value.dtype)
        t._replace_value(val)
