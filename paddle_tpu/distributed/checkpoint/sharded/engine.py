"""Sharded-native checkpoint engine: O(shard) save/load + dtype cast.

Per ROADMAP "Sharded checkpoint I/O + zero-downtime weight hot-swap",
SNIPPETS [3] (per-tensor pjit shard/gather fns with dtype casting) and
the O(shard)-residency discipline of arXiv 2112.01075 (the same
discipline PR 10's resharder and zero1's shard checkpoints follow):

- :func:`save_sharded` writes one raw piece file per (tensor, shard)
  STRAIGHT from each device's addressable shard — the full tensor never
  materializes on host; peak host residency is one shard (plus the json
  manifest). The commit is atomic (``reliability/snapshot.py``'s
  tmp-dir + fsync + one ``os.rename`` + parent-dir fsync discipline):
  a crash — or an injected ``ckpt.write`` fault — at any point leaves
  either the previous committed checkpoint or an ignorable tmp dir.
- :func:`load_sharded` restores via ``device_put`` per target shard +
  ``make_array_from_single_device_arrays``; when the saved and target
  shard grids differ (dp=8 pieces onto dp=4, dp=1, any N-d regrid) each
  target slice is assembled from ONLY the overlapping saved pieces —
  O(shard) per slice, the N-d generalization of zero1's
  ``_reslice_piece`` math — and a coverage gap fails loudly naming the
  tensor and range.
- dtype-converting load (SNIPPETS [3]): float pieces cast float→float
  on the host, one piece at a time, so an fp32 training checkpoint
  loads directly as bf16 serving weights. Non-float tensors never cast
  silently — a non-float dtype change raises.
- every failure mode — torn write, corrupt piece, truncated piece,
  incomplete piece set, unwritable directory — fails loudly with the
  piece named. There are no silent partial loads.

:func:`load_sharded_like` (new values shaped/placed/typed like a target
tree, nothing mutated) is the weight hot-swap's read path;
:func:`load_sharded_into` fills live Tensors in place (the
state_dict/snapshot restore path); :func:`convert_sharded` rewrites a
checkpoint under a new float dtype (``tools.ckpt convert``).
"""
from __future__ import annotations

import os
import shutil
import time
import uuid
from typing import Dict, List

import numpy as np

from ....reliability.faults import fault_point
from ....reliability.snapshot import fsync_dir
from . import manifest as mf

__all__ = ["save_sharded", "load_sharded", "load_sharded_like",
           "load_sharded_into", "convert_sharded", "is_sharded_checkpoint"]


def _tick(name: str, value: float = 1.0, **labels):
    try:
        from ....observability.metrics import registry

        registry.counter("ckpt." + name).inc(value, **labels)
    except Exception:
        pass


def is_sharded_checkpoint(directory: str) -> bool:
    """Does ``directory`` hold a committed sharded checkpoint?"""
    try:
        return os.path.exists(os.path.join(str(directory), mf.MANIFEST_NAME))
    except TypeError:
        return False


# ------------------------------------------------------------------- helpers
def _value_of(t):
    v = getattr(t, "_value", t)
    return v


def _norm_index(idx, shape) -> List[List[int]]:
    """A jax shard index (tuple of slices, possibly underspecified) as
    explicit ``[[start, stop], ...]`` bounds over ``shape``."""
    if not isinstance(idx, tuple):
        idx = (idx,)
    idx = tuple(idx) + (slice(None),) * (len(shape) - len(idx))
    out = []
    for s, dim in zip(idx, shape):
        start = 0 if s.start is None else int(s.start)
        stop = int(dim) if s.stop is None else int(s.stop)
        out.append([start, stop])
    return out


def _spec_of(v):
    """The PartitionSpec the array carries, as a json-able list (None
    when replicated / unsharded / unknown)."""
    spec = getattr(getattr(v, "sharding", None), "spec", None)
    if spec is None:
        return None
    out = []
    for e in spec:
        out.append(list(e) if isinstance(e, tuple) else e)
    return out if any(e for e in out) else None


def _host_pieces(v, shape):
    """Yield ``(bounds, numpy)`` for each unique device shard of ``v``
    — one at a time (the caller writes and releases each before the
    next is pulled: O(largest shard) host residency). Replicas over
    other mesh axes share an index and are deduplicated."""
    shards = getattr(v, "addressable_shards", None)
    if not shards:
        yield [[0, int(d)] for d in shape], np.asarray(v)
        return
    seen = set()
    for sh in shards:
        bounds = _norm_index(sh.index, shape)
        key = tuple(tuple(b) for b in bounds)
        if key in seen:
            continue
        seen.add(key)
        yield bounds, np.asarray(sh.data)


def _cast(host: np.ndarray, target_dtype, tensor_name: str,
          strict: bool = False) -> np.ndarray:
    """SNIPPETS [3] dtype policy: float casts float→float; a matching
    dtype passes through. A blanket converting load (``strict=False``,
    e.g. ``load_sharded(dtype="bfloat16")``) leaves non-float tensors
    untouched — int ids must not be "converted". A target-derived dtype
    (``strict=True``, the hot-swap path) refuses any non-float mismatch
    loudly: an int tensor silently reinterpreted is a corruption, not a
    cast."""
    if target_dtype is None:
        return host
    target = mf.np_dtype(str(target_dtype))
    if host.dtype == target:
        return host
    import jax.numpy as jnp

    if jnp.issubdtype(host.dtype, jnp.floating) and \
            jnp.issubdtype(target, jnp.floating):
        return host.astype(target)
    if not strict:
        return host
    raise ValueError(
        f"sharded checkpoint: refusing to convert {tensor_name!r} from "
        f"{host.dtype} to {target} — only float→float conversion is "
        "supported (load with dtype=None to keep the saved dtype)")


# --------------------------------------------------------------------- save
# --------------------------------------------------------- atomic publish
def _new_tmp(directory: str, overwrite: bool, what: str):
    """Resolve the target, refuse a non-overwrite collision, create the
    sibling tmp dir every writer stages into. Returns
    ``(directory, parent, nonce, tmp)``."""
    directory = os.path.abspath(str(directory))
    if os.path.exists(directory) and not overwrite:
        raise FileExistsError(
            f"{directory} already exists — pass overwrite=True to replace "
            "the committed checkpoint")
    parent = os.path.dirname(directory)
    try:
        os.makedirs(parent, exist_ok=True)
    except OSError as e:
        raise OSError(
            f"{what}: cannot create checkpoint parent {parent!r}: "
            f"{e}") from e
    nonce = uuid.uuid4().hex[:8]
    tmp = os.path.join(parent,
                       f"{mf.TMP_PREFIX}{os.path.basename(directory)}_{nonce}")
    try:
        os.makedirs(tmp)
    except OSError as e:
        raise OSError(
            f"{what}: cannot write under {parent!r} "
            f"(read-only or unwritable): {e}") from e
    return directory, parent, nonce, tmp


def _commit(tmp: str, directory: str, nonce: str, manifest: dict) -> None:
    """Write + fsync the manifest into ``tmp``, then publish ``tmp`` as
    ``directory``. Fresh targets commit with ONE atomic rename. An
    overwrite needs two renames (POSIX cannot exchange non-empty
    directories atomically): the old checkpoint first moves aside as a
    ``.tmp_old_<name>_<nonce>`` sibling — so a crash in the narrow
    window between the renames strands the COMPLETE previous checkpoint
    under a recoverable name (``read_manifest`` points at it) rather
    than losing data — and the droppings are removed only after the new
    checkpoint is in place. The single writer-per-directory contract is
    the caller's (enforced at the ``save_state_dict`` seam)."""
    import json

    mpath = os.path.join(tmp, mf.MANIFEST_NAME)
    with open(mpath, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    # the injected torn-write point (reliability chaos): a crash here
    # leaves ONLY the tmp dir — a previous committed checkpoint stays
    # the valid one, and read_manifest refuses the tmp by design
    fault_point("ckpt.write")
    if os.path.exists(directory):
        old = os.path.join(
            os.path.dirname(directory),
            f"{mf.TMP_PREFIX}old_{os.path.basename(directory)}_{nonce}")
        os.rename(directory, old)
        os.rename(tmp, directory)
        shutil.rmtree(old, ignore_errors=True)
    else:
        os.rename(tmp, directory)  # the atomic publish


def save_sharded(state: Dict, directory: str, *,
                 overwrite: bool = False) -> dict:
    """Write ``state`` (a possibly nested state_dict of Tensors/arrays)
    as one sharded checkpoint directory. Returns a report::

        {"dir", "n_tensors", "n_pieces", "bytes", "max_piece_bytes",
         "seconds"}

    ``max_piece_bytes`` is the peak host bytes any single tensor
    contributed — the O(shard) residency accounting the tests gate.

    The publish is atomic: everything lands in a sibling
    ``.tmp_<name>_<nonce>`` dir (each piece fsynced), then ONE
    ``os.rename`` commits and the parent dir is fsynced. ``overwrite``
    replaces an existing committed checkpoint — that path needs a
    second rename (see :func:`_commit`): a crash inside its narrow
    window strands the previous checkpoint COMPLETE under a
    ``.tmp_old_*`` sibling name (recoverable, pointed at by
    ``read_manifest``'s error) instead of losing it; prefer a fresh
    directory per checkpoint (the snapshotter idiom) when strict
    single-rename atomicity matters."""
    from ..save_state_dict import _flatten_state

    t0 = time.perf_counter()
    directory, parent, nonce, tmp = _new_tmp(directory, overwrite,
                                             "save_sharded")
    flat = _flatten_state(state)
    entries = {}
    n_pieces = 0
    total = 0
    max_piece = 0
    try:
        for i, (name, t) in enumerate(flat.items()):
            v = _value_of(t)
            shape = [int(d) for d in v.shape]
            entry = {"shape": shape, "dtype": str(np.dtype(v.dtype)),
                     "spec": _spec_of(v), "pieces": []}
            for j, (bounds, host) in enumerate(_host_pieces(v, shape)):
                host = np.ascontiguousarray(host)
                fname = mf.piece_filename(i, name, j)
                fpath = os.path.join(tmp, fname)
                with open(fpath, "wb") as f:
                    f.write(host.tobytes())
                    f.flush()
                    os.fsync(f.fileno())
                entry["pieces"].append({
                    "file": fname,
                    "index": bounds,
                    "sha256": mf.sha256_file(fpath),
                    "bytes": int(host.nbytes),
                })
                n_pieces += 1
                total += int(host.nbytes)
                max_piece = max(max_piece, int(host.nbytes))
                del host  # one shard on host at a time — the O(shard) law
            entries[name] = entry
        _commit(tmp, directory, nonce,
                {"format": mf.FORMAT, "created_unix": time.time(),
                 "entries": entries})
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    fsync_dir(parent)
    _tick("pieces_saved", n_pieces)
    _tick("saves")
    return {"dir": directory, "n_tensors": len(flat), "n_pieces": n_pieces,
            "bytes": total, "max_piece_bytes": max_piece,
            "seconds": round(time.perf_counter() - t0, 4)}


# --------------------------------------------------------------------- load
class _PieceReader:
    """Per-load piece access: reads one piece file fully (O(piece) ≤
    O(largest saved shard)), verifies its sha256 ONCE per load pass,
    parses the raw bytes against the manifest's dtype/bounds. Every
    defect raises naming the piece."""

    def __init__(self, directory: str, verify: bool = True):
        self.dir = directory
        self.verify = verify
        self._verified = set()

    def read(self, tensor: str, entry: dict, piece: dict) -> np.ndarray:
        fname = piece["file"]
        path = os.path.join(self.dir, fname)
        if not os.path.exists(path):
            raise RuntimeError(
                f"sharded checkpoint {self.dir!r} is INCOMPLETE for "
                f"{tensor!r}: piece {fname!r} is missing — a shard file "
                "was lost or the save was torn; refusing a partial load")
        with open(path, "rb") as f:
            data = f.read()
        dtype = mf.np_dtype(entry["dtype"])
        bounds = piece["index"]
        shape = tuple(int(b) - int(a) for a, b in bounds)
        want = int(np.prod(shape)) * dtype.itemsize if bounds \
            else dtype.itemsize
        if len(data) != want:
            raise RuntimeError(
                f"sharded checkpoint piece {fname!r} ({tensor!r}) is "
                f"CORRUPT: {len(data)} bytes on disk, manifest promises "
                f"{want} — truncated or torn write; restore from a "
                "complete checkpoint")
        if self.verify and fname not in self._verified:
            import hashlib

            if hashlib.sha256(data).hexdigest() != piece.get("sha256"):
                raise RuntimeError(
                    f"sharded checkpoint piece {fname!r} ({tensor!r}) is "
                    "CORRUPT: sha256 mismatch — the bytes rotted or were "
                    "torn mid-write; refusing to load them")
            self._verified.add(fname)
        return np.frombuffer(data, dtype=dtype).reshape(shape)


def _assemble(reader: _PieceReader, tensor: str, entry: dict,
              bounds: List[List[int]], target_dtype,
              strict: bool = False) -> np.ndarray:
    """One target slice ``bounds`` of ``tensor``'s global array,
    assembled from ONLY the saved pieces overlapping it (the N-d
    re-slice: O(target slice) residency however the saved grid was
    laid out). A coverage gap — an incomplete piece set — fails loudly
    naming the tensor and range."""
    shape = tuple(int(b) - int(a) for a, b in bounds)
    numel = int(np.prod(shape)) if shape else 1
    overlapping = []
    for piece in entry["pieces"]:
        pidx = piece["index"]
        ov = [[max(int(a0), int(b0)), min(int(a1), int(b1))]
              for (a0, a1), (b0, b1) in zip(pidx, bounds)]
        if all(lo < hi for lo, hi in ov) or not bounds:
            overlapping.append((piece, ov))
    if len(overlapping) == 1:
        piece, ov = overlapping[0]
        if [list(map(int, b)) for b in piece["index"]] == \
                [list(map(int, b)) for b in bounds]:
            # exact-grid fast path: the saved piece IS the target slice
            return _cast(reader.read(tensor, entry, piece), target_dtype,
                         tensor, strict)
    out = np.zeros(shape, mf.np_dtype(entry["dtype"]))
    covered = 0
    for piece, ov in overlapping:
        arr = reader.read(tensor, entry, piece)
        src = tuple(slice(lo - int(p0), hi - int(p0))
                    for (lo, hi), (p0, _p1) in zip(ov, piece["index"]))
        dst = tuple(slice(lo - int(b0), hi - int(b0))
                    for (lo, hi), (b0, _b1) in zip(ov, bounds))
        out[dst] = arr[src]
        covered += int(np.prod([hi - lo for lo, hi in ov])) if ov else 1
        del arr
    if covered != numel:
        raise RuntimeError(
            f"sharded checkpoint {reader.dir!r} is INCOMPLETE for "
            f"{tensor!r}: saved pieces cover {covered}/{numel} elements "
            f"of slice {bounds} — shard file set incomplete (saved on a "
            "different grid and pieces are missing); refusing a partial "
            "load")
    return _cast(out, target_dtype, tensor, strict)


def _build_value(reader: _PieceReader, tensor: str, entry: dict,
                 sharding, target_dtype, strict: bool = False):
    """One restored jax array: per target shard, assemble the slice on
    host and ``device_put`` it to the owning device, then stitch with
    ``make_array_from_single_device_arrays`` — the full tensor only
    ever materializes when the target layout itself is one full-array
    shard (single device / replicated)."""
    import jax

    shape = tuple(int(d) for d in entry["shape"])
    if sharding is None:
        host = _assemble(reader, tensor, entry,
                         [[0, d] for d in shape], target_dtype, strict)
        return jax.numpy.asarray(host)
    try:
        idx_map = sharding.addressable_devices_indices_map(shape)
        groups: Dict[tuple, list] = {}
        for dev, idx in idx_map.items():
            bounds = _norm_index(idx, shape)
            groups.setdefault(tuple(tuple(b) for b in bounds),
                              []).append(dev)
        arrays = []
        for key, devs in groups.items():
            host = _assemble(reader, tensor, entry,
                             [list(b) for b in key], target_dtype, strict)
            for dev in devs:
                arrays.append(jax.device_put(host, dev))
            del host  # one target slice on host at a time
        return jax.make_array_from_single_device_arrays(
            shape, sharding, arrays)
    except (RuntimeError, ValueError):
        raise
    except Exception:
        # exotic sharding without an indices map: assemble whole + place
        host = _assemble(reader, tensor, entry,
                         [[0, d] for d in shape], target_dtype, strict)
        return jax.device_put(host, sharding)


def _resolve_dtype(dtype, name: str, entry: dict):
    if dtype is None:
        return None
    if isinstance(dtype, dict):
        return dtype.get(name)
    return dtype


def load_sharded(directory: str, *, mesh=None, specs=None, dtype=None,
                 names=None, verify: bool = True) -> Dict[str, object]:
    """Restore a sharded checkpoint as ``{name: jax.Array}``.

    - ``mesh`` + ``specs``: target placement. ``specs`` maps tensor name
      → PartitionSpec (or one spec for all); omitted names fall back to
      the spec recorded at save time when its axes exist on ``mesh``,
      else replicated. Without a mesh everything loads single-device.
    - ``dtype``: optional converting load (one dtype, or name → dtype):
      float tensors cast float→float per piece on host (fp32 checkpoint
      → bf16 serving weights); non-float conversion raises.
    - ``names``: restrict to a subset of entries.
    - ``verify=False`` skips the per-piece sha256 pass (trusted local
      disk); byte counts and coverage are always enforced.
    """
    man = mf.read_manifest(str(directory))
    reader = _PieceReader(str(directory), verify=verify)
    out = {}
    for name, entry in man["entries"].items():
        if names is not None and name not in names:
            continue
        sharding = _sharding_for(entry, mesh, specs, name)
        out[name] = _build_value(reader, name, entry, sharding,
                                 _resolve_dtype(dtype, name, entry))
    _tick("loads")
    return out


def _sharding_for(entry: dict, mesh, specs, name: str):
    if mesh is None:
        return None
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = None
    if isinstance(specs, dict):
        spec = specs.get(name)
    elif specs is not None:
        spec = specs
    if spec is None:
        saved = entry.get("spec")
        if saved:
            axes = set(mesh.axis_names)

            def known(e):
                if e is None:
                    return True
                if isinstance(e, (list, tuple)):
                    return all(a in axes for a in e)
                return e in axes

            if all(known(e) for e in saved):
                spec = P(*[tuple(e) if isinstance(e, list) else e
                           for e in saved])
    if spec is None:
        spec = P()
    return spec if isinstance(spec, NamedSharding) \
        else NamedSharding(mesh, spec)


def load_sharded_like(directory: str, targets: Dict[str, object], *,
                      require_all: bool = True,
                      verify: bool = True) -> Dict[str, object]:
    """New values for every array in ``targets`` (name → jax array /
    Tensor), each restored onto the TARGET's sharding and dtype — the
    weight hot-swap's read path: same shapes, same dtypes, same
    placement ⇒ the serving executables keep replaying. Nothing in
    ``targets`` is mutated. Missing checkpoint entries raise
    (``require_all``); shape mismatches always raise."""
    man = mf.read_manifest(str(directory))
    entries = man["entries"]
    missing = [k for k in targets if k not in entries]
    if missing and require_all:
        raise KeyError(
            f"sharded checkpoint {directory!r} is missing "
            f"{len(missing)} of the target's tensors (first: "
            f"{missing[:5]}) — it does not checkpoint this model")
    reader = _PieceReader(str(directory), verify=verify)
    out = {}
    for name, t in targets.items():
        if name not in entries:
            continue
        v = _value_of(t)
        entry = entries[name]
        if [int(d) for d in entry["shape"]] != [int(d) for d in v.shape]:
            raise ValueError(
                f"sharded checkpoint {directory!r}: {name!r} has shape "
                f"{entry['shape']}, target expects {list(v.shape)}")
        sharding = getattr(v, "sharding", None)
        out[name] = _build_value(reader, name, entry, sharding,
                                 str(v.dtype), strict=True)
    _tick("loads")
    return out


def load_sharded_into(state_dict: Dict, directory: str, *,
                      verify: bool = True) -> int:
    """Fill a live (possibly nested) state_dict's Tensors in place from
    a sharded checkpoint, resharding each value onto the tensor's
    CURRENT placement and dtype (float-casting when they differ).
    Returns the number of tensors restored; a tensor the checkpoint
    does not carry raises."""
    from ..save_state_dict import _flatten_state

    flat = _flatten_state(state_dict)
    new = load_sharded_like(directory, flat, verify=verify)
    for name, value in new.items():
        flat[name]._replace_value(value)
    return len(new)


# ------------------------------------------------------------------ convert
def convert_sharded(src: str, dst: str, *, dtype,
                    overwrite: bool = False) -> dict:
    """Rewrite checkpoint ``src`` as ``dst`` with float tensors cast to
    ``dtype`` (piece by piece — O(largest piece) host residency;
    non-float tensors copy through unchanged). Same atomic-publish
    contract as :func:`save_sharded`. Returns a report with per-dtype
    byte totals."""
    man = mf.read_manifest(str(src))
    reader = _PieceReader(str(src), verify=True)
    target = mf.np_dtype(str(dtype))
    import jax.numpy as jnp

    dst, parent, nonce, tmp = _new_tmp(dst, overwrite, "convert_sharded")
    n_cast = bytes_in = bytes_out = 0
    try:
        entries = {}
        for name, entry in man["entries"].items():
            casts = jnp.issubdtype(mf.np_dtype(entry["dtype"]),
                                   jnp.floating) and \
                jnp.issubdtype(target, jnp.floating) and \
                mf.np_dtype(entry["dtype"]) != target
            new_entry = dict(entry,
                             dtype=str(np.dtype(target)) if casts
                             else entry["dtype"],
                             pieces=[])
            for piece in entry["pieces"]:
                host = reader.read(name, entry, piece)
                bytes_in += host.nbytes
                if casts:
                    host = host.astype(target)
                fpath = os.path.join(tmp, piece["file"])
                with open(fpath, "wb") as f:
                    f.write(np.ascontiguousarray(host).tobytes())
                    f.flush()
                    os.fsync(f.fileno())
                new_entry["pieces"].append(dict(
                    piece, sha256=mf.sha256_file(fpath),
                    bytes=int(host.nbytes)))
                bytes_out += host.nbytes
                del host
            if casts:
                n_cast += 1
            entries[name] = new_entry
        _commit(tmp, dst, nonce,
                {"format": mf.FORMAT, "created_unix": time.time(),
                 "converted_from": {"dir": str(src), "dtype": str(dtype)},
                 "entries": entries})
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    fsync_dir(parent)
    return {"src": str(src), "dst": dst, "dtype": str(dtype),
            "n_tensors": len(entries), "n_cast": n_cast,
            "bytes_in": int(bytes_in), "bytes_out": int(bytes_out)}
