"""Sharded-checkpoint manifest: the on-disk index one checkpoint
directory carries.

One committed checkpoint directory holds ``manifest.json`` plus one raw
piece file per (tensor, shard). The manifest is the single source of
truth the loader, ``tools.ckpt`` and the ``ckpt`` lint family all read:

.. code-block:: json

    {
      "format": "paddle_tpu_sharded_ckpt_v1",
      "created_unix": 1754300000.0,
      "entries": {
        "linear_0.w_0": {
          "shape": [256, 128],
          "dtype": "float32",
          "spec": ["dp", null],
          "pieces": [
            {"file": "0000_linear_0.w_0.p0.bin",
             "index": [[0, 32], [0, 128]],
             "sha256": "...", "bytes": 16384}
          ]
        }
      }
    }

- ``index`` is the piece's half-open ``[start, stop)`` bounds per dim of
  the GLOBAL array — pieces of one entry are disjoint and together cover
  it exactly (:func:`verify_dir` checks both);
- ``spec`` records the PartitionSpec the array carried at save time
  (informational + the loader's default placement); ``null`` when the
  array was replicated or unsharded;
- piece payloads are raw C-order native-endian bytes (``.bin``), so any
  dtype jax can hold round-trips — including ``bfloat16``, which the
  ``.npy`` format cannot describe;
- ``sha256`` is over the piece file's raw bytes: a torn, truncated or
  bit-rotted piece fails loudly BY NAME at load/verify time, never a
  silent partial load.
"""
from __future__ import annotations

import hashlib
import json
import os
import re
from typing import List

import numpy as np

MANIFEST_NAME = "manifest.json"
FORMAT = "paddle_tpu_sharded_ckpt_v1"
PIECE_SUFFIX = ".bin"
TMP_PREFIX = ".tmp_"

__all__ = ["MANIFEST_NAME", "FORMAT", "PIECE_SUFFIX", "TMP_PREFIX",
           "np_dtype", "piece_filename", "read_manifest", "sha256_file",
           "verify_dir"]


def np_dtype(name: str) -> np.dtype:
    """``np.dtype`` for a manifest dtype string — including the ml_dtypes
    extensions (``bfloat16``/``float8_*``) plain numpy cannot parse."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def piece_filename(ordinal: int, name: str, piece: int) -> str:
    """Deterministic piece file name: entry ordinal (uniqueness even for
    os-hostile tensor names) + sanitized name (greppability) + piece
    index."""
    san = re.sub(r"[^A-Za-z0-9_.\-]", "_", name)[:80]
    return f"{ordinal:04d}_{san}.p{piece}{PIECE_SUFFIX}"


def sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def read_manifest(directory: str) -> dict:
    """Parse + structurally validate one checkpoint's manifest. Loud on
    every failure mode: no manifest (not a sharded checkpoint — or an
    uncommitted tmp dir), unparseable json, wrong format string."""
    path = os.path.join(directory, MANIFEST_NAME)
    if not os.path.exists(path):
        hint = ""
        try:
            parent, base = os.path.split(os.path.abspath(str(directory)))
            stranded = sorted(
                n for n in os.listdir(parent)
                if n.startswith(f"{TMP_PREFIX}old_{base}_"))
            if stranded and not os.path.exists(str(directory)):
                hint = (
                    "; an interrupted overwrite stranded the previous "
                    f"checkpoint COMPLETE at {stranded[-1]!r} — rename it "
                    f"back to {base!r} to recover")
        except OSError:
            pass
        raise FileNotFoundError(
            f"{directory!r} holds no {MANIFEST_NAME} — not a committed "
            "sharded checkpoint (an interrupted save leaves only a "
            f"'{TMP_PREFIX}*' dir, which is not loadable by design)"
            + hint)
    try:
        with open(path) as f:
            man = json.load(f)
    except ValueError as e:
        raise ValueError(
            f"{path}: manifest is unparseable ({e}) — the checkpoint "
            "commit was torn; restore from a complete checkpoint") from None
    if man.get("format") != FORMAT:
        raise ValueError(
            f"{path}: format {man.get('format')!r} is not {FORMAT!r}")
    return man


# ------------------------------------------------------------------ verify
def _piece_numel(index: List[List[int]]) -> int:
    n = 1
    for start, stop in index:
        n *= max(int(stop) - int(start), 0)
    return n


def _overlaps(a: List[List[int]], b: List[List[int]]) -> bool:
    return all(max(a0, b0) < min(a1, b1)
               for (a0, a1), (b0, b1) in zip(a, b))


def verify_dir(directory: str, *, deep: bool = True) -> List[dict]:
    """Integrity + completeness pass over one checkpoint directory.

    Returns ``[]`` when healthy, else one problem row per defect:
    ``{"kind", "tensor", "piece", "problem"}`` with kinds

    - ``manifest``:  missing/unparseable/wrong-format manifest,
    - ``missing``:   a manifest-referenced piece file absent on disk,
    - ``corrupt``:   piece byte count or sha256 (``deep=True``) mismatch,
    - ``mismatch``:  piece bounds outside the tensor, overlapping
      pieces, or a piece set that does not cover the tensor,
    - ``orphan``:    an unreferenced piece file or stale writer tmp dir.

    Shared by ``tools.ckpt verify`` (exit 1 on any row), the ``ckpt``
    lint family (CK95x) and the loader's own error paths.
    """
    problems: List[dict] = []
    try:
        man = read_manifest(directory)
    except (FileNotFoundError, ValueError) as e:
        return [{"kind": "manifest", "tensor": None, "piece": None,
                 "problem": str(e)}]
    referenced = set()
    for name, entry in man.get("entries", {}).items():
        shape = [int(d) for d in entry.get("shape", [])]
        numel = int(np.prod(shape)) if shape else 1
        itemsize = np_dtype(entry["dtype"]).itemsize
        covered = 0
        indexes = []
        for piece in entry.get("pieces", []):
            fname = piece["file"]
            referenced.add(fname)
            index = [[int(a), int(b)] for a, b in piece["index"]]
            if (len(index) != len(shape)
                    or any(a < 0 or b > d or a >= b
                           for (a, b), d in zip(index, shape))):
                if shape or index:  # scalar entries carry an empty index
                    problems.append({
                        "kind": "mismatch", "tensor": name, "piece": fname,
                        "problem": f"piece bounds {index} do not fit the "
                                   f"tensor shape {shape}"})
                    continue
            path = os.path.join(directory, fname)
            if not os.path.exists(path):
                problems.append({
                    "kind": "missing", "tensor": name, "piece": fname,
                    "problem": "manifest-referenced piece file is absent "
                               "— the checkpoint is INCOMPLETE"})
                continue
            want_bytes = _piece_numel(index) * itemsize if shape \
                else itemsize
            size = os.path.getsize(path)
            if size != int(piece.get("bytes", want_bytes)) \
                    or size != want_bytes:
                problems.append({
                    "kind": "corrupt", "tensor": name, "piece": fname,
                    "problem": f"piece holds {size} bytes, manifest "
                               f"promises {want_bytes} — truncated or "
                               "torn write"})
                continue
            if deep and sha256_file(path) != piece.get("sha256"):
                problems.append({
                    "kind": "corrupt", "tensor": name, "piece": fname,
                    "problem": "sha256 mismatch — the piece bytes rotted "
                               "or were torn mid-write"})
                continue
            for other in indexes:
                if shape and _overlaps(index, other):
                    problems.append({
                        "kind": "mismatch", "tensor": name, "piece": fname,
                        "problem": f"piece bounds {index} overlap another "
                                   f"piece's {other}"})
            indexes.append(index)
            covered += _piece_numel(index) if shape else 1
        if covered != numel:
            problems.append({
                "kind": "mismatch" if covered > numel else "missing",
                "tensor": name, "piece": None,
                "problem": f"pieces cover {covered}/{numel} elements — "
                           "the piece set does not reassemble the tensor"})
    referenced.add(MANIFEST_NAME)
    for fname in sorted(os.listdir(directory)):
        full = os.path.join(directory, fname)
        if os.path.isdir(full):
            if fname.startswith(TMP_PREFIX):
                problems.append({
                    "kind": "orphan", "tensor": None, "piece": fname,
                    "problem": "stale writer tmp dir — an interrupted "
                               "save's droppings; prune it"})
            continue
        if fname not in referenced and fname.endswith(PIECE_SUFFIX):
            problems.append({
                "kind": "orphan", "tensor": None, "piece": fname,
                "problem": "piece file referenced by no manifest entry"})
    return problems
