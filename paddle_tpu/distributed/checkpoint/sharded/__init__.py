"""Sharded-native checkpoint format + engine (ISSUE 15 tentpole).

``save_sharded(state, dir)`` writes one piece file per (tensor, shard)
straight from each device's shard — no host-side full-tensor gather —
under an atomic tmp+rename+fsync publish; ``load_sharded(dir, ...)``
restores via per-shard ``device_put`` + ``make_array_from_single_device_
arrays`` with cross-topology re-slice and optional dtype-converting
load. ``manifest.verify_dir`` / ``tools.ckpt`` / the ``ckpt`` lint
family audit the same on-disk index.
"""
from .engine import (convert_sharded, is_sharded_checkpoint,  # noqa: F401
                     load_sharded, load_sharded_into, load_sharded_like,
                     save_sharded)
from .manifest import (FORMAT, MANIFEST_NAME, read_manifest,  # noqa: F401
                       verify_dir)
