from .save_state_dict import save_state_dict, wait_async_save  # noqa: F401
from .load_state_dict import load_state_dict  # noqa: F401
from .sharded import (convert_sharded, is_sharded_checkpoint,  # noqa: F401
                      load_sharded, load_sharded_into, load_sharded_like,
                      save_sharded)
