"""Distributed checkpoint save.

Reference: distributed/checkpoint/save_state_dict.py:145 — each rank writes
its local shards plus a global metadata index enabling cross-topology resume.

TPU-native: arrays are *global* jax.Arrays whose shards live per-device; each
host writes only the shards it addresses (process-local), plus the
coordinator writes metadata (shapes/dtypes/chunk index). Because the on-disk
format is the global array (chunked), loading under ANY topology is a plain
device_put — load-time reshard is structural rather than a special pass.
Orbax-style async copy: the device->host transfer runs before serialization;
fsync off the training thread.

Multi-host commit protocol (the reference's all_gather_object discipline,
jax-native): per-rank chunk indices plus a coordinator nonce are
all-gathered across hosts BEFORE any IO so the coordinator's metadata
describes every rank's chunks; chunk keys and chunked shard filenames are
rank- AND nonce-qualified so a save never overwrites the files the previous
committed metadata references; each rank acks its durable shard with a
per-save nonce file; the coordinator renames metadata.json only after every
ack for THIS save landed (then GCs superseded nonce files) — a failed
commit leaves the previous checkpoint fully intact and loadable.
"""
from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Dict, Optional

import numpy as np

from ...core.tensor import Tensor
from ...observability.locks import named_lock
from ...reliability.faults import fault_point
from ...reliability.snapshot import fsync_dir


def _flatten_state(state_dict, prefix=""):
    flat = {}
    for k, v in state_dict.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            flat.update(_flatten_state(v, key + "."))
        elif isinstance(v, Tensor):
            flat[key] = v
        elif v is not None and hasattr(v, "shape"):
            flat[key] = Tensor(v)
    return flat


def _gather_object(obj):
    """All-gather one small JSON-serializable object per host — the public
    collective (communication.all_gather_object), list-returning."""
    from ..communication import all_gather_object

    out: list = []
    all_gather_object(out, obj)
    return out


# Pending async writers, keyed by checkpoint path so overlapping saves into
# different directories never join (or interleave with) each other. Failed
# async commits are recorded per path and re-raised by wait_async_save.
_pending_lock = named_lock("distributed.ckpt.pending")
_pending_writers: Dict[str, list] = {}
_pending_errors: Dict[str, Exception] = {}


def save_state_dict(state_dict: Dict, path: str, process_group=None, coordinator_rank: int = 0,
                    async_save: bool = False, format: str = "auto") -> None:
    """``format="sharded"`` routes through the manifest-format engine
    (``distributed.checkpoint.sharded``): one piece file per (tensor,
    shard) written straight from each device's shard — no host-side
    full-tensor gather, O(largest shard) peak host residency, sha256
    per piece, atomic tmp+rename publish. ``load_state_dict``
    auto-detects the format on read. ``"auto"`` (default) keeps the
    legacy npz layout — it remains the multi-host commit protocol;
    the sharded engine is single-writer-per-directory by design (each
    rank points at its own directory, the TrainSnapshotter idiom)."""
    from .. import env as env_mod

    if format == "sharded":
        from .sharded import save_sharded

        if async_save:
            raise ValueError(
                "save_state_dict(format='sharded') is synchronous — the "
                "sharded engine's atomic publish has no async writer yet")
        if env_mod.get_world_size() > 1:
            # the legacy branch below IS the multi-rank commit protocol
            # (rank-qualified chunks + gathered metadata + acks); the
            # sharded engine is single-writer-per-directory — racing
            # every rank's tmp/rename dance onto one path would collide
            # or last-writer-win with partial coverage
            raise ValueError(
                "save_state_dict(format='sharded') is single-writer: in a "
                f"multi-rank job (world_size={env_mod.get_world_size()}) "
                "point each rank at its own directory (e.g. "
                "f'{path}/rank{get_rank()}', the TrainSnapshotter idiom) "
                "or use the default format's multi-host commit protocol")
        save_sharded(state_dict, path, overwrite=True)
        return
    if format not in ("auto", "legacy"):
        raise ValueError(f"unknown checkpoint format {format!r} "
                         "(expected 'auto', 'legacy' or 'sharded')")
    os.makedirs(path, exist_ok=True)
    flat = _flatten_state(state_dict)
    rank = env_mod.get_rank()
    arrays = {}
    chunked = False  # did any array write host-local chunks (true multi-host)?
    meta = {"format": "paddle_tpu_dist_ckpt_v1", "world_size": env_mod.get_world_size(), "entries": {}}
    pending = {}  # k -> [(chunk_ordinal, host_array, index), ...]
    for k, t in flat.items():
        v = t._value
        entry = {"shape": list(v.shape), "dtype": str(np.dtype(v.dtype)), "chunks": []}
        if hasattr(v, "addressable_shards") and not getattr(v, "is_fully_addressable", True):
            chunked = True
            # multi-host: each host writes only the shards it addresses, once
            # per unique device slice (replicas dedup on replica_id==0).
            # Chunk keys are assigned after the gather, once the save's nonce
            # is known — key = {k}__r{rank}c{i}_{nonce}, so neither another
            # rank's chunks nor a PREVIOUS save's chunks can collide with
            # this save's in the merged shard namespace the loader builds.
            pending[k] = [
                (i, np.asarray(sh.data),
                 [[s.start or 0, s.stop if s.stop is not None else dim]
                  for s, dim in zip(sh.index, v.shape)])
                for i, sh in enumerate(v.addressable_shards)
                if sh.replica_id == 0]
        elif rank == coordinator_rank:
            arrays[k] = np.asarray(v)  # device->host once, before any disk IO
        meta["entries"][k] = entry

    t_start = time.time()  # GC horizon: never collect files newer than this
    nonce: Optional[str] = None
    ack_ranks: list = []
    if chunked:
        # Pre-IO metadata gather (the reference's all_gather_object step):
        # the coordinator's metadata must describe EVERY rank's chunks, and
        # the gathered nonce gives all ranks this save's identity for the
        # chunk keys, shard filename and durable-shard acks below. Runs on
        # the caller thread — collectives never run on the background writer.
        payload = {
            "rank": rank,
            "chunks": {k: [[i, index] for i, _, index in cs]
                       for k, cs in pending.items()},
            "nonce": uuid.uuid4().hex if rank == coordinator_rank else None,
        }
        gathered = _gather_object(payload)
        for got in gathered:
            if got["nonce"]:
                nonce = got["nonce"]
        if nonce is None:  # degenerate: coordinator absent from the gather
            nonce = "unknown"
        for got in gathered:
            if got["rank"] == rank:
                continue
            ack_ranks.append(got["rank"])
            for k, chunks in got["chunks"].items():
                if k in meta["entries"]:
                    meta["entries"][k]["chunks"].extend(
                        {"key": f"{k}__r{got['rank']}c{i}_{nonce}",
                         "index": index} for i, index in chunks)
        for k, cs in pending.items():
            for i, data, index in cs:
                ck = f"{k}__r{rank}c{i}_{nonce}"
                arrays[ck] = data
                meta["entries"][k]["chunks"].append({"key": ck, "index": index})

    def _write():
        # Atomic commit protocol (VERDICT r3 #8; reference
        # save_state_dict.py:145's tmp-then-finalize discipline): shard data
        # lands under .tmp names, is fsynced, renamed, then acked with this
        # save's nonce; the coordinator renames metadata.json only once every
        # rank's ack for THIS save is present — a crash at any point leaves
        # either the previous complete checkpoint or an ignorable set of
        # .tmp/ack files, never readable metadata pointing at missing or
        # stale shards. Fully-addressable saves (single host, or a rank
        # checkpointing its own state into a private dir, as the elastic path
        # does) skip the wait: the coordinator's own shard already holds
        # everything its metadata references. The device→host copies happened
        # before this thread started, so the training loop may already be
        # mutating (donated) device buffers.
        # Chunked shard files are nonce-qualified too: writing shard data for
        # save N+1 must not overwrite the files save N's metadata references
        # — if this commit fails, the PREVIOUS checkpoint must stay loadable
        # with its own (unclobbered) data, not a silent mix of two steps.
        # Stale nonce-files are GC'd by the coordinator after a successful
        # commit. The single-writer non-chunked path keeps the plain name:
        # its atomic replace is already sound.
        shard_final = os.path.join(
            path, f"shard_{rank}_{nonce}.npz" if chunked else f"shard_{rank}.npz")
        if rank == coordinator_rank:
            # the loader resolves PLAIN (non-chunked) keys from this file
            # specifically, so stale same-named keys in other shard files
            # can never shadow a committed save's values
            meta["coordinator_shard"] = os.path.basename(shard_final)
        shard_tmp = shard_final + ".tmp"
        with open(shard_tmp, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        # the injected torn-write point (reliability chaos): a crash here
        # leaves ONLY the fsynced .tmp file — metadata.json still points
        # at the previous complete checkpoint
        fault_point("ckpt.write")
        os.replace(shard_tmp, shard_final)
        # fsync the DIRECTORY too (the compile_cache/store.py discipline
        # completed): the rename itself must survive power loss, or a
        # committed metadata.json can reference a shard the directory
        # forgot (ISSUE 14 satellite)
        fsync_dir(path)
        if chunked:
            # durable-shard ack for this save. No pre-write cleanup here:
            # deleting "stale" acks from save N while its coordinator is
            # still polling would fail a commit whose shards all landed —
            # superseded acks are GC'd post-commit, where it is safe.
            with open(os.path.join(path, f"ack_{rank}_{nonce}"), "w") as f:
                f.flush()
                os.fsync(f.fileno())
        if rank == coordinator_rank:
            deadline = time.monotonic() + float(
                os.environ.get("PADDLE_CKPT_COMMIT_TIMEOUT_S", "600"))
            missing = list(ack_ranks)
            while missing and time.monotonic() < deadline:
                missing = [r for r in missing if not os.path.exists(
                    os.path.join(path, f"ack_{r}_{nonce}"))]
                if missing:
                    time.sleep(0.05)
            if missing:
                raise RuntimeError(
                    f"checkpoint {path} NOT committed: no durable-shard ack "
                    f"from ranks {missing} within timeout; metadata.json left "
                    "unwritten so the previous checkpoint (if any) stays the "
                    "valid one")
            meta_final = os.path.join(path, "metadata.json")
            meta_tmp = meta_final + ".tmp"
            with open(meta_tmp, "w") as f:
                json.dump(meta, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(meta_tmp, meta_final)
            fsync_dir(path)  # the commit rename must be durable too
            # GC: nonce-qualified shards/acks from superseded saves are
            # unreferenced now that this save's metadata is committed. Runs
            # for non-chunked commits too — a single-host save into a dir
            # that previously held a chunked save must clear the stale
            # nonce-shards, or the loader's merge would let their plain keys
            # shadow the fresh ones. Only files comfortably older than THIS
            # save's start are collected: other hosts' writers chain
            # per-process, so an overlapping save N+1 may already have
            # durable files here — they are newer than t_start and must
            # survive save N's GC. The skew margin absorbs NFS server clock
            # offset and coarse mtime granularity; a file that survives one
            # GC for being too new is collected by a later save's.
            skew = float(os.environ.get("PADDLE_CKPT_GC_SKEW_S", "60"))
            for old in os.listdir(path):
                if old.endswith(".tmp"):
                    continue
                parts = (old[:-4] if old.endswith(".npz") else old).split("_")
                if (len(parts) == 3 and parts[0] in ("shard", "ack")
                        and parts[2] != nonce):
                    try:
                        full = os.path.join(path, old)
                        if os.path.getmtime(full) < t_start - skew:
                            os.remove(full)
                    except OSError:
                        pass

    def _write_retried():
        # bounded retry (ISSUE 14): a transient disk fault mid-commit is
        # replayed — safe because every piece of _write is idempotent
        # (same tmp-then-rename names, same ack file, same metadata) —
        # while a fatal error (or exhausted budget) propagates with the
        # previous checkpoint still the committed one
        from ...reliability.policy import RetryPolicy

        RetryPolicy("ckpt.write", max_delay_s=0.5).run(_write)

    if async_save:
        # Writers for the SAME path are chained: save N+1's writer first
        # joins save N's, so overlapping async saves can never interleave
        # their shard writes, acks, or GC (a later save's GC would delete
        # files an earlier in-flight commit still references). The thread is
        # started INSIDE the lock so every queued thread is joinable, and it
        # stays queued until _join_writers prunes it after completion.
        with _pending_lock:
            queue = _pending_writers.setdefault(path, [])
            prev_th = queue[-1] if queue else None

            def _guarded():
                if prev_th is not None:
                    prev_th.join()
                try:
                    _write_retried()
                except Exception as e:  # surfaced by wait_async_save
                    from ...base.log import get_logger

                    get_logger().warning(
                        "async checkpoint save to %s failed: %s", path, e)
                    with _pending_lock:
                        _pending_errors.setdefault(path, e)

            th = threading.Thread(target=_guarded, daemon=False)
            queue.append(th)
            th.start()
    else:
        # a sync save must not interleave with in-flight async writers for
        # the same path (same tmp names, and its GC would delete files an
        # uncommitted async save still references)
        _join_writers(path)
        _write_retried()


def _join_writers(path: str):
    """Join every pending writer for ``path`` (all paths when None). Threads
    stay in the queue until they are DONE — popping before the join would
    let a concurrent save chain onto nothing and interleave with a writer
    that is still running."""
    while True:
        with _pending_lock:
            if path is None:
                targets = list(_pending_writers)
            else:
                targets = [path] if path in _pending_writers else []
            th = None
            for target in targets:
                writers = _pending_writers.get(target, [])
                writers[:] = [t for t in writers if t.is_alive()]
                if writers:
                    th = writers[-1]  # the chain tail joins the whole chain
                    break
                _pending_writers.pop(target, None)
        if th is None:
            return
        th.join()


def wait_async_save(path: str = None):
    """Join pending async writers — all of them, or only those for ``path``.
    Raises the first recorded commit failure for the joined path(s)."""
    _join_writers(path)
    with _pending_lock:
        if path is None:
            errs = list(_pending_errors.values())
            _pending_errors.clear()
        else:
            err = _pending_errors.pop(path, None)
            errs = [err] if err else []
    if errs:
        raise errs[0]
