"""Distributed checkpoint save.

Reference: distributed/checkpoint/save_state_dict.py:145 — each rank writes
its local shards plus a global metadata index enabling cross-topology resume.

TPU-native: arrays are *global* jax.Arrays whose shards live per-device; each
host writes only the shards it addresses (process-local), plus rank-0 writes
metadata (shapes/dtypes/shardings). Because the on-disk format is the global
array (chunked), loading under ANY topology is a plain device_put — load-time
reshard is structural rather than a special pass. Orbax-style async copy: the
device->host transfer runs before serialization; fsync off the training
thread.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Dict

import numpy as np

from ...core.tensor import Tensor


def _flatten_state(state_dict, prefix=""):
    flat = {}
    for k, v in state_dict.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            flat.update(_flatten_state(v, key + "."))
        elif isinstance(v, Tensor):
            flat[key] = v
        elif v is not None and hasattr(v, "shape"):
            flat[key] = Tensor(v)
    return flat


_pending_writers = []


def save_state_dict(state_dict: Dict, path: str, process_group=None, coordinator_rank: int = 0,
                    async_save: bool = False) -> None:
    from .. import env as env_mod

    os.makedirs(path, exist_ok=True)
    flat = _flatten_state(state_dict)
    rank = env_mod.get_rank()
    arrays = {}
    meta = {"format": "paddle_tpu_dist_ckpt_v1", "world_size": env_mod.get_world_size(), "entries": {}}
    for k, t in flat.items():
        v = t._value
        entry = {"shape": list(v.shape), "dtype": str(np.dtype(v.dtype)), "chunks": []}
        if hasattr(v, "addressable_shards") and not getattr(v, "is_fully_addressable", True):
            # multi-host: each host writes only the shards it addresses, once
            # per unique device slice (replicas dedup on replica_id==0)
            for i, sh in enumerate(v.addressable_shards):
                if sh.replica_id != 0:
                    continue
                ck = f"{k}__chunk{i}"
                arrays[ck] = np.asarray(sh.data)
                entry["chunks"].append({
                    "key": ck,
                    "index": [[s.start or 0, s.stop if s.stop is not None else dim]
                              for s, dim in zip(sh.index, v.shape)],
                })
        elif rank == coordinator_rank:
            arrays[k] = np.asarray(v)  # device->host once, before any disk IO
        meta["entries"][k] = entry

    def _write():
        # Atomic commit protocol (VERDICT r3 #8; reference
        # save_state_dict.py:145's tmp-then-finalize discipline): shard data
        # lands under .tmp names, is fsynced, renamed, and ONLY THEN does the
        # coordinator rename metadata.json into place — a crash at any point
        # leaves either the previous complete checkpoint or an ignorable set
        # of .tmp files, never a readable-but-partial one. The device→host
        # copies happened above, before this thread started, so the training
        # loop may already be mutating (donated) device buffers.
        shard_final = os.path.join(path, f"shard_{rank}.npz")
        shard_tmp = shard_final + ".tmp"
        with open(shard_tmp, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(shard_tmp, shard_final)
        if rank == coordinator_rank:
            meta_final = os.path.join(path, "metadata.json")
            meta_tmp = meta_final + ".tmp"
            with open(meta_tmp, "w") as f:
                json.dump(meta, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(meta_tmp, meta_final)

    if async_save:
        th = threading.Thread(target=_write, daemon=False)
        th.start()
        _pending_writers.append(th)
    else:
        _write()


def wait_async_save():
    while _pending_writers:
        _pending_writers.pop().join()
