"""DataParallel + parallel-env entry points.

Reference: `paddle.DataParallel` (python/paddle/distributed/parallel.py:219)
wraps a Layer and hooks a C++ Reducer (paddle/fluid/distributed/collective/
reducer.cc) that buckets gradients and overlaps NCCL allreduce with backward.

TPU-native design: none of that machinery exists because XLA *is* the reducer.
Parameters are laid out replicated over the mesh; the batch is sharded over
the `dp` axis. Under GSPMD, the backward of a replicated->sharded use is a
psum — the gradient allreduce — which XLA's latency-hiding scheduler overlaps
with the rest of the backward automatically, fused and bucketed better than a
hand-written reducer. `no_sync` falls out as not-yet-averaged local grads only
in multi-controller mode; in single-controller SPMD it is a no-op context.
"""
from __future__ import annotations

import contextlib

from jax.sharding import NamedSharding, PartitionSpec as P

import jax

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from . import env as env_mod
from .env import init_parallel_env  # re-export  # noqa: F401


def _shard_value(value, mesh, spec):
    return jax.device_put(value, NamedSharding(mesh, spec))


def shard_batch(x, mesh=None, axis: str = "dp", dim: int = 0):
    """Place a host batch sharded along the data axis (the input pipeline's
    device_put; reference: DataLoader places on each rank's GPU)."""
    mesh = mesh or env_mod.get_mesh()
    if mesh.shape[axis] == 1:
        return x
    spec = [None] * getattr(x, "ndim", 1)
    spec[dim] = axis
    if isinstance(x, Tensor):
        x._replace_value(_shard_value(x._value, mesh, P(*spec)))
        return x
    return _shard_value(x, mesh, P(*spec))


def replicate_layer(layer: Layer, mesh=None):
    """Pin every parameter/buffer replicated over the mesh (so GSPMD sees an
    explicit layout rather than single-device arrays)."""
    mesh = mesh or env_mod.get_mesh()
    for p in layer.parameters(include_sublayers=True):
        if p._placements is None:  # keep explicit TP/auto-parallel placements
            p._replace_value(_shard_value(p._value, mesh, P()))
    for _, buf in layer.named_buffers():
        if buf._placements is None:
            buf._replace_value(_shard_value(buf._value, mesh, P()))
    return layer


class DataParallel(Layer):
    """Data-parallel wrapper (reference parallel.py:219).

    Usage matches the reference: model = paddle.DataParallel(model); the
    wrapper shards `Tensor` positional inputs along dim 0 over the `dp` mesh
    axis and replicates parameters. Gradient synchronization is implicit in
    XLA's partitioning of the backward.
    """

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25, last_comm_buffer_size=1,
                 find_unused_parameters=False, group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        self._mesh = env_mod.get_mesh()
        self._dp_axis = "dp"
        replicate_layer(layers, self._mesh)

    def forward(self, *inputs, **kwargs):
        sharded = tuple(
            shard_batch(x, self._mesh, self._dp_axis) if isinstance(x, Tensor) else x
            for x in inputs
        )
        return self._layers(*sharded, **kwargs)

    @contextlib.contextmanager
    def no_sync(self):
        """Grad-sync-free region (reference parallel.py DataParallel.no_sync).
        In single-controller SPMD gradients are only materialized at step
        boundaries, so accumulation without sync is already the default."""
        yield

    # state passthrough: checkpoints see the inner layer's names
    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, state_dict, *a, **k):
        return self._layers.set_state_dict(state_dict, *a, **k)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def train(self):
        self._layers.train()
        return super().train()

    def eval(self):
        self._layers.eval()
        return super().eval()


class ParallelEnv:
    """Env-var view compat (reference base/dygraph ParallelEnv)."""

    @property
    def rank(self):
        return env_mod.get_rank()

    local_rank = rank

    @property
    def world_size(self):
        return env_mod.get_world_size()

    nranks = world_size

    @property
    def device_id(self):
        return 0

    @property
    def dev_id(self):
        return 0
