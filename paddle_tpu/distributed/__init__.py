"""paddle_tpu.distributed — the distributed stack, TPU-native.

Public surface mirrors python/paddle/distributed/__init__.py: bootstrap
(init_parallel_env/get_rank/...), functional collectives, DataParallel, fleet
(hybrid parallelism), auto_parallel (shard_tensor/reshard/...), sharding,
checkpoint, launch. Implementation: ONE device mesh + XLA collectives
(SURVEY.md §2.14 "comm backend inventory" TPU-native column).
"""
from __future__ import annotations

from .env import (  # noqa: F401
    HYBRID_AXES,
    ParallelEnv as _Env,
    barrier,
    get_mesh,
    get_rank,
    get_world_size,
    init_parallel_env,
    is_initialized,
    set_mesh,
)
from .communication import (  # noqa: F401
    Group,
    P2POp,
    ReduceOp,
    all_gather,
    all_gather_object,
    all_reduce,
    all_to_all,
    all_to_all_single,
    alltoall,
    batch_isend_irecv,
    broadcast,
    gather,
    get_backend,
    get_group,
    irecv,
    isend,
    new_group,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
    shift,
    wait,
)
from .parallel import DataParallel, ParallelEnv, shard_batch  # noqa: F401
from .spmd import spmd, spmd_region, in_spmd_region  # noqa: F401

from . import fleet  # noqa: F401
from .auto_parallel.process_mesh import ProcessMesh  # noqa: F401
from .auto_parallel.placement_type import Partial, Placement, Replicate, Shard  # noqa: F401
from .auto_parallel.api import (  # noqa: F401
    dtensor_from_fn,
    reshard,
    shard_layer,
    shard_optimizer,
    shard_tensor,
    unshard_dtensor,
)
from . import checkpoint  # noqa: F401
from . import sharding  # noqa: F401
from . import ps  # noqa: F401
from . import rpc  # noqa: F401
from . import auto_tuner  # noqa: F401
from .utils import moe_utils  # noqa: F401
from .spawn import spawn  # noqa: F401


def __getattr__(name):
    # native TCPStore loads lazily (compiles the native lib on first use)
    if name == "TCPStore":
        from ..native import TCPStore

        return TCPStore
    raise AttributeError(f"module 'paddle_tpu.distributed' has no attribute {name!r}")


def get_world_process_group():
    from .communication import get_group

    return get_group(0)


def is_available() -> bool:
    return True
