"""SPMD region: run eager paddle code per-device over mesh axes.

This is the rebuild's analog of the reference's "each process runs the same
script" model (test/legacy_test/test_dist_base.py multi-process harness): with
a single python controller, per-rank code lives inside a `shard_map` region.
`paddle_tpu.distributed` collectives called inside the region lower to XLA
collectives (lax.psum / all_gather / ppermute / all_to_all) over the named
mesh axes — the NCCL-ring replacement (SURVEY.md §2.14 comm backend row).

Tensor is a jax pytree node, so paddle functions cross the shard_map boundary
unchanged.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional, Sequence

import jax
from jax.sharding import PartitionSpec as P

from ..base.jax_compat import shard_map as _shard_map
from . import env as env_mod

_tls = threading.local()


def _region_stack():
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    return _tls.stack


def current_region_axes() -> Optional[tuple]:
    stack = _region_stack()
    return stack[-1] if stack else None


def in_spmd_region() -> bool:
    return bool(_region_stack())


@contextlib.contextmanager
def spmd_region(axes: Sequence[str]):
    _region_stack().append(tuple(axes))
    try:
        yield
    finally:
        _region_stack().pop()


def spmd(fn=None, *, mesh=None, in_specs=None, out_specs=None, axes=None, check_vma=False):
    """Wrap ``fn`` to run per-device over the mesh (collectives enabled).

    in_specs/out_specs: PartitionSpec pytrees as in shard_map; default
    fully-replicated in, fully-replicated out. axes: which mesh axes the body
    communicates over (defaults to all mesh axes).
    """
    if fn is None:
        import functools

        return functools.partial(spmd, mesh=mesh, in_specs=in_specs, out_specs=out_specs, axes=axes, check_vma=check_vma)

    def wrapped(*args):
        from ..core.dispatch import primitive
        from ..core.tensor import Tensor

        m = mesh or env_mod.get_mesh()
        region_axes = tuple(axes) if axes is not None else tuple(m.axis_names)
        ispecs = in_specs if in_specs is not None else P()
        ospecs = out_specs if out_specs is not None else P()

        def body(*vals):
            # stop_gradient=True: no inner tape — the OUTER primitive's
            # jax.vjp differentiates through the whole shard_map region, so
            # non-differentiable collectives (pmax/pmin) stay usable in
            # forward-only paths.
            tensors = [Tensor(v, stop_gradient=True) for v in vals]
            with spmd_region(region_axes):
                out = fn(*tensors)
            if isinstance(out, (tuple, list)):
                return tuple(o._value if isinstance(o, Tensor) else o for o in out)
            return out._value if isinstance(out, Tensor) else out

        smapped = _shard_map(body, mesh=m, in_specs=ispecs, out_specs=ospecs, check_vma=check_vma)

        # route through the dispatcher so the eager tape links across the
        # shard_map boundary (jax.vjp differentiates through shard_map).
        # The engaged comm wire dtype rides along as a static attr: the
        # kernel cache keys on it, so flipping FLAGS_comm_quantize_dp_grads
        # (or an amp comm_dtype region) retraces the region instead of
        # replaying the other tier's cached executable
        from .collective_opt import engaged_comm_dtype

        def call(*vals, comm_dtype="fp32"):
            del comm_dtype  # cache-key material only; the body reads policy
            return smapped(*vals)

        return primitive("spmd_region", call, list(args),
                         attrs={"comm_dtype": engaged_comm_dtype() or "fp32"})

    return wrapped
