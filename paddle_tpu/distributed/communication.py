"""Functional collectives + Group.

Rebuild of python/paddle/distributed/communication/* (all_reduce.py:29 et al)
and the Group abstraction (communication/group.py:29). The reference backs
these with ProcessGroupNCCL (paddle/fluid/distributed/collective/
process_group_nccl.h:37); here a Group is a *view over mesh axes* and every
collective lowers to the matching XLA collective:

    all_reduce      -> lax.psum / pmax / pmin
    all_gather      -> lax.all_gather
    reduce_scatter  -> lax.psum_scatter
    all_to_all      -> lax.all_to_all
    broadcast       -> select + psum (root's shard broadcast)
    send/recv       -> lax.ppermute
    scatter/gather  -> slice / all_gather at root

Semantics by execution context:
- inside an SPMD region (paddle_tpu.distributed.spmd) these are the per-device
  collectives over the group's mesh axes — the hot path used by TP/PP/MoE/ring
  attention, differentiable (JAX supplies collective VJPs: psum<->identity,
  all_gather<->psum_scatter, ...);
- outside a region, collectives act at the *process* level (multi-host eager):
  with one controller per host group, world_size==jax.process_count(); on a
  single process they are the world-size-1 identity, matching the reference's
  behavior on one rank.

All in-place-style ops mutate the Tensor payload through _replace_value so the
jit functionalizer records them (see paddle_tpu/jit/functionalize.py).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.dispatch import primitive, passthrough
from ..core.tensor import Tensor
from . import env as env_mod
from .spmd import current_region_axes, in_spmd_region


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class Group:
    """A communicator: one or more mesh axes (reference Group: communication/
    group.py:29; ProcessGroup: paddle/phi/core/distributed/collective/
    process_group.h:48)."""

    def __init__(self, axes: Sequence[str], gid: int = 0, name: Optional[str] = None):
        self.axes = tuple(axes)
        self.id = gid
        self.name = name or ("world" if gid == 0 else f"group_{gid}")

    @property
    def nranks(self) -> int:
        mesh = env_mod.get_mesh()
        n = 1
        for ax in self.axes:
            n *= mesh.shape[ax]
        return n

    world_size = nranks

    @property
    def rank(self) -> int:
        # process-level view; per-device rank exists only inside spmd regions
        return env_mod.get_rank() % max(self.nranks, 1)

    def get_group_rank(self, rank):
        return rank

    @property
    def process_ids(self) -> List[int]:
        return list(range(self.nranks))

    ranks = process_ids

    def __repr__(self):
        return f"Group(axes={self.axes}, nranks={self.nranks})"


_groups: dict = {}
_next_gid = [1]


def _world_group() -> Group:
    if 0 not in _groups:
        mesh = env_mod.get_mesh()
        _groups[0] = Group(tuple(mesh.axis_names), gid=0)
    return _groups[0]


def get_group(gid: int = 0) -> Group:
    if gid == 0:
        return _world_group()
    return _groups[gid]


def new_group(ranks=None, backend=None, axes: Optional[Sequence[str]] = None, timeout=None) -> Group:
    """Create a communicator. TPU-native callers pass mesh ``axes``; the
    rank-list form (reference new_group) is honored for the world set and for
    contiguous sub-axis groups."""
    gid = _next_gid[0]
    _next_gid[0] += 1
    if axes is not None:
        g = Group(axes, gid=gid)
    else:
        world = _world_group()
        g = Group(world.axes, gid=gid)
        if ranks is not None and len(ranks) not in (0, world.nranks):
            # A proper-subset rank list has no mesh-axis representation here;
            # honoring it silently would reduce over the whole world. Callers
            # wanting subgroups pass axes= (fleet topology does).
            raise NotImplementedError(
                "new_group(ranks=<proper subset>) has no mesh-axis mapping; "
                "pass axes=... (e.g. axes=('dp',)) to communicate over a mesh axis"
            )
    _groups[gid] = g
    return g


def _axes_of(group: Optional[Group]):
    g = group if group is not None else _world_group()
    # restrict to axes live in the current spmd region, if any
    region = current_region_axes()
    if region is not None:
        axes = tuple(ax for ax in g.axes if ax in region)
        return axes if axes else tuple(region)
    return g.axes


def _group_size(group: Optional[Group]) -> int:
    g = group if group is not None else _world_group()
    return g.nranks


def _axes_nranks(axes) -> int:
    """Rank count across a set of mesh axes (the region-restricted group
    size a scatter chunk divides over)."""
    mesh = env_mod.get_mesh()
    n = 1
    for ax in axes:
        n *= int(mesh.shape[ax])
    return n


# --------------------------------------------------------------------- helpers
def _eager_world() -> int:
    return jax.process_count()


def _identity_inplace(tensor: Tensor) -> Tensor:
    return tensor


# --------------------------------------------------------------------- ops
def all_reduce(tensor: Tensor, op=ReduceOp.SUM, group: Optional[Group] = None, sync_op=True,
               quantized=None):
    """In-place allreduce (reference communication/all_reduce.py:29).

    ``quantized`` opts one SUM allreduce in/out of the blockwise-int8
    qpsum tier (collective_opt) regardless of the process-wide
    engagement (``FLAGS_comm_quantize_dp_grads`` /
    ``amp.auto_cast(comm_dtype="int8")``); ``None`` defers to that
    policy. Non-SUM ops, non-float dtypes and tensors below
    ``FLAGS_comm_quantize_min_bytes`` always ride full precision.
    """
    from ..reliability.faults import fault_point

    fault_point("collective")  # chaos hook: a failed/slow collective entry
    if in_spmd_region():
        axes = _axes_of(group)
        from . import collective_opt as _copt

        decision = _copt.quantize_decision(
            tensor._value, is_sum=(op == ReduceOp.SUM), axes=axes,
            explicit=quantized)

        def fn(x):
            if decision.quantize:
                return _copt.qpsum_lax(x, decision.axis, decision.axis_size,
                                       decision.block)
            if op == ReduceOp.SUM:
                return lax.psum(x, axes)
            if op == ReduceOp.MAX:
                return lax.pmax(x, axes)
            if op == ReduceOp.MIN:
                return lax.pmin(x, axes)
            if op == ReduceOp.AVG:
                return lax.pmean(x, axes)
            if op == ReduceOp.PROD:
                return lax.pprod(x, axes)
            raise ValueError(f"unknown ReduceOp {op}")

        out = primitive("all_reduce", fn, [tensor])
        tensor._replace_value(out._value)
        tensor.stop_gradient = out.stop_gradient
        tensor._grad_node = out._grad_node
        tensor._output_index = out._output_index
        return tensor
    if _eager_world() == 1:
        return _identity_inplace(tensor)
    from jax.experimental import multihost_utils

    summed = multihost_utils.process_allgather(tensor._value)
    if op == ReduceOp.SUM:
        red = summed.sum(axis=0)
    elif op == ReduceOp.MAX:
        red = summed.max(axis=0)
    elif op == ReduceOp.MIN:
        red = summed.min(axis=0)
    elif op == ReduceOp.AVG:
        red = summed.mean(axis=0)
    else:
        red = np.prod(summed, axis=0)
    tensor._replace_value(jnp.asarray(red))
    return tensor


def all_gather(tensor_list: Optional[List], tensor: Tensor, group: Optional[Group] = None, sync_op=True, axis: int = 0):
    """reference communication/all_gather.py. Inside spmd regions, returns the
    concatenated tensor (list API filled with per-rank slices)."""
    if in_spmd_region():
        axes = _axes_of(group)
        out = primitive(
            "all_gather",
            lambda x: lax.all_gather(x, axes, axis=0, tiled=False).reshape((-1,) + x.shape),
            [tensor],
        )
        if tensor_list is not None:
            n = out._value.shape[0]
            from ..ops import manipulation

            tensor_list.clear()
            tensor_list.extend(manipulation.unbind(out, 0))
        return out
    if _eager_world() == 1:
        if tensor_list is not None:
            tensor_list.clear()
            tensor_list.append(tensor)
        from ..ops import manipulation

        return manipulation.unsqueeze(tensor, 0)
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(tensor._value)
    out = Tensor(jnp.asarray(gathered))
    if tensor_list is not None:
        tensor_list.clear()
        tensor_list.extend(Tensor(g) for g in gathered)
    return out


def all_gather_object(object_list: List, obj, group=None):
    """reference communication/all_gather.py::all_gather_object — one small
    JSON-serializable object per host, gathered across all hosts. jax-native:
    two process_allgathers over a padded uint8 encoding (lengths, then
    payloads) — the host-RPC-free equivalent of torch's pickle gather."""
    import json

    import numpy as np

    object_list.clear()
    if _eager_world() == 1:
        object_list.append(obj)
        return
    from jax.experimental import multihost_utils

    data = np.frombuffer(json.dumps(obj).encode(), np.uint8)
    sizes = np.asarray(multihost_utils.process_allgather(
        np.array([data.size], np.int32))).reshape(-1)
    cap = int(sizes.max())
    padded = np.zeros(cap, np.uint8)
    padded[: data.size] = data
    gathered = np.asarray(multihost_utils.process_allgather(padded))
    gathered = gathered.reshape(len(sizes), cap)
    object_list.extend(json.loads(bytes(gathered[i, : sizes[i]]).decode())
                       for i in range(len(sizes)))


def reduce_scatter(tensor: Tensor, tensor_or_list, op=ReduceOp.SUM, group: Optional[Group] = None, sync_op=True):
    """reference communication/reduce_scatter.py — scatter dim 0."""
    from ..reliability.faults import fault_point

    fault_point("collective")  # chaos hook: a failed/slow collective entry
    src = tensor_or_list
    if isinstance(src, (list, tuple)):
        from ..ops import manipulation

        src = manipulation.concat(list(src), 0)
    if in_spmd_region():
        axes = _axes_of(group)
        if op == ReduceOp.SUM:
            fn = lambda x: lax.psum_scatter(x, axes, scatter_dimension=0, tiled=True)  # noqa: E731
        elif op in (ReduceOp.MAX, ReduceOp.MIN):
            # XLA has no fused max/min reduce-scatter: pmax/pmin the full
            # buffer, then each rank keeps its dim-0 chunk (one extra pass
            # of residency, same comm volume as an all-reduce)
            red = lax.pmax if op == ReduceOp.MAX else lax.pmin

            def fn(x):
                full = red(x, axes)
                n = _axes_nranks(axes)
                if full.shape[0] % n != 0:
                    # same loud contract as the SUM path (tiled
                    # psum_scatter): never silently drop trailing rows
                    raise ValueError(
                        f"reduce_scatter: scatter dimension size "
                        f"{full.shape[0]} must be divisible by the group's "
                        f"{n} ranks")
                chunk = full.shape[0] // n
                idx = _linear_axis_index(axes)
                return lax.dynamic_slice_in_dim(full, idx * chunk, chunk, axis=0)
        else:
            name = {ReduceOp.PROD: "PROD", ReduceOp.AVG: "AVG"}.get(op, repr(op))
            raise NotImplementedError(
                f"reduce_scatter(op=ReduceOp.{name}) is not supported on "
                "XLA; supported reductions: SUM (lax.psum_scatter), MAX and "
                "MIN (lax.pmax/pmin + local slice)")
        out = primitive("reduce_scatter", fn, [src])
        tensor._replace_value(out._value)
        tensor.stop_gradient = out.stop_gradient
        tensor._grad_node = out._grad_node
        tensor._output_index = out._output_index
        return tensor
    if _eager_world() == 1:
        tensor._replace_value(src._value)
        return tensor
    raise NotImplementedError("process-level reduce_scatter: wrap the step in dist.spmd")


def all_to_all(out_tensor_list, in_tensor_list, group: Optional[Group] = None, sync_op=True):
    """reference communication/all_to_all.py — also the Ulysses/MoE primitive."""
    from ..ops import manipulation

    if isinstance(in_tensor_list, Tensor):
        stacked = in_tensor_list
    else:
        stacked = manipulation.stack(list(in_tensor_list), 0)
    if in_spmd_region():
        axes = _axes_of(group)
        out = primitive(
            "all_to_all",
            lambda x: lax.all_to_all(x, axes, split_axis=0, concat_axis=0, tiled=False),
            [stacked],
        )
    else:
        if _eager_world() != 1:
            raise NotImplementedError("process-level all_to_all: wrap the step in dist.spmd")
        out = stacked
    if out_tensor_list is not None:
        out_tensor_list.clear()
        out_tensor_list.extend(manipulation.unbind(out, 0))
    return out


def alltoall(in_tensor_or_list, out_tensor_list=None, group=None, sync_op=True):
    return all_to_all(out_tensor_list, in_tensor_or_list, group=group, sync_op=sync_op)


def all_to_all_single(out_tensor, in_tensor, in_split_sizes=None, out_split_sizes=None, group=None, sync_op=True):
    if in_split_sizes or out_split_sizes:
        raise NotImplementedError("uneven all_to_all splits are not supported on XLA; pad to equal splits")
    if in_spmd_region():
        axes = _axes_of(group)
        out = primitive(
            "all_to_all_single",
            lambda x: lax.all_to_all(x, axes, split_axis=0, concat_axis=0, tiled=True),
            [in_tensor],
        )
    else:
        if _eager_world() != 1:
            raise NotImplementedError("process-level all_to_all: wrap the step in dist.spmd")
        out = in_tensor
    if out_tensor is not None:
        out_tensor._replace_value(out._value)
        out_tensor._grad_node = out._grad_node
        out_tensor._output_index = out._output_index
        out_tensor.stop_gradient = out.stop_gradient
        return out_tensor
    return out


def broadcast(tensor: Tensor, src: int = 0, group: Optional[Group] = None, sync_op=True):
    """reference communication/broadcast.py — root rank's value to all."""
    if in_spmd_region():
        axes = _axes_of(group)

        def fn(x):
            idx = lax.axis_index(axes[0]) if len(axes) == 1 else _linear_axis_index(axes)
            masked = jnp.where(idx == src, x, jnp.zeros_like(x))
            return lax.psum(masked, axes)

        out = primitive("broadcast", fn, [tensor])
        tensor._replace_value(out._value)
        tensor._grad_node = out._grad_node
        tensor._output_index = out._output_index
        tensor.stop_gradient = out.stop_gradient
        return tensor
    if _eager_world() == 1:
        return _identity_inplace(tensor)
    from jax.experimental import multihost_utils

    val = multihost_utils.broadcast_one_to_all(tensor._value, is_source=env_mod.get_rank() == src)
    tensor._replace_value(jnp.asarray(val))
    return tensor


def _linear_axis_index(axes):
    idx = lax.axis_index(axes[0])
    for ax in axes[1:]:
        idx = idx * lax.axis_size(ax) + lax.axis_index(ax)
    return idx


def reduce(tensor: Tensor, dst: int = 0, op=ReduceOp.SUM, group: Optional[Group] = None, sync_op=True):
    """All ranks compute the reduction; non-dst ranks simply keep it (XLA has
    no cheaper rooted reduce on a torus)."""
    return all_reduce(tensor, op=op, group=group, sync_op=sync_op)


def scatter(tensor: Tensor, tensor_list=None, src: int = 0, group: Optional[Group] = None, sync_op=True):
    """reference communication/scatter.py — root's list scattered over ranks."""
    from ..ops import manipulation

    if in_spmd_region():
        axes = _axes_of(group)
        stacked = manipulation.stack(list(tensor_list), 0) if tensor_list else tensor

        def fn(x):
            idx = _linear_axis_index(axes)
            return lax.dynamic_index_in_dim(x, idx, axis=0, keepdims=False)

        out = primitive("scatter", fn, [stacked])
        tensor._replace_value(out._value)
        tensor._grad_node = out._grad_node
        tensor._output_index = out._output_index
        tensor.stop_gradient = out.stop_gradient
        return tensor
    if _eager_world() == 1:
        if tensor_list:
            tensor._replace_value(tensor_list[src]._value)
        return tensor
    raise NotImplementedError("process-level scatter: wrap the step in dist.spmd")


def gather(tensor: Tensor, gather_list=None, dst: int = 0, group=None, sync_op=True):
    return all_gather(gather_list, tensor, group=group, sync_op=sync_op)


def send(tensor: Tensor, dst: int = 0, group: Optional[Group] = None, sync_op=True):
    """Point-to-point send (reference communication/send.py).

    Rank-divergent standalone send/recv is MPMD; a single SPMD program cannot
    express "my dst differs per rank" from one call site. Inside spmd regions
    use `shift` (ring offset) or `batch_isend_irecv` with P2POp(offset=...) —
    that is how the pipeline runtime exchanges stage activations.
    """
    if in_spmd_region():
        raise NotImplementedError(
            "standalone send() inside an spmd region: use dist.shift(tensor, offset) "
            "or batch_isend_irecv with P2POp offsets (ring semantics)"
        )
    if _eager_world() == 1:
        raise ValueError("send to self on a 1-process world")
    raise NotImplementedError("process-level p2p: wrap the step in dist.spmd")


def recv(tensor: Tensor, src: int = 0, group: Optional[Group] = None, sync_op=True):
    if in_spmd_region():
        raise NotImplementedError(
            "standalone recv() inside an spmd region: use dist.shift(tensor, offset) "
            "or batch_isend_irecv with P2POp offsets (ring semantics)"
        )
    if _eager_world() == 1:
        raise ValueError("recv from self on a 1-process world")
    raise NotImplementedError("process-level p2p: wrap the step in dist.spmd")


def shift(tensor: Tensor, offset: int = 1, group: Optional[Group] = None):
    """Ring shift over the group's (single) axis — the PP/ring-attention
    primitive. rank i's tensor goes to rank (i+offset)%n."""
    axes = _axes_of(group)
    ax = axes[0]
    n = env_mod.get_mesh().shape[ax]
    perm = [(i, (i + offset) % n) for i in range(n)]
    return primitive("shift", lambda x: lax.ppermute(x, ax, perm), [tensor])


def isend(tensor, dst=0, group=None):
    return _Task(send(tensor, dst, group))


def irecv(tensor, src=0, group=None):
    return _Task(recv(tensor, src, group))


class _Task:
    """Async task handle (reference ProcessGroup::Task). XLA dispatch is
    already async; wait() is a scheduling no-op."""

    def __init__(self, result=None):
        self.result = result

    def wait(self):
        return True

    def is_completed(self):
        return True


class P2POp:
    """One edge of a batched exchange. In SPMD the pattern must be uniform
    across ranks, so the edge is an `offset` on the group's ring (dst = rank +
    offset); `peer` is kept for reference-API compat and ignored when offset
    is given."""

    def __init__(self, op, tensor, peer=None, group=None, offset=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group
        self.offset = offset


def batch_isend_irecv(p2p_op_list: List[P2POp]):
    """Fused p2p batch (reference communication/batch_isend_irecv.py; NCCL
    group call). Each send op becomes one ppermute ring-shift by its offset;
    the recv op with the matching offset receives it (the reference pairs
    send/recv the same way in P2pHelper: send to next / recv from prev)."""
    if not in_spmd_region():
        raise NotImplementedError("batch_isend_irecv outside an spmd region")
    sends = [p for p in p2p_op_list if p.op in (isend, "isend", send)]
    recvs = [p for p in p2p_op_list if p.op in (irecv, "irecv", recv)]
    for s in sends:
        if s.offset is None:
            raise ValueError("SPMD batch_isend_irecv requires P2POp(offset=...) ring edges")
        out = shift(s.tensor, offset=s.offset, group=s.group)
        for r in recvs:
            r_off = r.offset if r.offset is not None else None
            if r_off == s.offset:
                r.tensor._replace_value(out._value)
                r.tensor._grad_node = out._grad_node
                r.tensor._output_index = out._output_index
                r.tensor.stop_gradient = out.stop_gradient
    return [_Task()]


def stream_allreduce(*a, **k):
    return all_reduce(*a, **k)


def wait(tensor, group=None, use_calc_stream=True):
    return None


def get_backend(group=None):
    return "xla"


# ---------------------------------------------------------------- watchdog
# reference comm_task_manager.cc: every collective launch is registered with
# the watchdog (no-op until enable_comm_watchdog is called)
def _with_watchdog(fn, tag):
    import functools

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        out = fn(*args, **kwargs)
        from .utils import watchdog as _wd

        _wd.maybe_watch(tag, out if out is not None else args[:1])
        return out

    return wrapped


for _name in ("all_reduce", "all_gather", "reduce_scatter", "all_to_all",
              "broadcast", "reduce", "scatter"):
    globals()[_name] = _with_watchdog(globals()[_name], _name)
del _name


def barrier(group: Optional[Group] = None):
    """Synchronization barrier (reference: paddle.distributed.barrier).
    Inside pjit a barrier is a no-op (SPMD programs are lockstep); in eager
    multi-process mode it all-reduces a scalar and blocks on the result."""
    t = Tensor(jnp.zeros((), jnp.float32))
    out = all_reduce(t)
    v = out._value if hasattr(out, "_value") else t._value
    try:
        jax.block_until_ready(v)
    except Exception:
        pass
    return None
