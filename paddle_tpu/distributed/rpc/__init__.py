"""paddle.distributed.rpc parity (reference: python/paddle/distributed/rpc/
— init_rpc / rpc_sync / rpc_async / shutdown over a gRPC agent).

TPU-native: the control-plane transport is the framework's native TCPStore
(the same server that backs rendezvous + elastic), not a second RPC stack.
Each worker runs a small dispatcher thread that polls its inbox key,
executes the pickled callable, and posts the pickled result; rpc_sync/
rpc_async are futures over that. This intentionally covers the reference's
*control* use cases (coordination, light metadata exchange) — bulk tensor
movement belongs to the XLA collective path, not RPC.
"""
from __future__ import annotations

import os
import pickle
import threading
import time
import uuid
from concurrent.futures import Future
from typing import Dict, Optional

from ...base.log import get_logger

_state: Dict = {"store": None, "name": None, "rank": None, "world": None,
                "thread": None, "stop": None, "names": {}}


class WorkerInfo:
    def __init__(self, name: str, rank: int):
        self.name = name
        self.rank = rank

    def __repr__(self):
        return f"WorkerInfo(name={self.name}, rank={self.rank})"


def init_rpc(name: str, rank: Optional[int] = None,
             world_size: Optional[int] = None, master_endpoint: Optional[str] = None):
    """Join the RPC group (reference rpc.init_rpc)."""
    from ...native import TCPStore

    rank = rank if rank is not None else int(os.environ.get("PADDLE_TRAINER_ID", 0))
    world = world_size if world_size is not None else int(
        os.environ.get("PADDLE_TRAINERS_NUM", 1))
    ep = master_endpoint or os.environ.get("PADDLE_MASTER", "127.0.0.1:49381")
    host, _, port = ep.rpartition(":")
    store = TCPStore(host or "127.0.0.1", int(port), is_master=(rank == 0),
                     world_size=world)
    _state.update(store=store, name=name, rank=rank, world=world)
    store.set(f"rpc/name/{rank}", name)
    store.add("rpc/joined", 1)
    stop = threading.Event()
    _state["stop"] = stop

    def serve():
        # the TCPStore client socket is not thread-safe: the dispatcher runs
        # on its own client connection to the same server
        serve_store = TCPStore(host or "127.0.0.1", int(port), is_master=False,
                               world_size=world)
        seq = 0
        while not stop.is_set():
            key = f"rpc/inbox/{rank}/{seq}"
            try:
                raw = serve_store.get(key, timeout=0.5)
            except Exception:
                continue
            seq += 1
            try:
                req = pickle.loads(raw)
                fn, args, kwargs = req["fn"], req["args"], req["kwargs"]
                try:
                    result = ("ok", fn(*args, **kwargs))
                except Exception as e:  # executed remotely: report, don't die
                    result = ("err", repr(e))
                serve_store.set(f"rpc/result/{req['id']}", pickle.dumps(result))
            except Exception as e:
                get_logger().warning("rpc dispatcher error: %s", e)
        serve_store.close()

    th = threading.Thread(target=serve, daemon=True)
    th.start()
    _state["thread"] = th
    # wait for the full group
    deadline = time.time() + 60
    while time.time() < deadline:
        if store.add("rpc/joined", 0) >= world:
            return
        time.sleep(0.05)
    raise TimeoutError("init_rpc: group did not assemble")


def get_worker_info(name: Optional[str] = None) -> WorkerInfo:
    store = _state["store"]
    if name is None:
        return WorkerInfo(_state["name"], _state["rank"])
    for r in range(_state["world"]):
        n = store.get(f"rpc/name/{r}", timeout=5.0).decode()
        if n == name:
            return WorkerInfo(n, r)
    raise KeyError(f"unknown rpc worker {name!r}")


def get_all_worker_infos():
    store = _state["store"]
    return [WorkerInfo(store.get(f"rpc/name/{r}", timeout=5.0).decode(), r)
            for r in range(_state["world"])]


def _post(to: str, fn, args, kwargs) -> str:
    store = _state["store"]
    info = get_worker_info(to)
    req_id = uuid.uuid4().hex
    payload = pickle.dumps({"id": req_id, "fn": fn, "args": args, "kwargs": kwargs})
    seq = store.add(f"rpc/seq/{info.rank}", 1) - 1
    store.set(f"rpc/inbox/{info.rank}/{seq}", payload)
    return req_id


def _wait(req_id: str, timeout: Optional[float]):
    store = _state["store"]
    raw = store.get(f"rpc/result/{req_id}", timeout=timeout or 60.0)
    status, value = pickle.loads(raw)
    if status == "err":
        raise RuntimeError(f"remote function raised: {value}")
    return value


def rpc_sync(to: str, fn, args=(), kwargs=None, timeout: Optional[float] = None):
    """Execute fn on worker `to`, block for the result (reference rpc_sync)."""
    return _wait(_post(to, fn, tuple(args), kwargs or {}), timeout)


def rpc_async(to: str, fn, args=(), kwargs=None, timeout: Optional[float] = None):
    """Fire-and-collect future (reference rpc_async)."""
    req_id = _post(to, fn, tuple(args), kwargs or {})
    fut: Future = Future()

    def collect():
        try:
            fut.set_result(_wait(req_id, timeout))
        except Exception as e:
            fut.set_exception(e)

    threading.Thread(target=collect, daemon=True).start()
    return fut


def shutdown():
    """Leave the group (reference rpc.shutdown): barrier on completion."""
    store = _state.get("store")
    if store is None:
        return
    stop = _state["stop"]
    store.add("rpc/done", 1)
    deadline = time.time() + 30
    while time.time() < deadline:
        if store.add("rpc/done", 0) >= _state["world"]:
            break
        time.sleep(0.05)
    stop.set()
    th = _state.get("thread")
    if th is not None:
        th.join(timeout=5)
    _state.update(store=None, thread=None, stop=None)
