"""Communication watchdog (reference: paddle/phi/core/distributed/
comm_task_manager.cc + nccl_comm_task.cc — async error polling / timeout
detection for hung collectives).

TPU-native: collectives are XLA ops on an async stream, so a "hung
collective" shows up as a result buffer that never becomes ready. The
watchdog tracks each collective's output array on a worker thread
(block_until_ready) while a monitor thread flags tasks that exceed the
timeout — logging the op tag and firing an optional handler, matching the
reference's CommTaskManager error report + abort hook.

Enable with `enable_comm_watchdog(timeout)`; the functional collectives in
distributed.communication register their outputs automatically.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional

import jax

from ...base.log import get_logger
from ...observability.locks import named_lock


@dataclass
class _Task:
    tag: str
    start: float
    done: bool = False
    seq: int = 0


class CommTaskManager:
    def __init__(self, timeout: float = 30.0,
                 on_timeout: Optional[Callable[[str, float], None]] = None,
                 poll_interval: float = 0.5):
        self.timeout = timeout
        self.on_timeout = on_timeout
        self.poll_interval = poll_interval
        self._tasks: List[_Task] = []
        self._lock = named_lock("distributed.watchdog")
        self._stop = threading.Event()
        self._seq = 0
        self.timeouts: List[str] = []  # tags that exceeded the deadline
        self._monitor = threading.Thread(target=self._monitor_loop, daemon=True)
        self._monitor.start()

    def watch(self, tag: str, values) -> None:
        """Track async values of one collective launch."""
        leaves = [v for v in jax.tree_util.tree_leaves(values) if hasattr(v, "block_until_ready")]
        if not leaves:
            return
        with self._lock:
            self._seq += 1
            task = _Task(tag=tag, start=time.time(), seq=self._seq)
            self._tasks.append(task)
        # chaos hook (reliability.faults, site "comm.watchdog"): an
        # injected "raise" simulates a HUNG collective — the result
        # buffer never becomes ready (no waiter marks the task done) and
        # the task is backdated past the deadline, so the monitor thread
        # exercises the real timeout path (log + handler + anomaly
        # forensic bundle) on its next poll
        hung = False
        try:
            from ...reliability.faults import FaultInjection, fault_point

            fault_point("comm.watchdog")
        except FaultInjection:
            hung = True
            task.start = time.time() - self.timeout - 1.0

        def waiter():
            try:
                for leaf in leaves:
                    leaf.block_until_ready()
            except Exception as e:
                get_logger().error("collective %s failed: %s", tag, e)
            finally:
                task.done = True

        if not hung:
            threading.Thread(target=waiter, daemon=True).start()

    def _monitor_loop(self):
        while not self._stop.wait(self.poll_interval):
            now = time.time()
            with self._lock:
                pending = [t for t in self._tasks if not t.done]
                self._tasks = pending
                overdue = [t for t in pending if now - t.start > self.timeout]
            for t in overdue:
                age = now - t.start
                get_logger().error(
                    "comm watchdog: collective '%s' (seq %d) not complete after %.1fs",
                    t.tag, t.seq, age)
                self.timeouts.append(t.tag)
                # a hung collective produces a FORENSIC BUNDLE, not just a
                # log line (ISSUE 14 satellite): the flight recorder grabs
                # the span tail + metrics + step window while the stall is
                # still observable
                try:
                    from ...observability.anomaly import monitor
                    from ...observability.metrics import registry

                    registry.counter(
                        "comm.watchdog_timeout",
                        "collectives the comm watchdog flagged as hung "
                        "(exceeded the task deadline)").inc(tag=t.tag)
                    if monitor.enabled:
                        monitor.on_exception("comm.watchdog", TimeoutError(
                            f"collective '{t.tag}' (seq {t.seq}) not "
                            f"complete after {age:.1f}s (deadline "
                            f"{self.timeout}s)"))
                except Exception:
                    pass
                if self.on_timeout is not None:
                    self.on_timeout(t.tag, age)
                t.done = True  # report once

    def shutdown(self):
        self._stop.set()
        self._monitor.join(timeout=5)


_manager: Optional[CommTaskManager] = None


def enable_comm_watchdog(timeout: float = 30.0, on_timeout=None) -> CommTaskManager:
    global _manager
    if _manager is not None:
        _manager.shutdown()
    _manager = CommTaskManager(timeout=timeout, on_timeout=on_timeout)
    return _manager


def disable_comm_watchdog():
    global _manager
    if _manager is not None:
        _manager.shutdown()
        _manager = None


def maybe_watch(tag: str, out) -> None:
    """Called by the functional collectives after each launch."""
    if _manager is None:
        return
    values = jax.tree_util.tree_map(
        lambda x: getattr(x, "_value", x), out,
        is_leaf=lambda x: hasattr(x, "_value"))
    _manager.watch(tag, values)
