"""MoE exchange collectives.

Reference: distributed/utils/moe_utils.py — global_scatter (:20) /
global_gather (:153): counts-driven uneven all-to-all moving expert-assigned
tokens between ranks.

TPU-native: XLA all_to_all is even-split, so the dispatch path uses
capacity-bucketed dense layouts (tokens padded per expert to capacity) and a
single lax.all_to_all over the `ep` group — see paddle_tpu.incubate.moe for
the full MoE layer + gates. The functions below keep the reference signature
for capacity-shaped tensors.
"""
from __future__ import annotations

from ..communication import all_to_all_single


def global_scatter(x, local_count=None, global_count=None, group=None):
    """Token dispatch across expert ranks (capacity-dense layout)."""
    return all_to_all_single(None, x, group=group)


def global_gather(x, local_count=None, global_count=None, group=None):
    """Inverse of global_scatter."""
    return all_to_all_single(None, x, group=group)
