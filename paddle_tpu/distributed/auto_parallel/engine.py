"""DistEngine — static auto-parallel engine equivalent.

Reference: python/paddle/distributed/auto_parallel/static/engine.py:98
(prepare/fit/evaluate over a distributed program built by completion.py +
partitioner.py + reshard.py). TPU-native: the "distributed program" is the
whole-step jit of the sharded model — GSPMD performs completion (dist-attr
propagation), partitioning (per-device program) and reshard (collective
insertion) inside XLA.
"""
from __future__ import annotations

from typing import Optional


class DistEngine:
    def __init__(self, layer, loader=None, loss=None, optimizer=None, strategy=None):
        from ...jit.api import TrainStep

        self._layer = layer
        self._loader = loader
        self._loss = loss
        self._optimizer = optimizer
        self._strategy = strategy
        self._step: Optional[TrainStep] = None
        self._plan = None

    def prepare(self, batch_size: Optional[int] = None, seq_len: Optional[int] = None,
                hbm_bytes: int = 16 << 30, n_devices: Optional[int] = None,
                mode: str = "auto"):
        """Plan the mesh (dp/mp/pp degrees) for this model WITHOUT user
        input, then initialize the hybrid environment (reference:
        static/engine.py:98 prepare() over completion + planner; search tier
        auto_tuner/prune.py). Returns the chosen Plan."""
        import jax

        from .. import fleet
        from .planner import ModelSpec, choose_plan

        n = n_devices or len(jax.devices())
        spec = ModelSpec.from_model(self._layer, seq_len=seq_len)
        self._plan = choose_plan(spec, n, batch_size or max(n, 8),
                                 hbm_bytes=hbm_bytes)
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = self._plan.degrees
        fleet.init(is_collective=True, strategy=strategy)
        return self._plan

    def _ensure_step(self):
        if self._step is None:
            from ...jit.api import TrainStep

            def loss_fn(x, y):
                out = self._layer(x)
                return self._loss(out, y)

            self._step = TrainStep(model=self._layer, optimizer=self._optimizer, loss_fn=loss_fn)
        return self._step

    # reference Engine surface
    def fit(self, train_data=None, epochs=1, verbose=1, steps_per_epoch=None):
        data = train_data if train_data is not None else self._loader
        step = self._ensure_step()
        history = []
        for _ in range(epochs):
            for i, batch in enumerate(data):
                if steps_per_epoch is not None and i >= steps_per_epoch:
                    break
                if isinstance(batch, (list, tuple)):
                    loss = step(*batch)
                else:
                    loss = step(batch)
                history.append(loss)
        return history

    def evaluate(self, valid_data=None):
        import numpy as np

        data = valid_data if valid_data is not None else self._loader
        was_training = self._layer.training
        self._layer.eval()
        losses = []
        try:
            for batch in data:
                x, y = batch if isinstance(batch, (list, tuple)) else (batch, None)
                out = self._layer(x)
                losses.append(float(self._loss(out, y).numpy()))
        finally:
            if was_training:
                self._layer.train()
        return float(np.mean(losses)) if losses else 0.0

    def predict(self, test_data=None):
        data = test_data if test_data is not None else self._loader
        was_training = self._layer.training
        self._layer.eval()
        outs = []
        try:
            for batch in data:
                x = batch[0] if isinstance(batch, (list, tuple)) else batch
                outs.append(self._layer(x))
        finally:
            if was_training:
                self._layer.train()
        return outs

    def dist_main_program(self, mode="train"):
        step = self._ensure_step()
        entry = step._compiled.last_entry
        return entry

    def state_dict(self):
        return self._layer.state_dict()
