"""DistEngine — static auto-parallel engine equivalent.

Reference: python/paddle/distributed/auto_parallel/static/engine.py:98
(prepare/fit/evaluate/predict over a distributed program built by
completion.py + partitioner.py + reshard.py, scored by the cost model and
transformed by the pass pipeline). TPU-native: the "distributed program"
is the whole-step jit of the sharded model — GSPMD performs completion
(dist-attr propagation), partitioning (per-device program) and reshard
(collective insertion) inside XLA. What remains engine-side, and lives
here:

- **planning** (prepare): candidate mesh shapes pruned by the memory model
  and RANKED by the analytic step-cost model (compute + dp/mp comm + pp
  bubble — planner.estimate_step_cost), the reference's cost-model pass;
- **partitioning**: when the plan has mp>1, parameters are placed sharded
  over the mp axis (largest divisible dim) — GSPMD propagates and inserts
  the collectives, the reference partitioner's job;
- **pass pipeline**: named passes applied when building the train step —
  "sharding_stage1/2" (ZeRO optimizer-state sharding), "amp" (bf16 O2
  decorate), mirroring the reference's pass_base registry.
"""
from __future__ import annotations

from typing import List, Optional


class DistEngine:
    def __init__(self, layer, loader=None, loss=None, optimizer=None,
                 strategy=None):
        from ...jit.api import TrainStep

        self._layer = layer
        self._loader = loader
        self._loss = loss
        self._optimizer = optimizer
        self._strategy = strategy
        self._step: Optional[TrainStep] = None
        self._plan = None
        self._passes: List[str] = []
        self.cost_report: List[dict] = []

    def prepare(self, batch_size: Optional[int] = None, seq_len: Optional[int] = None,
                hbm_bytes: int = 16 << 30, n_devices: Optional[int] = None,
                mode: str = "auto", passes: Optional[List[str]] = None,
                shard_params: bool = True, amortize_steps: int = 100):
        """Plan the mesh for this model WITHOUT user input: enumerate
        candidates (each dp>1 shape both replicated and ZeRO-1-sharded),
        prune by memory (zero1 variants price optimizer state at 1/dp —
        they survive budgets that OOM the replicated twin), rank by the
        step-cost model PLUS the one-time resharding cost of moving the
        live parameters into the candidate's placement (``plan_route``
        wire volume, amortized over ``amortize_steps``), then initialize
        the hybrid environment and (mp>1) shard the parameters
        (reference static/engine.py:98 prepare → completion + planner +
        partitioner + the reshard pass' cost). Ties between a zero1 and
        a replicated candidate break to replicated (simpler program);
        memory pressure and the quantized comm tier are what tip the
        ranking to zero1. Returns the chosen Plan; the scored candidate
        list is kept in ``cost_report`` (``zero_sharding``,
        ``reshard_bytes``, ``score_seconds`` per row). A chosen zero1
        plan auto-appends the ``sharding_stage1`` pass."""
        import dataclasses

        import jax

        from .. import fleet
        from .planner import (ModelSpec, estimate_per_device_bytes,
                              estimate_step_cost, iter_feasible)

        known_passes = {"sharding_stage1", "sharding_stage2", "amp"}
        bad = [p for p in (passes or []) if p not in known_passes]
        if bad:
            raise ValueError(f"unknown engine pass(es) {bad}; "
                             f"known: {sorted(known_passes)}")
        n = n_devices or len(jax.devices())
        bs = batch_size or max(n, 8)
        spec = ModelSpec.from_model(self._layer, seq_len=seq_len)
        self.cost_report = []
        best, best_score = None, float("inf")
        # the reshard volume depends only on the candidate's mp degree
        # (the target param placement), not dp/pp/sep — memoize it so a
        # large-model prepare doesn't replan every param per candidate
        reshard_by_mp: dict = {}
        for plan, why in iter_feasible(spec, n, bs, hbm_bytes=hbm_bytes):
            if why == "infeasible":
                continue
            variants = [(plan, why)]
            if plan.dp > 1 and why in (None, "oom"):
                z = dataclasses.replace(plan, sharding=plan.dp)
                z.per_device_bytes = estimate_per_device_bytes(
                    spec, bs, z.dp, z.mp, z.pp, z.sep, sharding=z.sharding)
                variants.append(
                    (z, "oom" if z.per_device_bytes > hbm_bytes else None))
            for cand, pruned in variants:
                row = {"plan": (cand.dp, cand.mp, cand.pp),
                       "zero_sharding": cand.sharding,
                       "bytes": cand.per_device_bytes}
                if pruned is not None:
                    row["pruned"] = pruned
                    self.cost_report.append(row)
                    continue
                cost = estimate_step_cost(spec, bs, cand)
                if cand.mp not in reshard_by_mp:
                    reshard_by_mp[cand.mp] = self._plan_reshard_bytes(cand)
                reshard_bytes = reshard_by_mp[cand.mp]
                reshard_s = reshard_bytes / 100e9
                score = cost["step_seconds"] + \
                    reshard_s / max(amortize_steps, 1)
                row.update(cost, reshard_bytes=reshard_bytes,
                           score_seconds=score)
                self.cost_report.append(row)
                if score < best_score:
                    best, best_score = cand, score
        if best is None:
            raise ValueError(
                f"no feasible parallel plan for {n} devices within "
                f"{hbm_bytes / 2**30:.0f} GiB/device")
        best.reason = (f"cost-model best of {len(self.cost_report)} "
                       f"candidates: ~{best_score * 1e3:.2f} ms/step est"
                       + (" (zero1 sharded update)"
                          if best.sharding > 1 else ""))
        self._plan = best
        self._passes = list(passes or [])
        if best.sharding > 1 and not any(
                p.startswith("sharding_stage") for p in self._passes):
            self._passes.append("sharding_stage1")
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = best.degrees
        fleet.init(is_collective=True, strategy=strategy)
        if shard_params and best.mp > 1:
            self._shard_parameters("mp")
        return self._plan

    def _plan_reshard_bytes(self, plan) -> float:
        """One-time wire bytes of moving the live parameters from their
        CURRENT placements into ``plan``'s target layout (mp>1: sharded
        over the mp axis on the largest divisible dim; else replicated),
        priced by ``collective_opt.plan_route`` — the reshard-pass cost
        the candidate ranking folds in. Fresh replicated models cost 0
        (r_to_s is a local slice); re-preparing a live sharded model
        pays the planned all_to_all/all_gather volume."""
        from ..collective_opt import plan_route
        from ..env import HYBRID_AXES
        from .placement_type import Replicate, Shard

        degrees = {"pp": plan.pp, "dp": plan.dp, "sharding": 1,
                   "sep": plan.sep, "mp": plan.mp}

        class _View:
            dim_names = list(HYBRID_AXES)
            shape = [degrees[a] for a in HYBRID_AXES]

        mp_idx = _View.dim_names.index("mp")
        total = 0.0
        for p in self._layer.parameters():
            shape = tuple(p._value.shape)
            recorded = getattr(p, "_placements", None)
            if recorded is None:
                src = [Replicate() for _ in _View.dim_names]
            else:
                # remap the recorded placements (relative to the param's
                # own ProcessMesh) onto the hybrid axis order by name
                pm = getattr(p, "_process_mesh", None)
                names = list(getattr(pm, "dim_names", _View.dim_names))
                by_name = dict(zip(names, recorded))
                src = [by_name.get(ax, Replicate())
                       for ax in _View.dim_names]
            dst = [Replicate() for _ in _View.dim_names]
            if plan.mp > 1 and shape:
                best_dim = max((d for d in range(len(shape))
                                if shape[d] % plan.mp == 0
                                and shape[d] >= plan.mp),
                               key=lambda d: shape[d], default=None)
                if best_dim is not None:
                    dst[mp_idx] = Shard(best_dim)
            route = plan_route(src, dst, _View, shape,
                               int(p._value.dtype.itemsize))
            if route.supported:
                total += route.comm_bytes_new
            else:
                total += route.comm_bytes_old or 0.0
        return total

    def _shard_parameters(self, axis: str):
        """GSPMD partitioning: place each parameter sharded over ``axis``
        on its largest divisible dim; XLA propagates the layouts through
        the step and inserts the collectives (the reference partitioner +
        reshard passes)."""
        from .. import env as env_mod
        from ..env import shard_largest_dim

        jmesh = env_mod.get_mesh()
        for p in self._layer.parameters():
            p._replace_value(shard_largest_dim(p._value, jmesh, axis))

    def _apply_passes(self):
        if getattr(self, "_passes_applied", False):
            return  # model/optimizer transforms must not re-wrap on retry
        self._passes_applied = True
        for name in self._passes:
            if name in ("sharding_stage1", "sharding_stage2"):
                from ..sharding import group_sharded_parallel

                level = "os" if name.endswith("1") else "os_g"
                self._layer, self._optimizer, _ = group_sharded_parallel(
                    self._layer, self._optimizer, level=level)
            elif name == "amp":
                from ... import amp as amp_mod

                amp_mod.decorate(self._layer, level="O2", dtype="bfloat16")
            else:
                raise ValueError(f"unknown engine pass {name!r} "
                                 "(sharding_stage1|sharding_stage2|amp)")

    def _ensure_step(self):
        if self._step is None:
            from ...jit.api import TrainStep

            self._apply_passes()

            def loss_fn(x, y):
                out = self._layer(x)
                return self._loss(out, y)

            self._step = TrainStep(model=self._layer,
                                   optimizer=self._optimizer,
                                   loss_fn=loss_fn)
        return self._step

    # reference Engine surface
    def fit(self, train_data=None, epochs=1, verbose=1, steps_per_epoch=None):
        data = train_data if train_data is not None else self._loader
        step = self._ensure_step()
        history = []
        for _ in range(epochs):
            for i, batch in enumerate(data):
                if steps_per_epoch is not None and i >= steps_per_epoch:
                    break
                if isinstance(batch, (list, tuple)):
                    loss = step(*batch)
                else:
                    loss = step(batch)
                history.append(loss)
        return history

    def evaluate(self, valid_data=None):
        import numpy as np

        data = valid_data if valid_data is not None else self._loader
        was_training = self._layer.training
        self._layer.eval()
        losses = []
        try:
            for batch in data:
                x, y = batch if isinstance(batch, (list, tuple)) else (batch, None)
                out = self._layer(x)
                losses.append(float(self._loss(out, y).numpy()))
        finally:
            if was_training:
                self._layer.train()
        return float(np.mean(losses)) if losses else 0.0

    def predict(self, test_data=None):
        data = test_data if test_data is not None else self._loader
        was_training = self._layer.training
        self._layer.eval()
        outs = []
        try:
            for batch in data:
                x = batch[0] if isinstance(batch, (list, tuple)) else batch
                outs.append(self._layer(x))
        finally:
            if was_training:
                self._layer.train()
        return outs

    def save(self, path: str):
        """reference engine.save: model + optimizer state."""
        from ...framework.io import save

        save(self._layer.state_dict(), path + ".pdparams")
        if self._optimizer is not None:
            save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path: str):
        from ...framework.io import load

        self._layer.set_state_dict(load(path + ".pdparams"))
        if self._optimizer is not None:
            import os

            if os.path.exists(path + ".pdopt"):
                self._optimizer.set_state_dict(load(path + ".pdopt"))

    def dist_main_program(self, mode="train"):
        step = self._ensure_step()
        entry = step._compiled.last_entry
        return entry

    def state_dict(self):
        return self._layer.state_dict()
