"""Parallelism planner: greedy mesh/degree chooser backed by a memory model.

Rebuild of the reference's auto-parallel search tier — the cost-model-guided
planner in python/paddle/distributed/auto_parallel/static/ (completion +
partitioner + cost model) and the black-box search pruner
(python/paddle/distributed/auto_tuner/prune.py). GSPMD already does
completion/partitioning inside XLA, so what remains to plan is the *mesh
shape*: how to factor N devices into dp×mp×pp×sep. The chooser:

1. enumerates all divisor factorizations (auto_tuner's candidate grid),
2. prunes infeasible ones (divisibility of batch/heads/layers/seq — the
   same rules as auto_tuner/prune.py), and configs whose per-device memory
   estimate exceeds the HBM budget,
3. greedily scores the survivors: data parallelism first (cheapest
   comms — gradient allreduce overlaps), then the smallest mp that fits
   (mp collectives sit on the critical path), pp last (bubble), mirroring
   the reference tuner's default ordering.

The memory model follows the standard transformer accounting (params,
grads, Adam moments, activations with remat) — the same quantities the
reference's cost model estimates from the dist program.

Two estimate tiers feed the pruning/scoring:

- **closed-form** — the analytic transformer accounting below, available
  before anything is traced;
- **jaxpr-backed** — when a traced ``TrainStep`` is available, its static
  ``CostReport`` (``analysis/cost_model.py``: liveness peak residency +
  exact program FLOPs) is *preferred* over the closed-form spec: pass
  ``cost_report=`` to :func:`estimate_per_device_bytes` /
  :func:`estimate_step_cost`, or let :func:`compare_with_measured` report
  all three tiers (closed-form / cost-model / XLA ``memory_analysis``)
  side by side.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional


@dataclasses.dataclass
class ModelSpec:
    """What the planner needs to know about the model."""

    num_params: int
    num_layers: int = 1
    hidden_size: int = 1024
    num_heads: int = 16
    vocab_size: int = 50304
    seq_len: int = 1024

    @classmethod
    def from_model(cls, model, seq_len: Optional[int] = None) -> "ModelSpec":
        import numpy as np

        n = int(sum(int(np.prod(p.shape)) for p in model.parameters()))
        cfg = getattr(model, "config", None)
        get = lambda name, d: int(getattr(cfg, name, d)) if cfg is not None else d
        return cls(
            num_params=n,
            num_layers=get("num_hidden_layers", 1),
            hidden_size=get("hidden_size", 1024),
            num_heads=get("num_attention_heads", 16),
            vocab_size=get("vocab_size", 50304),
            seq_len=seq_len or get("max_position_embeddings", 1024),
        )


@dataclasses.dataclass
class Plan:
    dp: int
    mp: int
    pp: int
    sep: int = 1
    sharding: int = 1  # ZeRO optimizer-state sharding degree (over dp)
    per_device_bytes: int = 0
    reason: str = ""

    @property
    def degrees(self) -> dict:
        """MESH axis degrees (feed these to hybrid_configs). ZeRO sharding
        rides the dp axis (group_sharded shards over "dp"), so it is NOT a
        mesh axis here — read ``plan.sharding`` separately."""
        return {"dp_degree": self.dp, "mp_degree": self.mp,
                "pp_degree": self.pp, "sep_degree": self.sep}

    @property
    def describe(self) -> dict:
        return dict(self.degrees, zero_sharding=self.sharding)


def _factorizations(n: int) -> List[tuple]:
    """All (dp, mp, pp, sep) with dp*mp*pp*sep == n."""
    out = []
    for dp in range(1, n + 1):
        if n % dp:
            continue
        r1 = n // dp
        for mp in range(1, r1 + 1):
            if r1 % mp:
                continue
            r2 = r1 // mp
            for pp in range(1, r2 + 1):
                if r2 % pp:
                    continue
                out.append((dp, mp, pp, r2 // pp))
    return out


def resident_state_bytes(spec: ModelSpec, mp: int, pp: int,
                         param_bytes: int = 2,
                         master_weights: bool = True) -> int:
    """Persistent per-device state: params + 2 Adam moments (+fp32 master),
    sharded over mp·pp. This is the component XLA reports as the compiled
    program's argument size, and the piece the calibration test pins to
    ±30% of measured (VERDICT r3 #9); transient grads/activations are in
    the peak estimate below."""
    shard = spec.num_params / (mp * pp)
    return int(shard * (param_bytes + 8 + (4 if master_weights else 0)))


def calibrate_against_compiled(step, spec: ModelSpec, batch_size: int,
                               degrees: dict, param_bytes: int = 4,
                               master_weights: bool = False) -> dict:
    """Compare the planner's estimates with the ACTUAL compiled program's
    memory_analysis (step must be a TrainStep that has executed once).
    Returns estimated/measured pairs; callers (tests, AutoTuner history)
    assert or record the ratio."""
    ma = step._compiled.memory_analysis()
    if ma is None:
        raise RuntimeError("step has not run compiled yet")
    dp = degrees.get("dp_degree", 1)
    mp = degrees.get("mp_degree", 1)
    pp = degrees.get("pp_degree", 1)
    sep = degrees.get("sep_degree", 1)
    sharding = degrees.get("zero_sharding", degrees.get("sharding_degree", 1))
    est_state = resident_state_bytes(spec, mp, pp, param_bytes, master_weights)
    est_peak = estimate_per_device_bytes(
        spec, batch_size, dp, mp, pp, sep, param_bytes=param_bytes,
        master_weights=master_weights, sharding=sharding)
    measured_state = int(ma.argument_size_in_bytes)
    measured_peak = int(ma.argument_size_in_bytes + ma.temp_size_in_bytes)
    return {
        "est_state": est_state, "measured_state": measured_state,
        "state_ratio": est_state / max(measured_state, 1),
        "est_peak": est_peak, "measured_peak": measured_peak,
        "peak_ratio": est_peak / max(measured_peak, 1),
    }


def compare_with_measured(step, spec: ModelSpec, batch_size: int,
                          degrees: dict, param_bytes: int = 4,
                          master_weights: bool = False) -> dict:
    """All three memory-estimate tiers for one traced+run ``TrainStep``,
    side by side:

    - ``closed_form``: the analytic transformer accounting
      (:func:`estimate_per_device_bytes` from the ``ModelSpec``);
    - ``cost_model``: the static jaxpr walker's liveness peak
      (``step.cost()`` — no compilation);
    - ``xla``: the compiled program's ``memory_analysis`` ground truth
      (argument + temp), ``None`` when the step has not run compiled.

    Ratios are cost_model/xla and closed_form/xla (when xla is present) —
    the calibration numbers the AutoTuner history and the bench's
    ``extras.cost_model`` record."""
    dp = degrees.get("dp_degree", 1)
    mp = degrees.get("mp_degree", 1)
    pp = degrees.get("pp_degree", 1)
    sep = degrees.get("sep_degree", 1)
    sharding = degrees.get("zero_sharding", degrees.get("sharding_degree", 1))

    closed_form = int(estimate_per_device_bytes(
        spec, batch_size, dp, mp, pp, sep, param_bytes=param_bytes,
        master_weights=master_weights, sharding=sharding))
    report = step.cost()
    cost_model = estimate_per_device_bytes_from_report(
        report, dp=dp, mp=mp, pp=pp, sep=sep, sharding=sharding)

    out = {
        "closed_form": {"peak_bytes": closed_form},
        "cost_model": {
            "peak_bytes": cost_model,
            "program_peak_bytes": int(report.peak_bytes),
            "arg_bytes": int(report.arg_bytes),
            "flops": float(report.flops),
            "analysis_seconds": round(report.analysis_seconds, 4),
        },
        "xla": None,
    }
    ma = step._compiled.memory_analysis()
    if ma is not None:
        measured = int(ma.argument_size_in_bytes + ma.temp_size_in_bytes)
        out["xla"] = {
            "peak_bytes": measured,
            "argument_bytes": int(ma.argument_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
        }
        out["cost_model_vs_xla"] = report.peak_bytes / max(measured, 1)
        out["closed_form_vs_xla"] = closed_form / max(measured, 1)
    return out


def estimate_per_device_bytes_from_report(report, dp: int = 1, mp: int = 1,
                                          pp: int = 1, sep: int = 1,
                                          sharding: int = 1) -> int:
    """Jaxpr-backed per-device HBM estimate from a traced step's
    ``CostReport``: the program's argument bytes (params + optimizer
    state + batch — the resident state XLA reports as argument size)
    shard over mp·pp, the transient remainder of the liveness peak
    (activations/grads) over dp·mp·sep. The ZeRO ``sharding`` degree is
    deliberately NOT applied here: when the step was traced with the
    zero1 strategy engaged, its optimizer-state cells are committed
    dp-sharded arrays and the sharding-aware liveness walk already
    prices them at shard size — dividing again would double-count the
    drop (a replicated-traced report simply has no shard split to
    apply)."""
    state = int(report.arg_bytes)
    transient = max(int(report.peak_bytes) - state, 0)
    del sharding  # see docstring
    return int(state / max(mp * pp, 1) + transient / max(dp * mp * sep, 1))


def estimate_per_device_bytes(spec: ModelSpec, batch_size: int, dp: int,
                              mp: int, pp: int, sep: int = 1,
                              param_bytes: int = 2, master_weights: bool = True,
                              remat: bool = True, sharding: int = 1,
                              cost_report=None) -> int:
    """Per-device HBM estimate: params + grads + Adam moments (+fp32
    master) sharded over mp·pp — with the optimizer-state component further
    divided by the ZeRO ``sharding`` degree (stage 1/2 shard moments and
    master weights over dp) — plus activations sharded over dp·mp·sep.
    Activation term uses the remat'd transformer footprint
    (~2·s·h bytes/layer/sample boundaries instead of ~34·s·h full).

    When ``cost_report`` (a traced step's ``analysis.cost_model``
    CostReport) is given, the measured-from-jaxpr path is preferred over
    this closed-form accounting."""
    if cost_report is not None:
        return estimate_per_device_bytes_from_report(
            cost_report, dp=dp, mp=mp, pp=pp, sep=sep, sharding=sharding)
    model_shard = spec.num_params / (mp * pp)
    # bf16 param + bf16-ish grad replicated over dp; 2 fp32 moments
    # (+ fp32 master) ZeRO-sharded
    opt_mult = (8 + (4 if master_weights else 0)) / max(sharding, 1)
    state_mult = param_bytes + param_bytes + opt_mult
    model_bytes = model_shard * state_mult

    micro_batch = max(batch_size // dp, 1)
    layers_per_stage = max(spec.num_layers // pp, 1)
    act_per_layer = (2.0 if remat else 34.0) * spec.seq_len * spec.hidden_size / sep
    act_bytes = micro_batch * layers_per_stage * act_per_layer * param_bytes
    # logits + embedding activations
    head_bytes = micro_batch * spec.seq_len * spec.vocab_size / mp * 2
    return int(model_bytes + act_bytes + head_bytes)


def feasible(spec: ModelSpec, batch_size: int, dp: int, mp: int, pp: int,
             sep: int = 1) -> bool:
    """auto_tuner/prune.py-style divisibility rules."""
    if batch_size % dp:
        return False
    if spec.num_heads % (mp * sep):
        return False
    if spec.hidden_size % mp:
        return False
    if spec.num_layers % pp:
        return False
    if spec.seq_len % sep:
        return False
    if pp > 1 and (batch_size // dp) % pp:
        return False  # need ≥pp microbatches per dp replica
    return True


def iter_feasible(spec: ModelSpec, n_devices: int, batch_size: int,
                  hbm_bytes: int = 16 << 30, max_mp: int = 8,
                  use_sep: bool = False):
    """Yield (plan, pruned_reason) over the candidate grid — the single
    enumeration/pruning rule set shared by choose_plan, the DistEngine cost
    model and the AutoTuner (divisibility prunes per auto_tuner/prune.py,
    memory prunes per the HBM estimate, mp capped at max_mp: tensor
    parallelism past one slice's ICI is never chosen automatically).
    pruned_reason is None for survivors."""
    for dp, mp, pp, sep in _factorizations(n_devices):
        if not use_sep and sep != 1:
            continue
        if mp > max_mp:
            yield Plan(dp, mp, pp, sep), "mp_cap"
            continue
        if not feasible(spec, batch_size, dp, mp, pp, sep):
            yield Plan(dp, mp, pp, sep), "infeasible"
            continue
        mem = estimate_per_device_bytes(spec, batch_size, dp, mp, pp, sep)
        plan = Plan(dp, mp, pp, sep, per_device_bytes=mem)
        yield plan, ("oom" if mem > hbm_bytes else None)


def choose_plan(spec: ModelSpec, n_devices: int, batch_size: int,
                hbm_bytes: int = 16 << 30, max_mp: int = 8,
                use_sep: bool = False) -> Plan:
    """Greedy chooser over the pruned candidate grid."""
    best: Optional[Plan] = None
    candidates = [p for p, why in iter_feasible(
        spec, n_devices, batch_size, hbm_bytes, max_mp, use_sep)
        if why is None]
    if not candidates:
        raise ValueError(
            f"no feasible parallel plan for {n_devices} devices, "
            f"batch {batch_size}, ~{spec.num_params/1e6:.1f}M params within "
            f"{hbm_bytes/2**30:.0f} GiB/device")
    # greedy order: max dp, then min pp (bubble), then min mp (critical-path
    # collectives), then min memory
    candidates.sort(key=lambda p: (-p.dp, p.pp, p.mp, p.per_device_bytes))
    best = candidates[0]
    best.reason = (
        f"dp-first greedy over {len(candidates)} feasible configs; "
        f"~{best.per_device_bytes / 2**30:.2f} GiB/device")
    return best


def estimate_step_cost(spec: ModelSpec, batch_size: int, plan: Plan,
                       device_tflops: float = 197.0,
                       ici_gbps: float = 100.0,
                       cost_report=None,
                       comm_quantize: Optional[bool] = None) -> dict:
    """Relative step-time model over a candidate plan (the reference
    Engine's cost-model pass, auto_parallel/static/cost/: compute + comm +
    bubble). Absolute numbers are nominal (bf16 peak, ICI link bw); only
    the RANKING between candidates matters.

    - compute: 6·tokens·params FLOPs split over all devices — unless
      ``cost_report`` (a traced step's CostReport, whose FLOPs already
      include forward + backward + optimizer at the traced batch) is
      given, in which case the measured-from-jaxpr FLOPs are preferred;
    - dp comm: one gradient all-reduce per step, 2·(dp-1)/dp ring factor
      — priced at the quantized tier's wire bytes (int8 payload + fp32
      scale overhead, ``collective_opt.wire_report``) when
      ``comm_quantize`` is True (default: ``FLAGS_comm_quantize_dp_grads``),
      so plans are ranked on the bytes the sync actually moves. A zero1
      plan (``plan.sharding > 1``) is priced at its actual pair — the
      fp32 reduce-scatter of the grads plus the all-gather of the
      updated weights ((dp-1)/dp each; the gather at int8+scales wire
      bytes when ``comm_quantize``) — the ``sharding/zero1`` accounting
      the bench cross-checks within 1.3x of measured;
    - mp comm: two activation all-reduces per layer (Megatron row+column),
      on the critical path;
    - pp bubble: (p-1)/(m+p-1) idle fraction on top of compute.
    """
    n = plan.dp * plan.mp * plan.pp * plan.sep
    tokens = batch_size * spec.seq_len
    if cost_report is not None and cost_report.flops > 0:
        flops = float(cost_report.flops)
    else:
        flops = 6.0 * tokens * spec.num_params
    compute_s = flops / (n * device_tflops * 1e12)
    grad_elems = spec.num_params / (plan.mp * plan.pp)
    grad_bytes = 2.0 * grad_elems
    if comm_quantize is None:
        try:
            from ...base.flags import get_flag

            comm_quantize = bool(get_flag("comm_quantize_dp_grads"))
        except Exception:
            comm_quantize = False
    dp_comm_bytes = 2.0 * (plan.dp - 1) / max(plan.dp, 1) * grad_bytes \
        if plan.dp > 1 else 0.0
    zero1 = plan.dp > 1 and getattr(plan, "sharding", 1) > 1
    if zero1:
        # the zero1 pair: fp32 reduce-scatter of the grads + all-gather
        # of the updated weights (int8 blocks + fp32 scales on the wire
        # when the quantized tier engages) — one fused-bucket model, same
        # granularity as the all-reduce pricing above
        from ...distributed.sharding.zero1 import zero1_wire_report

        row = zero1_wire_report([("grads", int(grad_elems), 2)], plan.dp,
                                quantize=bool(comm_quantize))
        dp_comm_bytes = row["wire_bytes"]
    elif comm_quantize and plan.dp > 1:
        from ..collective_opt import wire_report

        # one fused-bucket model: the whole grad set syncs as one flat
        # int8+scales payload (per-tensor min-bytes fallbacks are noise
        # at planning granularity)
        row = wire_report([(int(grad_elems), 2, True)], plan.dp)
        dp_comm_bytes = row["wire_bytes"]
    dp_comm_s = dp_comm_bytes / (ici_gbps * 1e9) if plan.dp > 1 else 0.0
    act_bytes = 2.0 * tokens / plan.dp * spec.hidden_size / plan.sep
    mp_comm_s = (2.0 * spec.num_layers * 2.0 * (plan.mp - 1) / plan.mp
                 * act_bytes / (ici_gbps * 1e9)) if plan.mp > 1 else 0.0
    micro = max((batch_size // plan.dp), 1)
    m = max(micro // max(plan.pp, 1), 1) if plan.pp > 1 else 1
    bubble = (plan.pp - 1) / (m + plan.pp - 1) if plan.pp > 1 else 0.0
    step_s = (compute_s + mp_comm_s) / max(1.0 - bubble, 1e-6) + dp_comm_s
    return {"step_seconds": step_s, "compute_seconds": compute_s,
            "dp_comm_seconds": dp_comm_s, "mp_comm_seconds": mp_comm_s,
            "dp_comm_bytes": dp_comm_bytes,
            "comm_quantized": bool(comm_quantize and plan.dp > 1),
            "zero1": zero1,
            "pp_bubble_fraction": bubble}
