"""Semi-auto parallel user API.

Reference: python/paddle/distributed/auto_parallel/api.py — shard_tensor
(:215), reshard (:713), shard_layer (:824), shard_optimizer (:1615),
to_static (:2731). The reference routes every op through generated dist
branches (dist_api_gen.py): InferSPMD -> reshard inputs -> local kernel.

TPU-native: placements map to `jax.sharding.NamedSharding`; SPMD *propagation*
is GSPMD inside XLA (the reference's ~60 hand-written spmd rules come for
free), and `reshard` is a sharding-constrained device_put. Eager ops on
sharded jax arrays already execute distributed (per-op GSPMD), so sharded
eager training works without wrappers; whole-step jit then optimizes layouts
globally.
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from .placement_type import Partial, Placement, Replicate, Shard, to_partition_spec
from .process_mesh import ProcessMesh


def _named_sharding(mesh: ProcessMesh, placements, ndim):
    spec = to_partition_spec(placements, mesh.dim_names, ndim)
    return NamedSharding(mesh.to_jax_mesh(), spec)


def _normalize_placements(mesh: ProcessMesh, placements):
    if placements is None:
        return [Replicate() for _ in range(mesh.ndim)]
    out = list(placements)
    while len(out) < mesh.ndim:
        out.append(Replicate())
    return out


def shard_tensor(data, mesh: ProcessMesh, placements: Sequence[Placement], dtype=None, stop_gradient=None) -> Tensor:
    """Annotate + place a tensor on the mesh (reference api.py:215).

    Inside jit traces this lowers to with_sharding_constraint; eagerly it is a
    device_put to the NamedSharding.
    """
    t = data if isinstance(data, Tensor) else Tensor(data, dtype=dtype)
    placements = _normalize_placements(mesh, placements)
    if any(isinstance(p, Partial) for p in placements):
        # partial state cannot be *constructed* eagerly in single-controller
        # mode (the local values it would describe do not exist separately);
        # it arises from ops and is resolved by reshard.
        raise ValueError("shard_tensor cannot create Partial placements; use ops that produce them or reshard")
    sharding = _named_sharding(mesh, placements, t.ndim)
    if isinstance(t._value, jax.core.Tracer):
        new_val = jax.lax.with_sharding_constraint(t._value, sharding)
    else:
        new_val = jax.device_put(t._value, sharding)
    t._replace_value(new_val)
    t._placements = placements
    t._process_mesh = mesh
    if stop_gradient is not None:
        t.stop_gradient = stop_gradient
    return t


def dtensor_from_fn(fn: Callable, mesh: ProcessMesh, placements, *args, **kwargs) -> Tensor:
    """reference api.py dtensor_from_fn: build sharded without materializing
    the full value per device — jit the initializer with out_shardings."""
    placements = _normalize_placements(mesh, placements)

    def raw():
        out = fn(*args, **kwargs)
        return out._value if isinstance(out, Tensor) else out

    shape_probe = jax.eval_shape(raw)
    sharding = _named_sharding(mesh, placements, len(shape_probe.shape))
    val = jax.jit(raw, out_shardings=sharding)()
    t = Tensor(val, stop_gradient=False)
    t._placements = placements
    t._process_mesh = mesh
    return t


def reshard(dist_tensor: Tensor, mesh: ProcessMesh, placements: Sequence[Placement]) -> Tensor:
    """Transfer between placements (reference api.py:713; C++ reshard functions
    paddle/phi/core/distributed/auto_parallel/reshard/*). All r_to_s / s_to_r /
    p_to_r / s_to_s compositions reduce to one sharding-changing device_put —
    XLA emits the minimal collective (slice, all_gather, psum, all_to_all)."""
    placements = _normalize_placements(mesh, placements)
    if any(isinstance(p, Partial) for p in placements):
        raise ValueError("reshard target cannot be Partial")
    sharding = _named_sharding(mesh, placements, dist_tensor.ndim)
    from ...core.dispatch import primitive

    if isinstance(dist_tensor._value, jax.core.Tracer):
        out = primitive("reshard", lambda x: jax.lax.with_sharding_constraint(x, sharding), [dist_tensor])
    else:
        out = primitive("reshard", lambda x: jax.device_put(x, sharding), [dist_tensor])
    out._placements = placements
    out._process_mesh = mesh
    out.stop_gradient = dist_tensor.stop_gradient
    return out


def unshard_dtensor(dist_tensor: Tensor) -> Tensor:
    """Gather to a fully-replicated dense tensor (reference api.py)."""
    mesh = dist_tensor._process_mesh
    if mesh is None:
        return dist_tensor
    return reshard(dist_tensor, mesh, [Replicate() for _ in range(mesh.ndim)])


def shard_layer(
    layer,
    process_mesh: ProcessMesh,
    shard_fn: Optional[Callable] = None,
    input_fn: Optional[Callable] = None,
    output_fn: Optional[Callable] = None,
):
    """Shard a Layer's parameters in place (reference api.py:824).

    shard_fn(name, layer, mesh) applies shard_tensor to the sublayer's params;
    default replicates everything.
    """
    from ...nn.layer.layers import Layer

    def _default_shard(name, sublayer, mesh):
        for _, p in sublayer.named_parameters(include_sublayers=False):
            shard_tensor(p, mesh, [Replicate() for _ in range(mesh.ndim)])

    fn = shard_fn or _default_shard
    for name, sub in layer.named_sublayers(include_self=True):
        fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(lambda lyr, inputs: input_fn(inputs, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(lambda lyr, inputs, outputs: output_fn(outputs, process_mesh))
    return layer


class _ShardingStage:
    def __init__(self, mesh_dim: str = "dp"):
        self.mesh_dim = mesh_dim


class ShardingStage1(_ShardingStage):
    """Shard optimizer states over the data axis (reference api.py:1028)."""


class ShardingStage2(_ShardingStage):
    """+ shard gradients. Under XLA the gradient buffers inside the compiled
    step are already partitioned by GSPMD once the master weights/accumulators
    are sharded; stage2 therefore behaves as stage1 annotations."""


class ShardingStage3(_ShardingStage):
    """+ shard parameters."""


def _shard_over_axis(value, mesh: ProcessMesh, axis_name: str):
    """Pick the largest dim divisible by the axis size; replicate if none."""
    from .. import env as _env

    return _env.shard_largest_dim(value, mesh.to_jax_mesh(), axis_name)


def shard_optimizer(optimizer, shard_fn: Optional[_ShardingStage] = None):
    """ZeRO via sharded accumulator pytrees (reference api.py:1615).

    The reference re-implements ZeRO stages as rank-local slice bookkeeping;
    here each accumulator simply *is* a global array sharded over the
    dp/sharding axis — XLA partitions the optimizer update accordingly
    (SURVEY.md §7 translation table "sharding stage1/2/3").
    """
    stage = shard_fn if shard_fn is not None else ShardingStage1()
    mesh_axis = getattr(stage, "mesh_dim", "dp")
    from .. import env as env_mod
    from .process_mesh import get_mesh_from_jax

    mesh = get_mesh_from_jax(env_mod.get_mesh())
    if mesh_axis not in mesh.dim_names:
        mesh_axis = mesh.dim_names[0]

    orig_get_acc = optimizer._get_accumulator

    def sharded_get_accumulator(name, param, fill=0.0, dtype=None):
        store = optimizer._accumulators[name]
        fresh = id(param) not in store
        acc = orig_get_acc(name, param, fill, dtype)
        if fresh:
            acc._replace_value(_shard_over_axis(acc._value, mesh, mesh_axis))
        return acc

    optimizer._get_accumulator = sharded_get_accumulator

    if isinstance(stage, ShardingStage3):
        for p in optimizer._parameter_list:
            p._replace_value(_shard_over_axis(p._value, mesh, mesh_axis))
    return optimizer


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None):
    """dist.to_static (reference api.py:2731): returns an engine-like object
    whose train step is one compiled SPMD program."""
    from .engine import DistEngine

    return DistEngine(layer, loader, loss, optimizer, strategy)
