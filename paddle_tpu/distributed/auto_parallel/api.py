"""Semi-auto parallel user API.

Reference: python/paddle/distributed/auto_parallel/api.py — shard_tensor
(:215), reshard (:713), shard_layer (:824), shard_optimizer (:1615),
to_static (:2731). The reference routes every op through generated dist
branches (dist_api_gen.py): InferSPMD -> reshard inputs -> local kernel.

TPU-native: placements map to `jax.sharding.NamedSharding`; SPMD *propagation*
is GSPMD inside XLA (the reference's ~60 hand-written spmd rules come for
free), and `reshard` is a sharding-constrained device_put. Eager ops on
sharded jax arrays already execute distributed (per-op GSPMD), so sharded
eager training works without wrappers; whole-step jit then optimizes layouts
globally.
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from .placement_type import Partial, Placement, Replicate, Shard, to_partition_spec
from .process_mesh import ProcessMesh


def _named_sharding(mesh: ProcessMesh, placements, ndim):
    spec = to_partition_spec(placements, mesh.dim_names, ndim)
    return NamedSharding(mesh.to_jax_mesh(), spec)


def _normalize_placements(mesh: ProcessMesh, placements):
    if placements is None:
        return [Replicate() for _ in range(mesh.ndim)]
    out = list(placements)
    while len(out) < mesh.ndim:
        out.append(Replicate())
    return out


def shard_tensor(data, mesh: ProcessMesh, placements: Sequence[Placement], dtype=None, stop_gradient=None) -> Tensor:
    """Annotate + place a tensor on the mesh (reference api.py:215).

    Inside jit traces this lowers to with_sharding_constraint; eagerly it is a
    device_put to the NamedSharding.
    """
    t = data if isinstance(data, Tensor) else Tensor(data, dtype=dtype)
    placements = _normalize_placements(mesh, placements)
    if any(isinstance(p, Partial) for p in placements):
        # partial state cannot be *constructed* eagerly in single-controller
        # mode (the local values it would describe do not exist separately);
        # it arises from ops and is resolved by reshard.
        raise ValueError("shard_tensor cannot create Partial placements; use ops that produce them or reshard")
    sharding = _named_sharding(mesh, placements, t.ndim)
    if isinstance(t._value, jax.core.Tracer):
        new_val = jax.lax.with_sharding_constraint(t._value, sharding)
    else:
        new_val = jax.device_put(t._value, sharding)
    t._replace_value(new_val)
    t._placements = placements
    t._process_mesh = mesh
    if stop_gradient is not None:
        t.stop_gradient = stop_gradient
    return t


def dtensor_from_fn(fn: Callable, mesh: ProcessMesh, placements, *args, **kwargs) -> Tensor:
    """reference api.py dtensor_from_fn: build sharded without materializing
    the full value per device — jit the initializer with out_shardings."""
    placements = _normalize_placements(mesh, placements)

    def raw():
        out = fn(*args, **kwargs)
        return out._value if isinstance(out, Tensor) else out

    shape_probe = jax.eval_shape(raw)
    sharding = _named_sharding(mesh, placements, len(shape_probe.shape))
    val = jax.jit(raw, out_shardings=sharding)()
    t = Tensor(val, stop_gradient=False)
    t._placements = placements
    t._process_mesh = mesh
    return t


def _reshard_route(dist_tensor: Tensor, mesh: ProcessMesh, placements):
    """Plan the portable collective route for one eager reshard, or the
    reason it falls back to the legacy device_put path."""
    from ...base.flags import get_flag
    from ..collective_opt import plan_route, ReshardRoute

    if not get_flag("comm_portable_reshard"):
        return ReshardRoute("fallback", reason="flag_off"), None
    if isinstance(dist_tensor._value, jax.core.Tracer):
        # inside a whole-program trace GSPMD already plans globally; the
        # explicit sequence would pin a layout mid-program
        return ReshardRoute("fallback", reason="traced"), None
    src = getattr(dist_tensor, "_placements", None)
    if src is None:
        return ReshardRoute("fallback", reason="unknown_source"), None
    src_mesh = getattr(dist_tensor, "_process_mesh", None)
    if src_mesh is not None and list(getattr(src_mesh, "dim_names", ())) != \
            list(mesh.dim_names):
        return ReshardRoute("fallback", reason="mesh_change"), None
    src = _normalize_placements(mesh, src)
    route = plan_route(src, placements, mesh, dist_tensor.shape,
                       dist_tensor._value.dtype.itemsize)
    return route, src


def reshard(dist_tensor: Tensor, mesh: ProcessMesh, placements: Sequence[Placement]) -> Tensor:
    """Transfer between placements (reference api.py:713; C++ reshard functions
    paddle/phi/core/distributed/auto_parallel/reshard/*).

    Eager transitions with a known source placement ride the *portable*
    collective routes (``collective_opt.reshard``): s_to_s axis moves are
    one tiled ``all_to_all`` (O(shard) peak residency instead of the
    gather path's O(full array)), r_to_s is a comm-free local slice,
    s_to_r one ``all_gather``. Everything else — traced values, Partial
    sources, multi-dim transitions, indivisible shards, or
    ``FLAGS_comm_portable_reshard=0`` — keeps the legacy sharding-changing
    device_put, where XLA emits the movement. The route chosen (or the
    fallback reason) ticks ``comm.reshard_route``."""
    placements = _normalize_placements(mesh, placements)
    if any(isinstance(p, Partial) for p in placements):
        raise ValueError("reshard target cannot be Partial")
    sharding = _named_sharding(mesh, placements, dist_tensor.ndim)
    from ...core.dispatch import primitive
    from ..collective_opt import apply_route, _tick

    route, src = _reshard_route(dist_tensor, mesh, placements)
    if route.supported and route.kind != "noop":
        from .placement_type import to_partition_spec

        jmesh = mesh.to_jax_mesh()
        src_spec = to_partition_spec(src, mesh.dim_names, dist_tensor.ndim)
        dst_spec = to_partition_spec(placements, mesh.dim_names,
                                     dist_tensor.ndim)
        from ...observability.tracing import tracer

        span = tracer.span("comm.reshard", track="comm", route=route.kind,
                           axis=route.axis) if tracer.enabled else None
        try:
            out = primitive(
                "reshard",
                lambda x: apply_route(x, jmesh, route, src_spec, dst_spec),
                [dist_tensor])
        finally:
            if span is not None:
                span.end()
        _tick("reshard_route", route=route.kind)
    else:
        # a supported no-op transition is not a fallback: label it as its
        # own kind so the fallback-rate counter stays honest
        label = "noop" if route.kind == "noop" \
            else f"device_put:{route.reason or route.kind}"
        _tick("reshard_route", route=label)
        if isinstance(dist_tensor._value, jax.core.Tracer):
            out = primitive("reshard", lambda x: jax.lax.with_sharding_constraint(x, sharding), [dist_tensor])
        else:
            out = primitive("reshard", lambda x: jax.device_put(x, sharding), [dist_tensor])
    out._placements = placements
    out._process_mesh = mesh
    out.stop_gradient = dist_tensor.stop_gradient
    return out


def unshard_dtensor(dist_tensor: Tensor) -> Tensor:
    """Gather to a fully-replicated dense tensor (reference api.py)."""
    mesh = dist_tensor._process_mesh
    if mesh is None:
        return dist_tensor
    return reshard(dist_tensor, mesh, [Replicate() for _ in range(mesh.ndim)])


def shard_layer(
    layer,
    process_mesh: ProcessMesh,
    shard_fn: Optional[Callable] = None,
    input_fn: Optional[Callable] = None,
    output_fn: Optional[Callable] = None,
):
    """Shard a Layer's parameters in place (reference api.py:824).

    shard_fn(name, layer, mesh) applies shard_tensor to the sublayer's params;
    default replicates everything.
    """
    from ...nn.layer.layers import Layer

    def _default_shard(name, sublayer, mesh):
        for _, p in sublayer.named_parameters(include_sublayers=False):
            shard_tensor(p, mesh, [Replicate() for _ in range(mesh.ndim)])

    fn = shard_fn or _default_shard
    for name, sub in layer.named_sublayers(include_self=True):
        fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(lambda lyr, inputs: input_fn(inputs, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(lambda lyr, inputs, outputs: output_fn(outputs, process_mesh))
    return layer


class _ShardingStage:
    def __init__(self, mesh_dim: str = "dp"):
        self.mesh_dim = mesh_dim


class ShardingStage1(_ShardingStage):
    """Shard optimizer states over the data axis (reference api.py:1028)."""


class ShardingStage2(_ShardingStage):
    """+ shard gradients. Under XLA the gradient buffers inside the compiled
    step are already partitioned by GSPMD once the master weights/accumulators
    are sharded; stage2 therefore behaves as stage1 annotations."""


class ShardingStage3(_ShardingStage):
    """+ shard parameters."""


def _shard_over_axis(value, mesh: ProcessMesh, axis_name: str):
    """Pick the largest dim divisible by the axis size; replicate if none."""
    from .. import env as _env

    return _env.shard_largest_dim(value, mesh.to_jax_mesh(), axis_name)


def shard_optimizer(optimizer, shard_fn: Optional[_ShardingStage] = None):
    """ZeRO via sharded accumulator pytrees (reference api.py:1615).

    The reference re-implements ZeRO stages as rank-local slice bookkeeping;
    here each accumulator simply *is* a global array sharded over the
    dp/sharding axis — XLA partitions the optimizer update accordingly
    (SURVEY.md §7 translation table "sharding stage1/2/3").
    """
    stage = shard_fn if shard_fn is not None else ShardingStage1()
    mesh_axis = getattr(stage, "mesh_dim", "dp")
    from .. import env as env_mod
    from .process_mesh import get_mesh_from_jax

    mesh = get_mesh_from_jax(env_mod.get_mesh())
    if mesh_axis not in mesh.dim_names:
        from ...base.log import get_logger

        fallback_axis = mesh.dim_names[0]
        get_logger().warning(
            "shard_optimizer: requested mesh_dim %r is not an axis of the "
            "installed mesh %s; sharding optimizer state over %r instead — "
            "pass one of the mesh's axes to shard where you intended",
            mesh_axis, tuple(mesh.dim_names), fallback_axis)
        mesh_axis = fallback_axis

    orig_get_acc = optimizer._get_accumulator

    def sharded_get_accumulator(name, param, fill=0.0, dtype=None):
        store = optimizer._accumulators[name]
        fresh = id(param) not in store
        acc = orig_get_acc(name, param, fill, dtype)
        if fresh:
            acc._replace_value(_shard_over_axis(acc._value, mesh, mesh_axis))
        return acc

    optimizer._get_accumulator = sharded_get_accumulator

    if isinstance(stage, ShardingStage3):
        for p in optimizer._parameter_list:
            p._replace_value(_shard_over_axis(p._value, mesh, mesh_axis))
    return optimizer


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None):
    """dist.to_static (reference api.py:2731): returns an engine-like object
    whose train step is one compiled SPMD program."""
    from .engine import DistEngine

    return DistEngine(layer, loader, loss, optimizer, strategy)
