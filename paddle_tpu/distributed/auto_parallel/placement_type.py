"""Placement types (reference: paddle/phi/core/distributed/auto_parallel/
placement_types.h; python surface paddle.distributed.{Shard,Replicate,Partial}).

Shard(dim) / Replicate map 1:1 onto PartitionSpec entries. Partial(op) marks a
pending cross-axis reduction; GSPMD tracks the same notion internally, and the
reshard path materializes it with a psum when converting to Replicate/Shard.
"""
from __future__ import annotations


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicate(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = dim

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def get_dim(self):
        return self.dim

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("shard", self.dim))

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Replicate(Placement):
    def is_replicate(self):
        return True

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("replicate")

    def __repr__(self):
        return "Replicate()"


class Partial(Placement):
    def __init__(self, reduce_type: str = "sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __eq__(self, other):
        return isinstance(other, Partial) and other.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("partial", self.reduce_type))

    def __repr__(self):
        return f"Partial(reduce_type={self.reduce_type})"


def to_partition_spec(placements, mesh_dim_names, ndim: int):
    """placements (indexed by MESH dim) -> PartitionSpec (indexed by TENSOR dim)."""
    from jax.sharding import PartitionSpec as P

    entries = [None] * ndim
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            ax = mesh_dim_names[mesh_dim]
            if entries[pl.dim] is None:
                entries[pl.dim] = ax
            elif isinstance(entries[pl.dim], tuple):
                entries[pl.dim] = entries[pl.dim] + (ax,)
            else:
                entries[pl.dim] = (entries[pl.dim], ax)
    return P(*entries)
