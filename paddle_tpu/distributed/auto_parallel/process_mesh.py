"""ProcessMesh — the auto-parallel mesh abstraction.

Reference: python/paddle/distributed/auto_parallel/process_mesh.py (python
view over paddle/phi/core/distributed/auto_parallel/process_mesh.h:34).

TPU-native: a ProcessMesh is a *named view over jax devices*. `to_jax_mesh()`
materializes the corresponding `jax.sharding.Mesh`, which is what every
sharding annotation ultimately consumes. Process ids index `jax.devices()`
(single-controller SPMD: one "process" per device).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh


class ProcessMesh:
    def __init__(self, mesh: Sequence, dim_names: Optional[List[str]] = None, shape=None, process_ids=None):
        if shape is not None and process_ids is not None:
            arr = np.asarray(process_ids).reshape(shape)
        else:
            arr = np.asarray(mesh)
        if arr.ndim == 0:
            arr = arr.reshape(1)
        self._mesh = arr
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        if len(dim_names) != arr.ndim:
            raise ValueError(f"dim_names {dim_names} rank != mesh ndim {arr.ndim}")
        self._dim_names = list(dim_names)
        self._jax_mesh: Optional[Mesh] = None

    # ------------------------------------------------------------- properties
    @property
    def mesh(self):
        return self._mesh

    @property
    def shape(self) -> List[int]:
        return list(self._mesh.shape)

    @property
    def ndim(self) -> int:
        return self._mesh.ndim

    @property
    def dim_names(self) -> List[str]:
        return list(self._dim_names)

    @property
    def process_ids(self) -> List[int]:
        return [int(x) for x in self._mesh.flatten()]

    @property
    def size(self) -> int:
        return int(self._mesh.size)

    def get_dim_size(self, dim_name: str) -> int:
        return self._mesh.shape[self._dim_names.index(dim_name)]

    def get_rank_by_dim_and_process_id(self, dim_name, pid):
        axis = self._dim_names.index(dim_name)
        loc = np.argwhere(self._mesh == pid)
        return int(loc[0][axis]) if len(loc) else -1

    # ------------------------------------------------------------- jax bridge
    def to_jax_mesh(self) -> Mesh:
        if self._jax_mesh is None:
            devs = jax.devices()
            if self._mesh.size > len(devs):
                raise ValueError(
                    f"ProcessMesh needs {self._mesh.size} devices, found {len(devs)}"
                )
            dev_arr = np.empty(self._mesh.shape, dtype=object)
            flat = self._mesh.flatten()
            for i, pid in enumerate(flat):
                dev_arr.flat[i] = devs[int(pid)]
            self._jax_mesh = Mesh(dev_arr, tuple(self._dim_names))
        return self._jax_mesh

    # ------------------------------------------------------------- misc
    def __eq__(self, other):
        return (
            isinstance(other, ProcessMesh)
            and self._dim_names == other._dim_names
            and np.array_equal(self._mesh, other._mesh)
        )

    def __hash__(self):
        return hash((tuple(self._dim_names), self._mesh.tobytes(), self._mesh.shape))

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self._dim_names})"

    def __getitem__(self, item):
        """Sub-mesh selection (reference ProcessMesh.__getitem__). Dim names
        follow the dims that survive indexing (integer indices drop a dim,
        slices keep it)."""
        sub = self._mesh[item]
        if np.isscalar(sub) or sub.ndim == 0:
            return ProcessMesh(np.asarray([sub]), ["d0"])
        idx = item if isinstance(item, tuple) else (item,)
        # expand Ellipsis to the slices it stands for so name tracking stays
        # aligned with numpy's dim bookkeeping
        n_explicit = sum(1 for e in idx if e is not Ellipsis and e is not None)
        expanded = []
        for entry in idx:
            if entry is Ellipsis:
                expanded.extend([slice(None)] * (self._mesh.ndim - n_explicit))
            else:
                expanded.append(entry)
        kept, pos = [], 0
        for entry in expanded:
            if entry is None:
                kept.append("d%d" % len(kept))  # np.newaxis adds an unnamed dim
            elif isinstance(entry, (int, np.integer)):
                pos += 1  # dim dropped
            else:
                kept.append(self._dim_names[pos])
                pos += 1
        kept.extend(self._dim_names[pos:])
        if not kept:
            kept = ["d0"]
        return ProcessMesh(sub, kept)


def get_mesh_from_jax(jmesh: Mesh) -> ProcessMesh:
    ids = np.vectorize(lambda d: d.id)(np.asarray(jmesh.devices))
    return ProcessMesh(ids, list(jmesh.axis_names))
