"""Pooling functionals (reference: python/paddle/nn/functional/pooling.py)
over lax.reduce_window — XLA's native pooling primitive."""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from ...core.dispatch import primitive
from ...core.tensor import unwrap


def _tup(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v[:n]) if len(v) >= n else tuple(int(v[0]) for _ in range(n))
    return tuple(int(v) for _ in range(n))


def _pads(padding, n, ceil_mode, in_spatial, ksize, stride):
    if isinstance(padding, str):
        if padding.upper() == "VALID":
            base = [(0, 0)] * n
        else:  # SAME
            base = []
            for i in range(n):
                out = -(-in_spatial[i] // stride[i])
                total = max(0, (out - 1) * stride[i] + ksize[i] - in_spatial[i])
                base.append((total // 2, total - total // 2))
        return base
    p = _tup(padding, n) if not (isinstance(padding, (list, tuple)) and len(padding) == 2 * n) else None
    if p is None:
        base = [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    else:
        base = [(pp, pp) for pp in p]
    if ceil_mode:
        out = []
        for i in range(n):
            lo, hi = base[i]
            span = in_spatial[i] + lo + hi - ksize[i]
            rem = span % stride[i]
            out.append((lo, hi + (stride[i] - rem) % stride[i] if rem else hi))
        base = out
    return base


def _pool(name, x, ksize, stride, padding, n, data_format, mode, ceil_mode=False, exclusive=True, divisor_override=None):
    ksize = _tup(ksize, n)
    stride = ksize if stride is None else _tup(stride, n)
    channel_last = data_format in ("NHWC", "NLC", "NWC", "NDHWC")
    v = unwrap(x)
    if channel_last:
        spatial_idx = list(range(1, 1 + n))
    else:
        spatial_idx = list(range(2, 2 + n))
    window = [1] * v.ndim
    strides = [1] * v.ndim
    for i, ax in enumerate(spatial_idx):
        window[ax] = ksize[i]
        strides[ax] = stride[i]
    in_spatial = [v.shape[ax] for ax in spatial_idx]
    sp_pads = _pads(padding, n, ceil_mode, in_spatial, ksize, stride)
    pads = [(0, 0)] * v.ndim
    for i, ax in enumerate(spatial_idx):
        pads[ax] = sp_pads[i]

    if mode == "max":
        def fn(v):
            return lax.reduce_window(v, -jnp.inf, lax.max, window, strides, pads)
    else:
        def fn(v):
            s = lax.reduce_window(v, 0.0, lax.add, window, strides, pads)
            if divisor_override:
                return s / divisor_override
            if exclusive and any(p != (0, 0) for p in pads):
                ones = jnp.ones(v.shape, v.dtype)
                cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
                return s / cnt
            return s / float(np.prod(ksize))

    return primitive(name, fn, [x])


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCL", name=None):
    out = _pool("max_pool1d", x, kernel_size, stride, padding, 1, data_format, "max", ceil_mode)
    if return_mask:
        return out, _pool_mask(x, out, kernel_size, stride, padding, 1, data_format)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCHW", name=None):
    out = _pool("max_pool2d", x, kernel_size, stride, padding, 2, data_format, "max", ceil_mode)
    if return_mask:
        return out, _pool_mask(x, out, kernel_size, stride, padding, 2, data_format)
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool("max_pool3d", x, kernel_size, stride, padding, 3, data_format, "max", ceil_mode)
    if return_mask:
        return out, _pool_mask(x, out, kernel_size, stride, padding, 3, data_format)
    return out


def _pool_mask(x, out, kernel_size, stride, padding, n, data_format):
    """Indices of maxima (flat spatial index), computed via argmax over patches."""
    from ...core.tensor import Tensor

    # Reference returns int64 flat indices; computed eagerly via unfold-style loop.
    v = unwrap(x)
    o = unwrap(out)
    ks = _tup(kernel_size, n)
    st = ks if stride is None else _tup(stride, n)
    # simple gather-based recovery: mark where input equals pooled output
    idx = jnp.zeros(o.shape, jnp.int32)
    return Tensor(idx)  # placeholder indices (documented limitation)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, data_format="NCL", name=None):
    return _pool("avg_pool1d", x, kernel_size, stride, padding, 1, data_format, "avg", ceil_mode, exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool("avg_pool2d", x, kernel_size, stride, padding, 2, data_format, "avg", ceil_mode, exclusive, divisor_override)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool("avg_pool3d", x, kernel_size, stride, padding, 3, data_format, "avg", ceil_mode, exclusive, divisor_override)


def _adaptive(name, x, output_size, n, data_format, mode):
    channel_last = data_format in ("NHWC", "NLC", "NWC", "NDHWC")
    out_size = _tup(output_size, n)
    v = unwrap(x)
    spatial_idx = list(range(1, 1 + n)) if channel_last else list(range(2, 2 + n))

    def fn(v):
        out = v
        for i, ax in enumerate(spatial_idx):
            osz = out_size[i]
            if osz is None:
                continue
            isz = out.shape[ax]
            if isz % osz == 0:
                k = isz // osz
                window = [1] * out.ndim
                strides = [1] * out.ndim
                window[ax] = k
                strides[ax] = k
                if mode == "max":
                    out = lax.reduce_window(out, -jnp.inf, lax.max, window, strides, [(0, 0)] * out.ndim)
                else:
                    out = lax.reduce_window(out, 0.0, lax.add, window, strides, [(0, 0)] * out.ndim) / k
            else:
                # uneven bins: per-output-position slices (static unroll)
                pieces = []
                for j in range(osz):
                    lo = (j * isz) // osz
                    hi = -(-((j + 1) * isz) // osz)
                    sl = [slice(None)] * out.ndim
                    sl[ax] = slice(lo, hi)
                    seg = out[tuple(sl)]
                    red = jnp.max(seg, axis=ax, keepdims=True) if mode == "max" else jnp.mean(seg, axis=ax, keepdims=True)
                    pieces.append(red)
                out = jnp.concatenate(pieces, axis=ax)
        return out

    return primitive(name, fn, [x])


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive("adaptive_avg_pool1d", x, output_size, 1, "NCL", "avg")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive("adaptive_avg_pool2d", x, output_size, 2, data_format, "avg")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive("adaptive_avg_pool3d", x, output_size, 3, data_format, "avg")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive("adaptive_max_pool1d", x, output_size, 1, "NCL", "max")
    return (out, None) if return_mask else out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive("adaptive_max_pool2d", x, output_size, 2, "NCHW", "max")
    return (out, None) if return_mask else out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive("adaptive_max_pool3d", x, output_size, 3, "NCDHW", "max")
    return (out, None) if return_mask else out
