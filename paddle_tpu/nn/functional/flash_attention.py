"""Flash-attention API family (reference:
python/paddle/nn/functional/flash_attention.py — flash_attention :195,
flash_attn_qkvpacked, flash_attn_unpadded :695, flashmask_attention :1098).

The dense fused path runs the Pallas TPU kernel
(paddle_tpu/ops/pallas/flash_attention.py); the variants here reshape /
mask / unpad around it. Flashmask's column-sparse mask semantics
(LTS/UTE start-end rows) follow the reference's startend_row_indices
contract.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.dispatch import primitive
from ...core.tensor import Tensor, unwrap
from .attention import _xla_attention, flash_attention, scaled_dot_product_attention  # noqa: F401


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False, return_softmax=False,
                         fixed_seed_offset=None, rng_name="", training=True,
                         name=None):
    """Packed (B, S, 3, H, D) QKV flash attention (reference:
    flash_attn_qkvpacked)."""
    v = unwrap(qkv)
    q, k, vv = (Tensor(v[:, :, 0]), Tensor(v[:, :, 1]), Tensor(v[:, :, 2]))
    if not qkv.stop_gradient:
        # re-slice through the autograd tape so grads flow back into the pack
        from ...ops.manipulation import getitem

        q = getitem(qkv, (slice(None), slice(None), 0))
        k = getitem(qkv, (slice(None), slice(None), 1))
        vv = getitem(qkv, (slice(None), slice(None), 2))
    return flash_attention(q, k, vv, dropout=dropout, causal=causal,
                           return_softmax=return_softmax, training=training)


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale=None, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Varlen attention over packed (total_tokens, H, D) tensors with
    cumulative sequence offsets (reference: flash_attn_unpadded). On TPU the
    ragged batch is computed as one dense masked attention per sequence via
    a segment-id mask — static shapes, MXU-friendly."""
    sc = scale if scale is not None else 1.0 / math.sqrt(unwrap(query).shape[-1])
    cq = jnp.asarray(unwrap(cu_seqlens_q))
    ck = jnp.asarray(unwrap(cu_seqlens_k))

    def fn(q, k, v):
        tq, H, D = q.shape
        tk = k.shape[0]
        seg_q = jnp.cumsum(jnp.zeros(tq, jnp.int32).at[cq[1:-1]].add(1))
        seg_k = jnp.cumsum(jnp.zeros(tk, jnp.int32).at[ck[1:-1]].add(1))
        same = seg_q[:, None] == seg_k[None, :]
        if causal:
            pos_q = jnp.arange(tq) - cq[seg_q]
            pos_k = jnp.arange(tk) - ck[seg_k]
            same = same & (pos_q[:, None] >= pos_k[None, :])
        logits = jnp.einsum("qhd,khd->hqk", q, k) * sc
        logits = jnp.where(same[None], logits, -1e30)
        probs = jax.nn.softmax(logits, -1)
        out = jnp.einsum("hqk,khd->qhd", probs, v)
        if return_softmax:
            return out, probs
        return out

    if return_softmax:
        out, probs = primitive("flash_attn_unpadded", fn, [query, key, value])
        return out, probs
    out = primitive("flash_attn_unpadded", fn, [query, key, value])
    return out, None


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k, max_seqlen_q,
                                max_seqlen_k, scale=None, dropout=0.0,
                                causal=False, return_softmax=False,
                                fixed_seed_offset=None, rng_name="",
                                training=True, varlen_padded=True, name=None):
    """(reference: flash_attn_varlen_qkvpacked)."""
    v = unwrap(qkv)
    q, k, vv = Tensor(v[:, 0]), Tensor(v[:, 1]), Tensor(v[:, 2])
    return flash_attn_unpadded(q, k, vv, cu_seqlens_q, cu_seqlens_k,
                               max_seqlen_q, max_seqlen_k, scale=scale,
                               dropout=dropout, causal=causal,
                               return_softmax=return_softmax, training=training)


def flashmask_attention(query, key, value, startend_row_indices=None,
                        dropout=0.0, causal=True, window_size=None, name=None):
    """Column-sparse masked attention (reference: flashmask_attention :1098).

    startend_row_indices (B, H|1, S_k, 1|2|4) gives, per key column, the query
    rows where masking starts/ends — the compressed representation of
    causal-document / sliding-window / shared-prefix masks. The fused TPU
    path is the Pallas flashmask kernel (ops/pallas/flashmask.py); fallback
    composes the dense mask in XLA.
    """
    from ...ops.pallas import flash_attention as pallas_fa

    scale = 1.0 / math.sqrt(unwrap(query).shape[-1])
    if startend_row_indices is None:
        return flash_attention(query, key, value, dropout=dropout,
                               causal=causal)[0]

    if window_size is not None:
        raise NotImplementedError("window_size with startend_row_indices")

    idx = jnp.asarray(unwrap(startend_row_indices))

    if pallas_fa.available() and dropout == 0.0:
        from ...ops.pallas.flashmask import flashmask_value

        return primitive(
            "flashmask_attention",
            lambda q, k, v: flashmask_value(q, k, v, idx, causal=causal,
                                            scale=scale),
            [query, key, value],
        )

    from ...base import global_state

    dkey = global_state.default_generator.split() if dropout > 0.0 else None

    def fn(q, k, v):
        B, S, H, D = q.shape
        Sk = k.shape[1]
        rows = jnp.arange(S)[:, None]  # query row index
        # expand the compressed columns to a dense (B, Hm, S, Sk) bool mask
        if causal:
            if idx.shape[-1] == 1:
                start = idx[..., 0]  # (B, Hm, Sk): mask rows >= start
                masked = rows[None, None] >= start[:, :, None, :]
            else:
                start = idx[..., 0]
                end = idx[..., 1]
                masked = ((rows[None, None] >= start[:, :, None, :])
                          & (rows[None, None] < end[:, :, None, :]))
            base = rows < jnp.arange(Sk)[None, :]  # causal upper triangle
            disallowed = masked | base[None, None]
        else:
            lts, lte = idx[..., 0], idx[..., 1]
            uts, ute = idx[..., 2], idx[..., 3]
            lower = ((rows[None, None] >= lts[:, :, None, :])
                     & (rows[None, None] < lte[:, :, None, :]))
            upper = ((rows[None, None] >= uts[:, :, None, :])
                     & (rows[None, None] < ute[:, :, None, :]))
            disallowed = lower | upper
        bias = jnp.where(disallowed, -1e30, 0.0)
        return _xla_attention(q, k, v, causal=False, scale=scale, bias=bias,
                              dropout=dropout, dropout_key=dkey)

    return primitive("flashmask_attention_xla", fn, [query, key, value])


def calc_reduced_attn_scores(query, key, softmax_lse=None, name=None):
    """Mean-over-queries attention scores per key (reference op:
    calc_reduced_attn_scores — used by sparse-attention score pruning)."""

    def fn(q, k):
        scale = 1.0 / math.sqrt(q.shape[-1])
        logits = jnp.einsum("bshd,bthd->bhst", q, k) * scale
        probs = jax.nn.softmax(logits, -1)
        return probs.mean(axis=2)  # (B, H, S_k)

    return primitive("calc_reduced_attn_scores", fn, [query, key])


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block-sparse attention with CSR connectivity (reference op:
    sparse_attention). TPU path: densify the per-row allowed set into a mask
    (XLA) — the CSR pattern is static so the mask folds at compile time."""
    off = jnp.asarray(unwrap(sparse_csr_offset))
    cols = jnp.asarray(unwrap(sparse_csr_columns))

    def fn(q, k, v):
        B, H, S, D = q.shape  # reference uses (B, H, S, D) here
        counts = off[..., 1:] - off[..., :-1]
        # dense mask from CSR: row r attends to cols[off[r]:off[r+1]]
        row_of_entry = jnp.repeat(jnp.arange(S), counts.reshape(-1)[:S], total_repeat_length=cols.shape[-1]) \
            if cols.ndim == 1 else None
        if cols.ndim == 1:
            mask = jnp.zeros((S, S), bool).at[row_of_entry, cols].set(True)
            mask = mask[None, None]
        else:
            flat_cols = cols.reshape(B, H, -1)
            mask = jnp.zeros((B, H, S, S), bool)
            rows = jnp.repeat(jnp.arange(S)[None, None, :], B, 0)
            # per (b, h): scatter
            def scatter_bh(m, c, o):
                r = jnp.searchsorted(o, jnp.arange(c.shape[0]), side="right") - 1
                return m.at[r, c].set(True)
            mask = jax.vmap(jax.vmap(scatter_bh))(mask, flat_cols, off[..., :-1])
        scale = 1.0 / math.sqrt(D)
        logits = jnp.einsum("bhsd,bhtd->bhst", q, k) * scale
        logits = jnp.where(mask, logits, -1e30)
        probs = jax.nn.softmax(logits, -1)
        return jnp.einsum("bhst,bhtd->bhsd", probs, v)

    return primitive("sparse_attention", fn, [query, key, value])


def fused_softmax_mask(x, mask, name=None):
    """softmax(x + mask) fused (reference fused op: fused_softmax_mask)."""
    return primitive("fused_softmax_mask",
                     lambda v, m: jax.nn.softmax(v + m, -1), [x, mask])


def fused_softmax_mask_upper_triangle(x, name=None):
    """Causal-masked softmax (reference fused op:
    fused_softmax_mask_upper_triangle)."""

    def fn(v):
        S, T = v.shape[-2], v.shape[-1]
        mask = jnp.tril(jnp.ones((S, T), bool))
        return jax.nn.softmax(jnp.where(mask, v, -1e30), -1)

    return primitive("fused_softmax_mask_upper_triangle", fn, [x])
