"""Loss functionals (reference: python/paddle/nn/functional/loss.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import primitive
from ...core.tensor import Tensor, unwrap


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def mse_loss(input, label, reduction="mean", name=None):
    return primitive("mse_loss", lambda a, b: _reduce(jnp.square(a - b), reduction), [input, label])


def square_error_cost(input, label):
    return primitive("square_error_cost", lambda a, b: jnp.square(a - b), [input, label])


def l1_loss(input, label, reduction="mean", name=None):
    return primitive("l1_loss", lambda a, b: _reduce(jnp.abs(a - b), reduction), [input, label])


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        d = a - b
        ad = jnp.abs(d)
        out = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
        # paddle multiplies by delta
        return _reduce(out * delta, reduction)

    return primitive("smooth_l1_loss", fn, [input, label])


def log_loss(input, label, epsilon=1e-4, name=None):
    def fn(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)

    return primitive("log_loss", fn, [input, label])


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def fn(p, y, *w):
        out = -(y * jnp.log(jnp.clip(p, 1e-12)) + (1 - y) * jnp.log(jnp.clip(1 - p, 1e-12)))
        if w:
            out = out * w[0]
        return _reduce(out, reduction)

    args = [input, label] + ([weight] if weight is not None else [])
    return primitive("binary_cross_entropy", fn, args)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None, name=None):
    def fn(z, y, *extra):
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = extra[i]
            i += 1
        if pos_weight is not None:
            pw = extra[i]
        # stable: max(z,0) - z*y + log(1+exp(-|z|)), with pos_weight on the y term
        if pw is not None:
            log_w = (pw - 1) * y + 1
            out = (1 - y) * z + log_w * (jnp.logaddexp(0.0, -jnp.abs(z)) + jnp.maximum(-z, 0.0))
        else:
            out = jnp.maximum(z, 0.0) - z * y + jnp.logaddexp(0.0, -jnp.abs(z))
        if w is not None:
            out = out * w
        return _reduce(out, reduction)

    args = [logit, label] + [t for t in (weight, pos_weight) if t is not None]
    return primitive("bce_with_logits", fn, args)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    def fn(logp, *w):
        y = unwrap(label)
        C = logp.shape[1]
        if logp.ndim > 2:
            # [N, C, d1...] -> flatten spatial
            perm = (0,) + tuple(range(2, logp.ndim)) + (1,)
            lp = jnp.transpose(logp, perm).reshape(-1, C)
            yy = y.reshape(-1)
        else:
            lp, yy = logp, y.reshape(-1)
        picked = jnp.take_along_axis(lp, yy[:, None], axis=1)[:, 0]
        wvec = w[0][yy] if w else jnp.ones_like(picked)
        valid = (yy != ignore_index).astype(lp.dtype)
        out = -picked * wvec * valid
        if reduction == "mean":
            return jnp.sum(out) / jnp.maximum(jnp.sum(wvec * valid), 1e-12)
        if reduction == "sum":
            return jnp.sum(out)
        return out.reshape(y.shape)

    args = [input] + ([weight] if weight is not None else [])
    return primitive("nll_loss", fn, args)


def cross_entropy(
    input,
    label,
    weight=None,
    ignore_index=-100,
    reduction="mean",
    soft_label=False,
    axis=-1,
    use_softmax=True,
    label_smoothing=0.0,
    name=None,
):
    """Softmax cross entropy (reference phi cross_entropy_with_softmax kernel).

    Hard labels index the class axis; soft labels are full distributions.
    """

    def fn(z, *extra):
        y = unwrap(label)
        logp = jax.nn.log_softmax(z, axis=axis) if use_softmax else jnp.log(jnp.clip(z, 1e-12))
        if soft_label or (y.ndim == z.ndim and y.shape == z.shape and jnp.issubdtype(y.dtype, jnp.floating)):
            yy = y
            if label_smoothing > 0:
                k = z.shape[axis]
                yy = yy * (1 - label_smoothing) + label_smoothing / k
            out = -jnp.sum(yy * logp, axis=axis, keepdims=True)
            out = jnp.squeeze(out, axis)
            return _reduce(out, reduction)
        yy = y
        if yy.ndim == z.ndim and yy.shape[axis] == 1:
            yy = jnp.squeeze(yy, axis)
        ax = axis % z.ndim
        if label_smoothing > 0:
            k = z.shape[ax]
            onehot = jax.nn.one_hot(yy, k, axis=ax, dtype=logp.dtype)
            sm = onehot * (1 - label_smoothing) + label_smoothing / k
            out = -jnp.sum(sm * logp, axis=ax)
        else:
            picked = jnp.take_along_axis(logp, jnp.expand_dims(yy, ax), axis=ax)
            out = -jnp.squeeze(picked, ax)
        valid = (yy != ignore_index)
        out = jnp.where(valid, out, 0.0)
        if extra:  # class weights
            wvec = extra[0][yy] * valid.astype(logp.dtype)
            if reduction == "mean":
                return jnp.sum(out * extra[0][yy]) / jnp.maximum(jnp.sum(wvec), 1e-12)
            if reduction == "sum":
                return jnp.sum(out * extra[0][yy])
            return out * extra[0][yy]
        if reduction == "mean":
            return jnp.sum(out) / jnp.maximum(jnp.sum(valid.astype(logp.dtype)), 1e-12)
        if reduction == "sum":
            return jnp.sum(out)
        return out

    args = [input] + ([weight] if weight is not None else [])
    return primitive("cross_entropy", fn, args)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100, numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index, reduction="none", axis=axis)
    from ...ops.activation import softmax as _softmax
    from ...ops.manipulation import unsqueeze

    loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def fn(lp, y):
        if log_target:
            out = jnp.exp(y) * (y - lp)
        else:
            out = y * (jnp.log(jnp.clip(y, 1e-12)) - lp)
        if reduction == "batchmean":
            return jnp.sum(out) / lp.shape[0]
        return _reduce(out, reduction)

    return primitive("kl_div", fn, [input, label])


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    def fn(a, b, y):
        return _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction)

    return primitive("margin_ranking_loss", fn, [input, other, label])


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def fn(a, y):
        out = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(out, reduction)

    return primitive("hinge_embedding_loss", fn, [input, label])


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean", name=None):
    def fn(a, pos, neg):
        dp = jnp.sum(jnp.abs(a - pos) ** p, axis=-1) ** (1 / p)
        dn = jnp.sum(jnp.abs(a - neg) ** p, axis=-1) ** (1 / p)
        if swap:
            dn2 = jnp.sum(jnp.abs(pos - neg) ** p, axis=-1) ** (1 / p)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return primitive("triplet_margin_loss", fn, [input, positive, negative])


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum", name=None):
    def fn(z, y, *norm):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0.0) - z * y + jnp.logaddexp(0.0, -jnp.abs(z))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        out = a_t * ((1 - p_t) ** gamma) * ce
        if norm:
            out = out / norm[0]
        return _reduce(out, reduction)

    args = [logit, label] + ([normalizer] if normalizer is not None else [])
    return primitive("sigmoid_focal_loss", fn, args)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean", norm_by_times=False):
    """CTC forward-backward in log space via lax.scan (reference warpctc)."""

    def fn(lp):
        # lp: [T, B, C] log-probs (paddle convention)
        y = unwrap(labels)  # [B, S]
        in_len = unwrap(input_lengths)
        lab_len = unwrap(label_lengths)
        T, B, C = lp.shape
        S = y.shape[1]
        ext = jnp.full((B, 2 * S + 1), blank, dtype=y.dtype)
        ext = ext.at[:, 1::2].set(y)
        L = 2 * lab_len + 1
        NEG = -1e30

        def get(lp_t, idx):
            return jnp.take_along_axis(lp_t, idx, axis=1)

        alpha0 = jnp.full((B, 2 * S + 1), NEG)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        first_lab = jnp.where(lab_len > 0, get(lp[0], ext[:, 1:2])[:, 0], NEG)
        alpha0 = alpha0.at[:, 1].set(first_lab)

        same_as_2back = jnp.concatenate(
            [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1
        )

        def step(alpha, lp_t):
            a1 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]], axis=1)
            a2 = jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]], axis=1)
            a2 = jnp.where(same_as_2back, NEG, a2)
            new = jnp.logaddexp(jnp.logaddexp(alpha, a1), a2) + get(lp_t, ext)
            return new, new

        alphas_last, alphas = jax.lax.scan(step, alpha0, lp[1:])
        all_alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, B, 2S+1]
        # pick alpha at t = in_len-1, positions L-1 and L-2
        t_idx = jnp.clip(in_len - 1, 0, T - 1)
        at_T = jnp.take_along_axis(all_alphas, t_idx[None, :, None], axis=0)[0]  # [B, 2S+1]
        pos1 = jnp.take_along_axis(at_T, jnp.clip(L - 1, 0, 2 * S)[:, None], axis=1)[:, 0]
        pos2 = jnp.take_along_axis(at_T, jnp.clip(L - 2, 0, 2 * S)[:, None], axis=1)[:, 0]
        ll = jnp.logaddexp(pos1, jnp.where(lab_len > 0, pos2, NEG))
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len.astype(loss.dtype), 1.0))
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    return primitive("ctc_loss", fn, [log_probs])
