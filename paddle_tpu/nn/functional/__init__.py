"""paddle.nn.functional (reference: python/paddle/nn/functional/__init__.py)."""
from ...ops.activation import (  # noqa: F401
    celu,
    elu,
    gelu,
    glu,
    gumbel_softmax,
    hardshrink,
    hardsigmoid,
    hardswish,
    hardtanh,
    leaky_relu,
    log_sigmoid,
    log_softmax,
    maxout,
    mish,
    prelu,
    relu,
    relu6,
    rrelu,
    selu,
    sigmoid,
    silu,
    softmax,
    softplus,
    softshrink,
    softsign,
    swish,
    tanh,
    tanhshrink,
    temperature_scaled_softmax,
)
from .common import (  # noqa: F401
    bilinear,
    cosine_similarity,
    dropout,
    dropout2d,
    dropout3d,
    alpha_dropout,
    embedding,
    interpolate,
    upsample,
    label_smooth,
    linear,
    normalize,
    one_hot,
    pad,
    pixel_shuffle,
    pixel_unshuffle,
    channel_shuffle,
    unfold,
    fold,
)
from .conv import conv1d, conv1d_transpose, conv2d, conv2d_transpose, conv3d, conv3d_transpose  # noqa: F401
from .norm import batch_norm, group_norm, instance_norm, layer_norm, local_response_norm, rms_norm  # noqa: F401
from .pooling import (  # noqa: F401
    adaptive_avg_pool1d,
    adaptive_avg_pool2d,
    adaptive_avg_pool3d,
    adaptive_max_pool1d,
    adaptive_max_pool2d,
    avg_pool1d,
    avg_pool2d,
    avg_pool3d,
    max_pool1d,
    max_pool2d,
    max_pool3d,
)
from .loss import (  # noqa: F401
    binary_cross_entropy,
    binary_cross_entropy_with_logits,
    cross_entropy,
    ctc_loss,
    hinge_embedding_loss,
    kl_div,
    l1_loss,
    log_loss,
    margin_ranking_loss,
    mse_loss,
    nll_loss,
    sigmoid_focal_loss,
    smooth_l1_loss,
    softmax_with_cross_entropy,
    square_error_cost,
    triplet_margin_loss,
)
from .attention import scaled_dot_product_attention, sdp_kernel  # noqa: F401
# initialize the flash_attention SUBMODULE first (its import would otherwise
# setattr the module over the function later), then bind the function name —
# same dual nature as the reference: F.flash_attention(...) is the function,
# `from ...nn.functional.flash_attention import flashmask_attention` works
# via sys.modules
from . import flash_attention as _flash_attention_module  # noqa: F401
from .flash_attention import (  # noqa: F401
    calc_reduced_attn_scores,
    flash_attn_qkvpacked,
    flash_attn_unpadded,
    flash_attn_varlen_qkvpacked,
    flashmask_attention,
    sparse_attention,
)
from .attention import flash_attention  # noqa: F401,E402
