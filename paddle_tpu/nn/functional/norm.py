"""Normalization functionals (reference: python/paddle/nn/functional/norm.py;
rms_norm from phi fusion kernels paddle/phi/kernels/fusion/rms_norm* — here a
Pallas kernel with XLA fallback, see paddle_tpu/ops/pallas/rms_norm.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ...core.dispatch import primitive
from ...core.tensor import Tensor, unwrap


def _apply_affine(out, wb, has_w, has_b, shape=None):
    """Scale/shift ``out`` by the trailing ``wb`` args. The norm kernels
    close over presence BOOLEANS, never the weight/bias Tensors themselves:
    a Tensor closure cell would make every call an array_capture
    kernel-cache bypass, keeping the hottest norm ops on the
    trace-per-call slow path."""
    if has_w:
        w = wb[0]
        out = out * (w.reshape(shape) if shape is not None else w)
    if has_b:
        b = wb[1 if has_w else 0]
        out = out + (b.reshape(shape) if shape is not None else b)
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(normalized_shape)
    has_w, has_b = weight is not None, bias is not None

    def fn(v, *wb):
        axes = tuple(range(v.ndim - n_axes, v.ndim))
        mean = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.var(v, axis=axes, keepdims=True)
        out = (v - mean) * jax_rsqrt(var + epsilon)
        return _apply_affine(out, wb, has_w, has_b)

    args = [x] + [t for t in (weight, bias) if t is not None]
    return primitive("layer_norm", fn, args)


def jax_rsqrt(v):
    from jax import lax

    return lax.rsqrt(v)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (TPU fusion tier; Pallas kernel when enabled)."""
    from ...ops.pallas import rms_norm as pallas_rms

    if pallas_rms.available() and weight is not None:
        return pallas_rms.rms_norm(x, weight, epsilon)

    def fn(v, *w):
        ms = jnp.mean(jnp.square(v), axis=-1, keepdims=True)
        out = v * jax_rsqrt(ms + epsilon)
        if w:
            out = out * w[0]
        return out

    args = [x] + ([weight] if weight is not None else [])
    return primitive("rms_norm", fn, args)


def batch_norm(
    x,
    running_mean,
    running_var,
    weight=None,
    bias=None,
    training=False,
    momentum=0.9,
    epsilon=1e-5,
    data_format="NCHW",
    use_global_stats=None,
    name=None,
):
    """BatchNorm with running-stat update (reference phi batch_norm kernel).

    Running stats are mutated functionally (payload swap) so the jit
    functionalizer captures their update inside compiled steps.
    """
    v = unwrap(x)
    ch_axis = 1 if data_format.startswith("NC") and v.ndim > 1 else v.ndim - 1
    reduce_axes = tuple(i for i in range(v.ndim) if i != ch_axis)
    use_stats = (not training) if use_global_stats is None else use_global_stats

    has_w, has_b = weight is not None, bias is not None

    if use_stats:
        def fn(v, m, var, *wb):
            shape = [1] * v.ndim
            shape[ch_axis] = v.shape[ch_axis]
            out = (v - m.reshape(shape)) * jax_rsqrt(var.reshape(shape) + epsilon)
            return _apply_affine(out, wb, has_w, has_b, shape)

        args = [x, running_mean, running_var] + [t for t in (weight, bias) if t is not None]
        return primitive("batch_norm_infer", fn, args)

    # training: compute batch stats, update running stats
    def fn(v, *wb):
        mean = jnp.mean(v, axis=reduce_axes)
        var = jnp.var(v, axis=reduce_axes)
        shape = [1] * v.ndim
        shape[ch_axis] = v.shape[ch_axis]
        out = (v - mean.reshape(shape)) * jax_rsqrt(var.reshape(shape) + epsilon)
        return _apply_affine(out, wb, has_w, has_b, shape), mean, var

    args = [x] + [t for t in (weight, bias) if t is not None]
    out, batch_mean, batch_var = primitive("batch_norm", fn, args)
    batch_mean.stop_gradient = True
    batch_var.stop_gradient = True
    if running_mean is not None:
        n = 1
        for a in reduce_axes:
            n *= v.shape[a]
        unbiased = batch_var._value * (n / max(n - 1, 1))
        running_mean._replace_value(momentum * running_mean._value + (1 - momentum) * batch_mean._value)
        running_var._replace_value(momentum * running_var._value + (1 - momentum) * unbiased)
    return out


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None, use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW", name=None):
    has_w, has_b = weight is not None, bias is not None

    def fn(v, *wb):
        ch_axis = 1 if data_format.startswith("NC") else v.ndim - 1
        spatial = tuple(i for i in range(2, v.ndim)) if ch_axis == 1 else tuple(range(1, v.ndim - 1))
        mean = jnp.mean(v, axis=spatial, keepdims=True)
        var = jnp.var(v, axis=spatial, keepdims=True)
        out = (v - mean) * jax_rsqrt(var + eps)
        shape = [1] * v.ndim
        shape[ch_axis] = v.shape[ch_axis]
        return _apply_affine(out, wb, has_w, has_b, shape)

    args = [x] + [t for t in (weight, bias) if t is not None]
    return primitive("instance_norm", fn, args)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None, data_format="NCHW", name=None):
    has_w, has_b = weight is not None, bias is not None

    def fn(v, *wb):
        cl = not data_format.startswith("NC")
        if cl:
            v_t = jnp.moveaxis(v, -1, 1)
        else:
            v_t = v
        b, c = v_t.shape[0], v_t.shape[1]
        rest = v_t.shape[2:]
        g = v_t.reshape((b, num_groups, c // num_groups) + rest)
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) * jax_rsqrt(var + epsilon)).reshape(v_t.shape)
        shape = [1] * out.ndim
        shape[1] = c
        out = _apply_affine(out, wb, has_w, has_b, shape)
        if cl:
            out = jnp.moveaxis(out, 1, -1)
        return out

    args = [x] + [t for t in (weight, bias) if t is not None]
    return primitive("group_norm", fn, args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    def fn(v):
        ch_axis = 1 if data_format.startswith("NC") else v.ndim - 1
        sq = jnp.square(v)
        c = v.shape[ch_axis]
        half = size // 2
        pads = [(0, 0)] * v.ndim
        pads[ch_axis] = (half, size - half - 1)
        padded = jnp.pad(sq, pads)
        acc = jnp.zeros_like(v)
        for i in range(size):
            sl = [slice(None)] * v.ndim
            sl[ch_axis] = slice(i, i + c)
            acc = acc + padded[tuple(sl)]
        div = (k + alpha * acc) ** beta
        return v / div

    return primitive("local_response_norm", fn, [x])
