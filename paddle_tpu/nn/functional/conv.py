"""Convolutions (reference: python/paddle/nn/functional/conv.py over phi
conv kernels/cuDNN) — rebuilt on lax.conv_general_dilated, which XLA maps
onto the MXU natively. Weight layout follows paddle: [out_c, in_c/groups, *k].
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ...core.dispatch import primitive
from ...core.tensor import unwrap


def _tuplize(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(x) for x in v)
        if len(v) == 2 * n:  # paddle allows [before, after] pairs flattened
            return tuple(v)
        return tuple(int(v[0]) for _ in range(n))
    return tuple(int(v) for _ in range(n))


def _resolve_padding(padding, n, stride, dilation, ksize, in_spatial):
    """Return lax-style [(lo, hi)] * n."""
    if isinstance(padding, str):
        p = padding.upper()
        if p == "VALID":
            return [(0, 0)] * n
        if p == "SAME":
            pads = []
            for i in range(n):
                out = -(-in_spatial[i] // stride[i])
                eff_k = (ksize[i] - 1) * dilation[i] + 1
                total = max(0, (out - 1) * stride[i] + eff_k - in_spatial[i])
                pads.append((total // 2, total - total // 2))
            return pads
        raise ValueError(f"bad padding {padding}")
    if isinstance(padding, (list, tuple)) and len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    pads = _tuplize(padding, n)
    return [(p, p) for p in pads]


def _conv(name, x, weight, bias, stride, padding, dilation, groups, n, data_format, transpose=False, output_padding=0):
    stride = _tuplize(stride, n)
    dilation = _tuplize(dilation, n)
    channel_last = data_format in ("NHWC", "NLC", "NWC", "NDHWC")
    spatial = "DHW"[3 - n :]
    if channel_last:
        lhs_spec = "N" + spatial + "C"
    else:
        lhs_spec = "NC" + spatial
    rhs_spec = "OI" + spatial
    out_spec = lhs_spec
    dn = lax.conv_dimension_numbers(
        unwrap(x).shape, unwrap(weight).shape, (lhs_spec, rhs_spec, out_spec)
    )
    wv = unwrap(weight)
    ksize = wv.shape[2:]
    in_spatial = [unwrap(x).shape[i] for i, ch in enumerate(lhs_spec) if ch in spatial]
    pads = _resolve_padding(padding, n, stride, dilation, ksize, in_spatial)

    if not transpose:
        def fn(v, w, *b):
            out = lax.conv_general_dilated(
                v, w, window_strides=stride, padding=pads, rhs_dilation=dilation,
                dimension_numbers=dn, feature_group_count=groups,
            )
            if b:
                shape = [1] * out.ndim
                shape[out_spec.index("C")] = b[0].shape[0]
                out = out + b[0].reshape(shape)
            return out
    else:
        opad = _tuplize(output_padding, n)

        def fn(v, w, *b):
            # paddle conv_transpose weight: [in_c, out_c/groups, *k]
            # grad-of-conv formulation: lhs_dilation = stride
            k_t = jnp.flip(w, axis=tuple(range(2, 2 + n)))
            k_t = jnp.swapaxes(k_t, 0, 1)  # -> [out_c/groups, in_c, *k]
            if groups > 1:
                # regroup: [in_c, out_c/groups, *k] with feature groups
                ic = w.shape[0]
                ocg = w.shape[1]
                k_g = w.reshape((groups, ic // groups) + w.shape[1:])
                k_g = jnp.flip(k_g, axis=tuple(range(3, 3 + n)))
                k_g = jnp.swapaxes(k_g, 1, 2)  # [groups, out_c/groups, in_c/groups, *k]
                k_t = k_g.reshape((groups * ocg, ic // groups) + w.shape[2:])
            tpads = []
            for i in range(n):
                eff_k = (ksize[i] - 1) * dilation[i] + 1
                lo = eff_k - 1 - pads[i][0]
                hi = eff_k - 1 - pads[i][1] + opad[i]
                tpads.append((lo, hi))
            out = lax.conv_general_dilated(
                v, k_t, window_strides=(1,) * n, padding=tpads, lhs_dilation=stride,
                rhs_dilation=dilation, dimension_numbers=dn, feature_group_count=groups,
            )
            if b:
                shape = [1] * out.ndim
                shape[out_spec.index("C")] = b[0].shape[0]
                out = out + b[0].reshape(shape)
            return out

    args = [x, weight] + ([bias] if bias is not None else [])
    return primitive(name, fn, args)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCL", name=None):
    return _conv("conv1d", x, weight, bias, stride, padding, dilation, groups, 1, data_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCHW", name=None):
    return _conv("conv2d", x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCDHW", name=None):
    return _conv("conv3d", x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    return _conv("conv1d_transpose", x, weight, bias, stride, padding, dilation, groups, 1, data_format, transpose=True, output_padding=output_padding)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCHW", name=None):
    return _conv("conv2d_transpose", x, weight, bias, stride, padding, dilation, groups, 2, data_format, transpose=True, output_padding=output_padding)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCDHW", name=None):
    return _conv("conv3d_transpose", x, weight, bias, stride, padding, dilation, groups, 3, data_format, transpose=True, output_padding=output_padding)
