"""Attention functionals (reference: python/paddle/nn/functional/
flash_attention.py — flash_attention :195, scaled_dot_product_attention :976).

TPU-native: the fused path is a Pallas flash-attention kernel
(paddle_tpu/ops/pallas/flash_attention.py); off-TPU or when disabled, an XLA
composition (which XLA still fuses well) is used. Layout follows paddle:
[batch, seqlen, num_heads, head_dim].
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.dispatch import primitive
from ...core.tensor import unwrap


def _seed_from_key(key):
    """(1,) int32 seed for the in-kernel dropout PRNG, derived from (and
    threaded through compilation like) the framework RNG stream."""
    return jax.random.randint(key, (1,), 0, 2**31 - 1, jnp.int32)


def _xla_attention(q, k, v, *, causal, scale, bias=None, dropout=0.0, dropout_key=None):
    # q,k,v: [B, S, H, D] -> einsum over head dim
    logits = jnp.einsum("bshd,bthd->bhst", q, k) * scale
    if bias is not None:
        logits = logits + bias
    if causal:
        s, t = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((s, t), bool), t - s)
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout), 0.0)
    return jnp.einsum("bhst,bthd->bshd", probs, v)


def flash_attention(
    query,
    key,
    value,
    dropout=0.0,
    causal=False,
    return_softmax=False,
    fixed_seed_offset=None,
    rng_name="",
    training=True,
    name=None,
):
    """paddle.nn.functional.flash_attention.flash_attention parity."""
    from ...base import global_state
    from ...ops.pallas import flash_attention as pallas_fa

    scale = 1.0 / math.sqrt(unwrap(query).shape[-1])
    dkey = global_state.default_generator.split() if (dropout > 0.0 and training) else None

    if return_softmax:
        # The flash kernel never materializes the probability matrix — the
        # debug contract (reference flash_attention return_softmax=True)
        # is served by the XLA composition, which does.
        def fn(q, k, v):
            logits = jnp.einsum("bshd,bthd->bhst", q, k) * scale
            if causal:
                s, t = logits.shape[-2], logits.shape[-1]
                mask = jnp.tril(jnp.ones((s, t), bool), t - s)
                logits = jnp.where(mask, logits, -1e30)
            probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)  # noqa: NM1101 — widening for softmax stability, cast back after
            p = probs
            if dropout > 0.0 and training and dkey is not None:
                keep = jax.random.bernoulli(dkey, 1.0 - dropout, p.shape)
                p = jnp.where(keep, p / (1.0 - dropout), 0.0)
            return jnp.einsum("bhst,bthd->bshd", p, v), probs

        out, probs = primitive("flash_attention_xla", fn, [query, key, value])
        return out, probs

    if pallas_fa.available():
        drop_eff = dropout if training else 0.0
        seed = _seed_from_key(dkey) if drop_eff > 0.0 else None
        out = primitive(
            "flash_attention",
            lambda q, k, v: pallas_fa.flash_attention_value(
                q, k, v, causal=causal, scale=scale, dropout=drop_eff,
                seed=seed),
            [query, key, value],
        )
    else:
        out = primitive(
            "flash_attention_xla",
            lambda q, k, v: _xla_attention(
                q, k, v, causal=causal, scale=scale, dropout=dropout if training else 0.0, dropout_key=dkey
            ),
            [query, key, value],
        )
    return out, None


def scaled_dot_product_attention(
    query, key, value, attn_mask=None, dropout_p=0.0, is_causal=False, training=True, name=None
):
    """paddle.nn.functional.scaled_dot_product_attention parity
    (q/k/v: [B, S, H, D]; attn_mask broadcastable to [B, H, S, T])."""
    from ...base import global_state
    from ...ops.pallas import flash_attention as pallas_fa

    scale = 1.0 / math.sqrt(unwrap(query).shape[-1])
    if attn_mask is None and pallas_fa.available():
        drop_eff = dropout_p if training else 0.0
        seed = (_seed_from_key(global_state.default_generator.split())
                if drop_eff > 0.0 else None)
        return primitive(
            "sdpa_flash",
            lambda q, k, v: pallas_fa.flash_attention_value(
                q, k, v, causal=is_causal, scale=scale, dropout=drop_eff,
                seed=seed),
            [query, key, value],
        )
    dkey = global_state.default_generator.split() if (dropout_p > 0.0 and training) else None
    if attn_mask is not None:
        mask_v = unwrap(attn_mask)
        if mask_v.dtype == jnp.bool_:
            bias = jnp.where(mask_v, 0.0, -1e30)
        else:
            bias = mask_v

        return primitive(
            "sdpa_xla",
            lambda q, k, v, b: _xla_attention(
                q, k, v, causal=is_causal, scale=scale, bias=b,
                dropout=dropout_p if training else 0.0, dropout_key=dkey,
            ),
            [query, key, value, attn_mask if mask_v.dtype != jnp.bool_ else __wrap(bias)],
        )
    return primitive(
        "sdpa_xla",
        lambda q, k, v: _xla_attention(
            q, k, v, causal=is_causal, scale=scale, dropout=dropout_p if training else 0.0, dropout_key=dkey
        ),
        [query, key, value],
    )


def __wrap(arr):
    from ...core.tensor import Tensor

    return Tensor(arr)


class sdp_kernel:
    """Context manager selecting attention backends (compat shim)."""

    def __init__(self, enable_flash=True, enable_math=True, enable_mem_efficient=True):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False
