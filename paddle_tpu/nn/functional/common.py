"""Common NN functionals (reference: python/paddle/nn/functional/common.py,
input.py, vision.py)."""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...base import global_state
from ...core.dispatch import primitive
from ...core.tensor import Tensor, unwrap
from ...ops.manipulation import pad  # noqa: F401  (re-export; paddle has F.pad)


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b, W shaped [in, out] (reference phi matmul+add fused by XLA)."""
    if bias is None:
        return primitive("linear", lambda v, w: jnp.matmul(v, w), [x, weight])
    return primitive("linear", lambda v, w, b: jnp.matmul(v, w) + b, [x, weight, bias])


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    def fn(idx, w):
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out

    # gradient flows to weight only; indices pass through jnp.take
    return primitive("embedding", lambda w: fn(unwrap(x), w), [weight])


def one_hot(x, num_classes, name=None):
    from ...ops.creation import one_hot as _oh

    return _oh(x, num_classes)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(x)
    # the key is split host-side and threaded as a TRACED argument (not a
    # closure cell): the kernel-cache signature stays hashable, so dropout
    # replays one compiled executable per shape with per-call randomness
    # riding in as data (ROADMAP eager-dispatch leftover)
    key = global_state.default_generator.split()

    def fn(v, k):
        shape = list(v.shape)
        if axis is not None:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = [s if i in [a % v.ndim for a in axes] else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(k, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - p), 0.0)
        return jnp.where(keep, v, 0.0)

    return primitive("dropout", fn, [x, key])


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = [2, 3] if data_format == "NCHW" else [1, 2]
    drop_axes = [i for i in range(4) if i not in ax]
    return dropout(x, p, axis=drop_axes, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = [2, 3, 4] if data_format == "NCDHW" else [1, 2, 3]
    drop_axes = [i for i in range(5) if i not in ax]
    return dropout(x, p, axis=drop_axes, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    key = global_state.default_generator.split()

    def fn(v, k):  # key threaded as a traced arg — see dropout
        keep = jax.random.bernoulli(k, 1.0 - p, v.shape)
        a = ((1.0 - p) * (1.0 + p * alpha_p**2)) ** -0.5
        b = -a * alpha_p * p
        return a * jnp.where(keep, v, alpha_p) + b

    return primitive("alpha_dropout", fn, [x, key])


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def fn(v):
        norm = jnp.sum(jnp.abs(v) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return v / jnp.maximum(norm, epsilon)

    return primitive("normalize", fn, [x])


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def fn(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.sqrt(jnp.sum(a * a, axis=axis)) * jnp.sqrt(jnp.sum(b * b, axis=axis))
        return num / jnp.maximum(den, eps)

    return primitive("cosine_similarity", fn, [x1, x2])


def bilinear(x1, x2, weight, bias=None, name=None):
    def fn(a, b, w, *bias_arg):
        # w: [out, in1, in2]
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bias_arg:
            out = out + bias_arg[0]
        return out

    args = [x1, x2, weight] + ([bias] if bias is not None else [])
    return primitive("bilinear", fn, args)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def fn(v):
        k = v.shape[-1]
        if prior_dist is not None:
            return (1 - epsilon) * v + epsilon * unwrap(prior_dist)
        return (1 - epsilon) * v + epsilon / k

    return primitive("label_smooth", fn, [label])


def interpolate(
    x,
    size=None,
    scale_factor=None,
    mode="nearest",
    align_corners=False,
    align_mode=0,
    data_format="NCHW",
    name=None,
):
    v = unwrap(x)
    cl = data_format in ("NHWC", "NWC", "NDHWC")
    spatial_ndim = v.ndim - 2
    if cl:
        spatial = v.shape[1:-1]
    else:
        spatial = v.shape[2:]
    if size is not None:
        if isinstance(size, Tensor):
            size = size.tolist()
        out_size = [int(unwrap(s)) if isinstance(s, Tensor) else int(s) for s in (size if isinstance(size, (list, tuple)) else [size] * spatial_ndim)]
    else:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * spatial_ndim
        out_size = [int(np.floor(s * f)) for s, f in zip(spatial, sf)]

    method = {"nearest": "nearest", "bilinear": "linear", "linear": "linear", "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]

    def fn(v):
        if cl:
            new_shape = (v.shape[0],) + tuple(out_size) + (v.shape[-1],)
            axes = tuple(range(1, 1 + spatial_ndim))
        else:
            new_shape = v.shape[:2] + tuple(out_size)
            axes = tuple(range(2, 2 + spatial_ndim))
        if method == "nearest":
            # exact nearest (XLA gather): index mapping floor(i*scale)
            out = v
            for ax, osz in zip(axes, out_size):
                isz = out.shape[ax]
                idx = jnp.floor(jnp.arange(osz) * (isz / osz)).astype(jnp.int32)
                out = jnp.take(out, idx, axis=ax)
            return out
        if align_corners:
            out = v
            for ax, osz in zip(axes, out_size):
                isz = out.shape[ax]
                pos = jnp.linspace(0.0, isz - 1.0, osz)
                lo = jnp.floor(pos).astype(jnp.int32)
                hi = jnp.minimum(lo + 1, isz - 1)
                w = (pos - lo).astype(v.dtype)
                shape = [1] * out.ndim
                shape[ax] = osz
                w = w.reshape(shape)
                out = jnp.take(out, lo, axis=ax) * (1 - w) + jnp.take(out, hi, axis=ax) * w
            return out
        return jax.image.resize(v, new_shape, method=method)

    return primitive("interpolate", fn, [x])


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def fn(v):
        if data_format == "NCHW":
            b, c, h, w = v.shape
            v = v.reshape(b, c // (r * r), r, r, h, w)
            v = v.transpose(0, 1, 4, 2, 5, 3)
            return v.reshape(b, c // (r * r), h * r, w * r)
        b, h, w, c = v.shape
        v = v.reshape(b, h, w, r, r, c // (r * r))
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(b, h * r, w * r, c // (r * r))

    return primitive("pixel_shuffle", fn, [x])


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def fn(v):
        if data_format == "NCHW":
            b, c, h, w = v.shape
            v = v.reshape(b, c, h // r, r, w // r, r)
            v = v.transpose(0, 1, 3, 5, 2, 4)
            return v.reshape(b, c * r * r, h // r, w // r)
        b, h, w, c = v.shape
        v = v.reshape(b, h // r, r, w // r, r, c)
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(b, h // r, w // r, c * r * r)

    return primitive("pixel_unshuffle", fn, [x])


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def fn(v):
        if data_format == "NCHW":
            b, c, h, w = v.shape
            v = v.reshape(b, groups, c // groups, h, w)
            return v.transpose(0, 2, 1, 3, 4).reshape(b, c, h, w)
        b, h, w, c = v.shape
        v = v.reshape(b, h, w, groups, c // groups)
        return v.transpose(0, 1, 2, 4, 3).reshape(b, h, w, c)

    return primitive("channel_shuffle", fn, [x])


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference phi unfold kernel)."""
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    if len(pd) == 2:
        pd = [pd[0], pd[1], pd[0], pd[1]]
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2

    def fn(v):
        b, c, h, w = v.shape
        v = jnp.pad(v, ((0, 0), (0, 0), (pd[0], pd[2]), (pd[1], pd[3])))
        oh = (v.shape[2] - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (v.shape[3] - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        patches = []
        for ki in range(ks[0]):
            for kj in range(ks[1]):
                sub = v[:, :, ki * dl[0] : ki * dl[0] + oh * st[0] : st[0], kj * dl[1] : kj * dl[1] + ow * st[1] : st[1]]
                patches.append(sub)
        out = jnp.stack(patches, axis=2)  # [b, c, k*k, oh, ow]
        return out.reshape(b, c * ks[0] * ks[1], oh * ow)

    return primitive("unfold", fn, [x])


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    os_ = output_sizes if isinstance(output_sizes, (list, tuple)) else [output_sizes] * 2
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    if len(pd) == 2:
        pd = [pd[0], pd[1], pd[0], pd[1]]
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2

    def fn(v):
        b = v.shape[0]
        c = v.shape[1] // (ks[0] * ks[1])
        ph, pw = os_[0] + pd[0] + pd[2], os_[1] + pd[1] + pd[3]
        oh = (ph - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (pw - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        v = v.reshape(b, c, ks[0], ks[1], oh, ow)
        out = jnp.zeros((b, c, ph, pw), v.dtype)
        for ki in range(ks[0]):
            for kj in range(ks[1]):
                out = out.at[:, :, ki * dl[0] : ki * dl[0] + oh * st[0] : st[0], kj * dl[1] : kj * dl[1] + ow * st[1] : st[1]].add(
                    v[:, :, ki, kj]
                )
        return out[:, :, pd[0] : ph - pd[2], pd[1] : pw - pd[3]]

    return primitive("fold", fn, [x])
