"""Initializer implementations (reference: python/paddle/nn/initializer/
{constant,normal,uniform,xavier,kaiming,orthogonal,dirac,assign}.py).

Each initializer is a callable writing into a Parameter in place via the
global RNG (so paddle.seed reproduces the reference contract).
"""
from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp

from ...base import global_state
from ...core.tensor import Tensor

_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init, _global_bias_init = weight_init, bias_init


def global_initializer(is_bias):
    return _global_bias_init if is_bias else _global_weight_init


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0,
        "conv2d": 1.0,
        "conv3d": 1.0,
        "tanh": 5.0 / 3.0,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    return gains[nonlinearity]


def _fan_in_out(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels [out_c?, in_c?, *k] — paddle stores conv weight as
    # [out_c, in_c/groups, *k]; linear as [in, out].
    receptive = int(np.prod(shape[2:]))
    fan_in = shape[1] * receptive
    fan_out = shape[0] * receptive
    return fan_in, fan_out


class Initializer:
    def __call__(self, param: Tensor, block=None):
        raise NotImplementedError

    def _set(self, param: Tensor, value):
        param._replace_value(jnp.asarray(value, param._value.dtype))


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, param, block=None):
        self._set(param, jnp.full(param._value.shape, self.value))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, param, block=None):
        key = global_state.default_generator.split()
        self._set(param, self.mean + self.std * jax.random.normal(key, param._value.shape))


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, param, block=None):
        key = global_state.default_generator.split()
        z = jax.random.truncated_normal(key, (self.a - self.mean) / self.std, (self.b - self.mean) / self.std, param._value.shape)
        self._set(param, self.mean + self.std * z)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, param, block=None):
        key = global_state.default_generator.split()
        self._set(param, jax.random.uniform(key, param._value.shape, minval=self.low, maxval=self.high))


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, param, block=None):
        fi, fo = _fan_in_out(tuple(param._value.shape))
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        key = global_state.default_generator.split()
        self._set(param, std * jax.random.normal(key, param._value.shape))


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, param, block=None):
        fi, fo = _fan_in_out(tuple(param._value.shape))
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        key = global_state.default_generator.split()
        self._set(param, jax.random.uniform(key, param._value.shape, minval=-limit, maxval=limit))


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, param, block=None):
        fi, _ = _fan_in_out(tuple(param._value.shape))
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        key = global_state.default_generator.split()
        self._set(param, std * jax.random.normal(key, param._value.shape))


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, param, block=None):
        fi, _ = _fan_in_out(tuple(param._value.shape))
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        key = global_state.default_generator.split()
        self._set(param, jax.random.uniform(key, param._value.shape, minval=-limit, maxval=limit))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, param, block=None):
        shape = tuple(param._value.shape)
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        key = global_state.default_generator.split()
        flat = jax.random.normal(key, (max(rows, cols), min(rows, cols)))
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        self._set(param, self.gain * q[:rows, :cols].reshape(shape))


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, param, block=None):
        shape = tuple(param._value.shape)
        out = np.zeros(shape, np.float32)
        out_per_group = shape[0] // self.groups
        mid = tuple(s // 2 for s in shape[2:])
        for g in range(self.groups):
            for i in range(min(out_per_group, shape[1])):
                out[(g * out_per_group + i, i) + mid] = 1.0
        self._set(param, out)


class Bilinear(Initializer):
    def __call__(self, param, block=None):
        shape = tuple(param._value.shape)
        k = shape[-1]
        factor = (k + 1) // 2
        center = factor - 1 if k % 2 == 1 else factor - 0.5
        og = np.ogrid[:k, :k]
        filt = (1 - abs(og[0] - center) / factor) * (1 - abs(og[1] - center) / factor)
        out = np.zeros(shape, np.float32)
        out[range(shape[0]), range(shape[1]) if shape[1] == shape[0] else 0, :, :] = filt
        self._set(param, out)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, param, block=None):
        v = self.value.numpy() if isinstance(self.value, Tensor) else np.asarray(self.value)
        self._set(param, v.reshape(param._value.shape))
