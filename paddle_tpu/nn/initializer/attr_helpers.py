"""ParamAttr (reference: python/paddle/base/param_attr.py)."""
from __future__ import annotations


class ParamAttr:
    def __init__(
        self,
        name=None,
        initializer=None,
        learning_rate=1.0,
        regularizer=None,
        trainable=True,
        do_model_average=True,
        need_clip=True,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip


def resolve_param_attr(attr):
    """Normalize the `weight_attr`/`bias_attr` argument convention:
    None -> default; False -> no parameter; str -> named; Initializer -> wraps;
    ParamAttr -> as-is."""
    if attr is None:
        return ParamAttr()
    if attr is False:
        return None
    if isinstance(attr, str):
        return ParamAttr(name=attr)
    if isinstance(attr, ParamAttr):
        return attr
    # an Initializer instance
    return ParamAttr(initializer=attr)
