"""Parameter initializers (reference: python/paddle/nn/initializer/*)."""
from .initializers import (  # noqa: F401
    Assign,
    Bilinear,
    Constant,
    Dirac,
    Initializer,
    KaimingNormal,
    KaimingUniform,
    Normal,
    Orthogonal,
    TruncatedNormal,
    Uniform,
    XavierNormal,
    XavierUniform,
    calculate_gain,
    set_global_initializer,
)
from .attr_helpers import ParamAttr, resolve_param_attr  # noqa: F401
