"""Transformer layers.

Rebuild of the reference's transformer stack (python/paddle/nn/layer/
transformer.py): MultiHeadAttention (with incremental-decode caches),
TransformerEncoderLayer/TransformerEncoder, TransformerDecoderLayer/
TransformerDecoder, Transformer. TPU-native: attention routes through
F.scaled_dot_product_attention, which lowers to the Pallas flash-attention
kernel when applicable and otherwise to one fused XLA einsum-softmax-einsum
block; caches are functional (returned, not mutated) so the decode loop can
live under jit/lax.scan.
"""
from __future__ import annotations

import collections

import jax.numpy as jnp

from .. import functional as F
from ..initializer import XavierUniform
from .common import Dropout, Linear
from .container import LayerList
from .layers import Layer
from .norm import LayerNorm


def _convert_attention_mask(attn_mask, dtype):
    """Bool mask (True=keep) -> additive float mask; float passes through.
    Reference: transformer.py::_convert_attention_mask."""
    if attn_mask is None:
        return None
    v = attn_mask._value if hasattr(attn_mask, "_value") else attn_mask
    if v.dtype == jnp.bool_:
        return jnp.where(v, jnp.zeros([], dtype), jnp.full([], -1e9, dtype))
    return v.astype(dtype)


class MultiHeadAttention(Layer):
    """Reference: python/paddle/nn/layer/transformer.py::MultiHeadAttention.

    Inputs are [batch, seq, embed_dim]; ``num_heads`` attention heads run in
    parallel. ``cache`` support mirrors the reference's Cache/StaticCache
    namedtuples but functionally: forward returns (out, new_cache).
    """

    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(
        self,
        embed_dim,
        num_heads,
        dropout=0.0,
        kdim=None,
        vdim=None,
        need_weights=False,
        weight_attr=None,
        bias_attr=None,
    ):
        super().__init__()
        self.embed_dim = embed_dim
        self.kdim = kdim or embed_dim
        self.vdim = vdim or embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        if self.head_dim * num_heads != embed_dim:
            raise ValueError("embed_dim must be divisible by num_heads")
        self.dropout = dropout
        self.need_weights = need_weights
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _split_heads(self, x):
        b, s = x.shape[0], x.shape[1]
        return x.reshape([b, s, self.num_heads, self.head_dim])

    def gen_cache(self, key, value=None, type=None):
        """Build an empty/static cache (reference :396). ``type=Cache`` (the
        default) returns an EMPTY [B, 0, H, D] K/V pair so incremental decode
        starts from nothing; StaticCache stores the projected cross-attention
        memory."""
        if type == MultiHeadAttention.StaticCache or value is not None:
            value = key if value is None else value
            k = self._split_heads(self.k_proj(key))
            v = self._split_heads(self.v_proj(value))
            return MultiHeadAttention.StaticCache(k, v)
        from ...ops.creation import zeros

        b = key.shape[0]
        empty = zeros([b, 0, self.num_heads, self.head_dim], dtype=key.dtype)
        return MultiHeadAttention.Cache(empty, empty)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = key if value is None else value
        q = self._split_heads(self.q_proj(query))
        if isinstance(cache, MultiHeadAttention.StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self._split_heads(self.k_proj(key))
            v = self._split_heads(self.v_proj(value))
            if isinstance(cache, MultiHeadAttention.Cache):
                from ...ops.manipulation import concat

                k = concat([cache.k, k], axis=1)
                v = concat([cache.v, v], axis=1)
                cache = MultiHeadAttention.Cache(k, v)

        mask = _convert_attention_mask(attn_mask, jnp.float32)
        weights = None
        if self.need_weights:
            # explicit-softmax path (flash attention never materializes the
            # weight matrix); [B,S,H,D] -> [B,H,S,T] scores
            from ...ops.activation import softmax
            from ...ops.linalg import matmul
            from ...ops.manipulation import transpose

            qt = transpose(q, [0, 2, 1, 3])
            kt = transpose(k, [0, 2, 1, 3])
            vt = transpose(v, [0, 2, 1, 3])
            product = matmul(qt, kt, transpose_y=True) * (self.head_dim ** -0.5)
            if mask is not None:
                product = product + mask
            weights = softmax(product)
            dropped = F.dropout(weights, self.dropout, training=self.training)
            out = transpose(matmul(dropped, vt), [0, 2, 1, 3])
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=mask, dropout_p=self.dropout, training=self.training
            )
        out = out.reshape([out.shape[0], out.shape[1], self.embed_dim])
        out = self.out_proj(out)
        # reference returns (out[, weights][, cache]) for any non-None cache
        outs = [out]
        if self.need_weights:
            outs.append(weights)
        if cache is not None:
            outs.append(cache)
        return outs[0] if len(outs) == 1 else tuple(outs)


class TransformerEncoderLayer(Layer):
    """Reference: transformer.py::TransformerEncoderLayer (self-attn + FFN,
    pre/post-norm via ``normalize_before``)."""

    def __init__(
        self,
        d_model,
        nhead,
        dim_feedforward,
        dropout=0.1,
        activation="relu",
        attn_dropout=None,
        act_dropout=None,
        normalize_before=False,
        weight_attr=None,
        bias_attr=None,
        layer_norm_eps=1e-5,
    ):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout, weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout, mode="upscale_in_train")
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout, mode="upscale_in_train")
        self.dropout2 = Dropout(dropout, mode="upscale_in_train")
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    """Reference: transformer.py::TransformerEncoder."""

    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([encoder_layer] + [copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                output = layer(output, src_mask)
            else:
                output, c = layer(output, src_mask, cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    """Reference: transformer.py::TransformerDecoderLayer (self-attn +
    cross-attn + FFN)."""

    def __init__(
        self,
        d_model,
        nhead,
        dim_feedforward,
        dropout=0.1,
        activation="relu",
        attn_dropout=None,
        act_dropout=None,
        normalize_before=False,
        weight_attr=None,
        bias_attr=None,
        layer_norm_eps=1e-5,
    ):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout, weight_attr=weight_attr, bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout, weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout, mode="upscale_in_train")
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm3 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout, mode="upscale_in_train")
        self.dropout2 = Dropout(dropout, mode="upscale_in_train")
        self.dropout3 = Dropout(dropout, mode="upscale_in_train")
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        self_cache, static_cache = cache if cache is not None else (None, None)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if self_cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        else:
            tgt, self_cache = self.self_attn(tgt, tgt, tgt, tgt_mask, self_cache)
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if static_cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        else:
            tgt, static_cache = self.cross_attn(tgt, memory, memory, memory_mask, static_cache)
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (self_cache, static_cache))

    def gen_cache(self, memory):
        self_cache = self.self_attn.gen_cache(memory, type=MultiHeadAttention.Cache)
        static_cache = self.cross_attn.gen_cache(memory, memory, type=MultiHeadAttention.StaticCache)
        return self_cache, static_cache


class TransformerDecoder(Layer):
    """Reference: transformer.py::TransformerDecoder."""

    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([decoder_layer] + [copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        output = tgt
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                output = layer(output, memory, tgt_mask, memory_mask)
            else:
                output, c = layer(output, memory, tgt_mask, memory_mask, cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        caches = [layer.gen_cache(memory) for layer in self.layers]
        if do_zip:
            caches = list(zip(*caches))
        return caches


class Transformer(Layer):
    """Reference: transformer.py::Transformer — full encoder-decoder."""

    def __init__(
        self,
        d_model=512,
        nhead=8,
        num_encoder_layers=6,
        num_decoder_layers=6,
        dim_feedforward=2048,
        dropout=0.1,
        activation="relu",
        attn_dropout=None,
        act_dropout=None,
        normalize_before=False,
        weight_attr=None,
        bias_attr=None,
        custom_encoder=None,
        custom_decoder=None,
    ):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            encoder_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr,
            )
            encoder_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(encoder_layer, num_encoder_layers, encoder_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            decoder_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr,
            )
            decoder_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(decoder_layer, num_decoder_layers, decoder_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    def generate_square_subsequent_mask(self, length):
        """Lower-triangular additive causal mask (reference :1482)."""
        from ...core.tensor import Tensor

        m = jnp.where(jnp.tril(jnp.ones([length, length], jnp.bool_)), 0.0, -jnp.inf).astype(jnp.float32)
        return Tensor(m)
