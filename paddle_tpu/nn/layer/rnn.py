"""Recurrent layers: SimpleRNN/LSTM/GRU cells + sequence wrappers.

Rebuild of the reference's RNN stack (python/paddle/nn/layer/rnn.py:
RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN, BiRNN, SimpleRNN, LSTM,
GRU). TPU-native: the whole-sequence run is ONE framework primitive whose
implementation is `lax.scan` over time — XLA compiles the recurrence into a
single fused loop on device (no per-step python dispatch, static shapes), and
`jax.vjp` through the scan gives the BPTT gradient. Variable lengths use a
mask inside the scan instead of dynamic shapes.

Gate order matches the reference (i, f, c, o for LSTM; r, z, c for GRU) so
state dicts are interchangeable.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from ...core.dispatch import primitive
from ...core.tensor import Tensor, unwrap
from .. import functional as F
from ..initializer import Uniform
from .layers import Layer


def _std_init(hidden_size):
    k = 1.0 / math.sqrt(hidden_size)
    return Uniform(-k, k)


class RNNCellBase(Layer):
    """Reference: rnn.py::RNNCellBase — provides get_initial_states."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None, init_value=0.0, batch_dim_idx=0):
        batch = unwrap(batch_ref).shape[batch_dim_idx]
        dtype = dtype or "float32"
        if isinstance(self.state_shape, tuple):
            return tuple(
                Tensor(jnp.full((batch,) + tuple(s), init_value, dtype)) for s in self.state_shape
            )
        return Tensor(jnp.full((batch,) + tuple(self.state_shape), init_value, dtype))


class SimpleRNNCell(RNNCellBase):
    """h' = act(W_ih x + b_ih + W_hh h + b_hh). Reference rnn.py::SimpleRNNCell."""

    def __init__(self, input_size, hidden_size, activation="tanh", weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        init = _std_init(hidden_size)
        self.weight_ih = self.create_parameter([hidden_size, input_size], attr=weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size], attr=weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([hidden_size], attr=bias_ih_attr, is_bias=True, default_initializer=init) if bias_ih_attr is not False else None
        self.bias_hh = self.create_parameter([hidden_size], attr=bias_hh_attr, is_bias=True, default_initializer=init) if bias_hh_attr is not False else None
        self.input_size, self.hidden_size = input_size, hidden_size
        if activation not in ("tanh", "relu"):
            raise ValueError("SimpleRNNCell activation must be tanh or relu")
        self.activation = activation

    @property
    def state_shape(self):
        return [self.hidden_size]

    def _weights(self):
        return [self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh]

    @staticmethod
    def _step(act, x, h, w_ih, w_hh, b_ih, b_hh):
        z = x @ w_ih.T + h @ w_hh.T
        if b_ih is not None:
            z = z + b_ih
        if b_hh is not None:
            z = z + b_hh
        h = jnp.tanh(z) if act == "tanh" else jax.nn.relu(z)
        return h, h

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = self.activation
        has_ih, has_hh = self.bias_ih is not None, self.bias_hh is not None

        def fn(x, h, w_ih, w_hh, *biases):
            it = iter(biases)
            b_ih = next(it) if has_ih else None
            b_hh = next(it) if has_hh else None
            return SimpleRNNCell._step(act, x, h, w_ih, w_hh, b_ih, b_hh)[0]

        args = [inputs, states, self.weight_ih, self.weight_hh] + [b for b in (self.bias_ih, self.bias_hh) if b is not None]
        h = primitive("simple_rnn_cell", fn, args)
        return h, h


class LSTMCell(RNNCellBase):
    """Gate order i,f,c,o (reference rnn.py::LSTMCell)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, proj_size=0, name=None):
        super().__init__()
        if proj_size:
            raise NotImplementedError(
                "LSTMCell proj_size is not supported yet; use a Linear "
                "projection on the output instead"
            )
        init = _std_init(hidden_size)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size], attr=weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size], attr=weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([4 * hidden_size], attr=bias_ih_attr, is_bias=True, default_initializer=init) if bias_ih_attr is not False else None
        self.bias_hh = self.create_parameter([4 * hidden_size], attr=bias_hh_attr, is_bias=True, default_initializer=init) if bias_hh_attr is not False else None
        self.input_size, self.hidden_size = input_size, hidden_size

    @property
    def state_shape(self):
        return ([self.hidden_size], [self.hidden_size])

    def _weights(self):
        return [self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh]

    @staticmethod
    def _step(x, h, c, w_ih, w_hh, b_ih, b_hh):
        z = x @ w_ih.T + h @ w_hh.T
        if b_ih is not None:
            z = z + b_ih
        if b_hh is not None:
            z = z + b_hh
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c = f * c + i * g
        h = o * jnp.tanh(c)
        return h, c

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h0, c0 = states
        has_ih, has_hh = self.bias_ih is not None, self.bias_hh is not None

        def fn(x, h, c, w_ih, w_hh, *biases):
            it = iter(biases)
            b_ih = next(it) if has_ih else None
            b_hh = next(it) if has_hh else None
            return LSTMCell._step(x, h, c, w_ih, w_hh, b_ih, b_hh)

        args = [inputs, h0, c0, self.weight_ih, self.weight_hh] + [b for b in (self.bias_ih, self.bias_hh) if b is not None]
        h, c = primitive("lstm_cell", fn, args, n_outputs=2)
        return h, (h, c)


class GRUCell(RNNCellBase):
    """Gate order r,z,c; candidate uses r * (W_hh_c h + b_hh_c) like the
    reference (rnn.py::GRUCell)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        init = _std_init(hidden_size)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size], attr=weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size], attr=weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([3 * hidden_size], attr=bias_ih_attr, is_bias=True, default_initializer=init) if bias_ih_attr is not False else None
        self.bias_hh = self.create_parameter([3 * hidden_size], attr=bias_hh_attr, is_bias=True, default_initializer=init) if bias_hh_attr is not False else None
        self.input_size, self.hidden_size = input_size, hidden_size

    @property
    def state_shape(self):
        return [self.hidden_size]

    def _weights(self):
        return [self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh]

    @staticmethod
    def _step(x, h, w_ih, w_hh, b_ih, b_hh):
        zi = x @ w_ih.T
        zh = h @ w_hh.T
        if b_ih is not None:
            zi = zi + b_ih
        if b_hh is not None:
            zh = zh + b_hh
        ri, zi_, ci = jnp.split(zi, 3, axis=-1)
        rh, zh_, ch = jnp.split(zh, 3, axis=-1)
        r = jax.nn.sigmoid(ri + rh)
        z = jax.nn.sigmoid(zi_ + zh_)
        c = jnp.tanh(ci + r * ch)
        return (1.0 - z) * c + z * h

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        has_ih, has_hh = self.bias_ih is not None, self.bias_hh is not None

        def fn(x, h, w_ih, w_hh, *biases):
            it = iter(biases)
            b_ih = next(it) if has_ih else None
            b_hh = next(it) if has_hh else None
            return GRUCell._step(x, h, w_ih, w_hh, b_ih, b_hh)

        args = [inputs, states, self.weight_ih, self.weight_hh] + [b for b in (self.bias_ih, self.bias_hh) if b is not None]
        h = primitive("gru_cell", fn, args)
        return h, h


def _scan_layer(step, x, init_states, weights, *, reverse, mask):
    """Run one direction of one layer with lax.scan. x: [T,B,I] time-major.

    mask: [T,B] float (1=valid) or None. With a mask, state updates freeze
    past each sequence's length (the reference's sequence_length semantics).
    """
    def body(carry, inp):
        if mask is None:
            xt = inp
            new = step(xt, carry, weights)
            return new, (new[0] if isinstance(new, tuple) else new)
        xt, mt = inp
        new = step(xt, carry, weights)
        mt = mt[:, None]
        if isinstance(new, tuple):
            merged = tuple(mt * n + (1 - mt) * o for n, o in zip(new, carry))
            return merged, merged[0]
        merged = mt * new + (1 - mt) * carry
        return merged, merged

    xs = x if mask is None else (x, mask)
    final, outs = lax.scan(body, init_states, xs, reverse=reverse)
    return outs, final


class RNN(Layer):
    """Wrap a cell into a full-sequence runner (reference rnn.py::RNN).

    The wrapped run compiles to a single lax.scan primitive.
    """

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if initial_states is None:
            bi = 1 if self.time_major else 0
            initial_states = self.cell.get_initial_states(inputs, batch_dim_idx=bi)
        is_lstm = isinstance(self.cell, LSTMCell)
        cell = self.cell
        weights = [w for w in cell._weights() if w is not None]
        has_b_ih = cell.bias_ih is not None
        has_b_hh = cell.bias_hh is not None
        time_major, reverse = self.time_major, self.is_reverse

        def step_of(ws):
            w_ih, w_hh = ws[0], ws[1]
            b_ih = ws[2] if has_b_ih else None
            b_hh = ws[2 + int(has_b_ih)] if has_b_hh else None

            def step(xt, carry, _):
                if is_lstm:
                    return LSTMCell._step(xt, carry[0], carry[1], w_ih, w_hh, b_ih, b_hh)
                if isinstance(cell, GRUCell):
                    return GRUCell._step(xt, carry, w_ih, w_hh, b_ih, b_hh)
                return SimpleRNNCell._step(cell.activation, xt, carry, w_ih, w_hh, b_ih, b_hh)[0]

            return step

        has_sl = sequence_length is not None

        def fn(x, *rest):
            rest = list(rest)
            # sequence_length rides the primitive's tensor args (not a python
            # closure) so discovery tracing records the read per batch
            sl = jnp.asarray(rest.pop(0)) if has_sl else None
            if is_lstm:
                h0, c0, *ws = rest
                init = (h0, c0)
            else:
                h0, *ws = rest
                init = h0
            seq_mask = None
            if sl is not None:
                T = x.shape[1] if not time_major else x.shape[0]
                seq_mask = (jnp.arange(T)[:, None] < sl[None, :]).astype(x.dtype)
            xt = x if time_major else jnp.swapaxes(x, 0, 1)
            step = step_of(ws)
            outs, final = _scan_layer(step, xt, init, None, reverse=reverse, mask=seq_mask)
            outs = outs if time_major else jnp.swapaxes(outs, 0, 1)
            if is_lstm:
                return outs, final[0], final[1]
            return outs, final

        init_list = list(initial_states) if is_lstm else [initial_states]
        sl_list = [sequence_length] if has_sl else []
        n_out = 3 if is_lstm else 2
        res = primitive("rnn", fn, [inputs] + sl_list + init_list + weights, n_outputs=n_out)
        if is_lstm:
            return res[0], (res[1], res[2])
        return res[0], res[1]


class BiRNN(Layer):
    """Bidirectional wrapper (reference rnn.py::BiRNN)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw, self.cell_bw = cell_fw, cell_bw
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        st_fw, st_bw = (None, None) if initial_states is None else initial_states
        out_fw, fst_fw = self.rnn_fw(inputs, st_fw, sequence_length)
        out_bw, fst_bw = self.rnn_bw(inputs, st_bw, sequence_length)
        from ...ops.manipulation import concat

        return concat([out_fw, out_bw], axis=-1), (fst_fw, fst_bw)


class _RNNBase(Layer):
    """Multi-layer, optionally bidirectional runner shared by SimpleRNN/LSTM/
    GRU (reference rnn.py::RNNBase). Per-(layer,direction) weights live in
    cells; sequence execution is scan-per-layer."""

    def __init__(self, mode, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        if direction in ("forward",):
            self.num_directions = 1
        elif direction in ("bidirect", "bidirectional"):
            self.num_directions = 2
        else:
            raise ValueError(f"unknown direction {direction!r}")
        self.mode, self.input_size, self.hidden_size = mode, input_size, hidden_size
        self.num_layers, self.time_major, self.dropout = num_layers, time_major, dropout
        self.direction = direction

        def make_cell(in_sz):
            kw = dict(weight_ih_attr=weight_ih_attr, weight_hh_attr=weight_hh_attr,
                      bias_ih_attr=bias_ih_attr, bias_hh_attr=bias_hh_attr)
            if mode == "LSTM":
                return LSTMCell(in_sz, hidden_size, **kw)
            if mode == "GRU":
                return GRUCell(in_sz, hidden_size, **kw)
            return SimpleRNNCell(in_sz, hidden_size, activation=activation, **kw)

        from .container import LayerList

        runners = []
        for layer_i in range(num_layers):
            in_sz = input_size if layer_i == 0 else hidden_size * self.num_directions
            if self.num_directions == 2:
                runners.append(BiRNN(make_cell(in_sz), make_cell(in_sz), time_major=time_major))
            else:
                runners.append(RNN(make_cell(in_sz), time_major=time_major))
        self._runners = LayerList(runners)
        self.state_components = 2 if mode == "LSTM" else 1

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops.manipulation import stack

        x = inputs
        finals = []
        for i, runner in enumerate(self._runners):
            st = None
            if initial_states is not None:
                st = self._layer_states(initial_states, i)
            x, final = runner(x, st, sequence_length)
            finals.append(final)
            if self.dropout > 0.0 and i < self.num_layers - 1 and self.training:
                x = F.dropout(x, p=self.dropout, training=True)
        return x, self._pack_states(finals, stack)

    def _layer_states(self, initial_states, i):
        """Slice [num_layers*num_directions, B, H]-shaped states for layer i."""
        nd = self.num_directions
        if self.mode == "LSTM":
            h, c = initial_states
            if nd == 2:
                return ((h[2 * i], c[2 * i]), (h[2 * i + 1], c[2 * i + 1]))
            return (h[i], c[i])
        h = initial_states
        if nd == 2:
            return (h[2 * i], h[2 * i + 1])
        return h[i]

    def _pack_states(self, finals, stack):
        nd = self.num_directions
        if self.mode == "LSTM":
            hs, cs = [], []
            for f in finals:
                if nd == 2:
                    (h_f, c_f), (h_b, c_b) = f
                    hs += [h_f, h_b]
                    cs += [c_f, c_b]
                else:
                    hs.append(f[0])
                    cs.append(f[1])
            return stack(hs, axis=0), stack(cs, axis=0)
        hs = []
        for f in finals:
            if nd == 2:
                hs += [f[0], f[1]]
            else:
                hs.append(f)
        return stack(hs, axis=0)


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", **kw):
        super().__init__("RNN", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, activation, **kw)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kw):
        super().__init__("LSTM", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kw)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kw):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kw)
