"""nn.Layer: the module base class.

Rebuild of the reference's Layer (python/paddle/nn/layer/layers.py:354):
sublayer/parameter trees, forward pre/post hooks, state_dict/set_state_dict,
train/eval mode, buffers, apply, to(). TPU-native additions: parameters are
jax-backed Tensors; ``sharding_spec`` annotations on parameters drive
GSPMD placement in the jit path (paddle_tpu/jit, paddle_tpu/distributed).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Iterator, Optional, Tuple

import numpy as np

from ...base import dtype as dtype_mod
from ...base import global_state
from ...base.enforce import enforce
from ...core.tensor import Parameter, Tensor

_HOOK_ID = [0]


class HookRemoveHelper:
    def __init__(self, hooks, hid):
        self._hooks, self._hid = hooks, hid

    def remove(self):
        self._hooks.pop(self._hid, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtype
        self._parameters = OrderedDict()
        self._sub_layers = OrderedDict()
        self._buffers = OrderedDict()
        self._non_persistable_buffer_names_set = set()
        self._forward_pre_hooks = OrderedDict()
        self._forward_post_hooks = OrderedDict()
        self._casted_by_pure_fp16 = False
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # ------------------------------------------------ attribute plumbing
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            enforce(params is not None, "call super().__init__() before assigning parameters")
            params[name] = value
            layers.pop(name, None) if layers else None
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            enforce(layers is not None, "call super().__init__() before assigning sublayers")
            layers[name] = value
            params.pop(name, None) if params else None
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                if value is None:
                    params[name] = None
                    return
                if isinstance(value, Tensor):
                    # keep the registry authoritative: re-wrap as Parameter so
                    # parameters()/state_dict() keep seeing what forward uses
                    params[name] = Parameter(value._value)
                    return
                raise TypeError(
                    f"cannot assign {type(value).__name__!r} to parameter '{name}' "
                    "(expected Parameter, Tensor, or None)"
                )
            if layers is not None and name in layers:
                if value is None:
                    layers[name] = None
                    return
            if buffers is not None and name in buffers:
                if value is None or isinstance(value, Tensor):
                    buffers[name] = value
                    return
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + list(self._sub_layers) + list(self._buffers)

    # ------------------------------------------------ registration
    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            parameter = Parameter(parameter._value if isinstance(parameter, Tensor) else parameter)
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        enforce(isinstance(sublayer, Layer) or sublayer is None, "sublayer must be a Layer")
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(tensor)
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names_set.add(name)
        return tensor

    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ):
        """Reference Layer.create_parameter: build + initialize a Parameter."""
        from ..initializer import Constant, XavierNormal
        from ..initializer.attr_helpers import resolve_param_attr
        from ..initializer.initializers import global_initializer

        if attr is False:
            return None
        dtype = dtype or self._dtype or global_state.default_dtype
        attr = resolve_param_attr(attr)
        if attr is not None and attr.initializer is not None:
            init = attr.initializer
        elif default_initializer is not None:
            init = default_initializer
        elif global_initializer(is_bias) is not None:
            init = global_initializer(is_bias)
        else:
            init = Constant(0.0) if is_bias else XavierNormal()
        p = Parameter(np.zeros([int(s) for s in shape], dtype_mod.np_dtype(dtype)))
        init(p)
        if attr is not None:
            if attr.name:
                p.name = attr.name
            p.trainable = attr.trainable
            p.stop_gradient = not attr.trainable
            p.optimize_attr["learning_rate"] = attr.learning_rate
            p.regularizer = attr.regularizer
        p.init_fn = init
        return p

    # ------------------------------------------------ traversal
    def named_parameters(self, prefix="", include_sublayers=True) -> Iterator[Tuple[str, Parameter]]:
        memo = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in memo:
                    continue
                memo.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p
            if not include_sublayers:
                break

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None) -> Iterator[Tuple[str, "Layer"]]:
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from sub.named_sublayers(prefix=sub_prefix, include_self=True, layers_set=layers_set)

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return [l for l in self._sub_layers.values() if l is not None]

    def named_children(self):
        return [(n, l) for n, l in self._sub_layers.items() if l is not None]

    def named_buffers(self, prefix="", include_sublayers=True):
        memo = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in memo:
                    continue
                memo.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b
            if not include_sublayers:
                break

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    # ------------------------------------------------ mode
    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    # ------------------------------------------------ hooks
    def register_forward_pre_hook(self, hook):
        _HOOK_ID[0] += 1
        self._forward_pre_hooks[_HOOK_ID[0]] = hook
        return HookRemoveHelper(self._forward_pre_hooks, _HOOK_ID[0])

    def register_forward_post_hook(self, hook):
        _HOOK_ID[0] += 1
        self._forward_post_hooks[_HOOK_ID[0]] = hook
        return HookRemoveHelper(self._forward_post_hooks, _HOOK_ID[0])

    # ------------------------------------------------ call
    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            res = hook(self, inputs)
            if res is not None:
                inputs = res if isinstance(res, tuple) else (res,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    # ------------------------------------------------ state dict
    def state_dict(self, destination=None, include_sublayers=True, structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip(".")):
            dest[name] = p
        for name, layer in self.named_sublayers(prefix=structured_name_prefix.rstrip("."), include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names_set:
                    continue
                dest[f"{name}.{bname}" if name else bname] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, target in own.items():
            if name in state_dict:
                src = state_dict[name]
                arr = src.numpy() if isinstance(src, Tensor) else np.asarray(src)
                enforce(
                    list(arr.shape) == target.shape,
                    f"shape mismatch for '{name}': checkpoint {list(arr.shape)} vs model {target.shape}",
                )
                target.set_value(arr)
            else:
                missing.append(name)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    load_dict = set_state_dict

    # ------------------------------------------------ dtype/device movement
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._convert_dtype(dtype)
        if device is not None:
            import jax

            from ...device import _resolve_device

            dev = _resolve_device(device)
            for t in list(self.state_dict().values()):
                t._replace_value(jax.device_put(t._value, dev))
        return self

    def _convert_dtype(self, dtype):
        npd = dtype_mod.np_dtype(dtype)
        import jax.numpy as jnp

        for t in self.state_dict().values():
            if jnp.issubdtype(t._value.dtype, jnp.inexact):
                t._replace_value(t._value.astype(npd))
        self._dtype = dtype_mod.convert_dtype(dtype).name
        for layer in self.sublayers(include_self=True):
            layer._dtype = self._dtype
        return self

    def astype(self, dtype):
        return self._convert_dtype(dtype)

    def float(self):
        return self._convert_dtype("float32")

    def bfloat16(self):
        return self._convert_dtype("bfloat16")

    def float16(self):
        return self._convert_dtype("float16")

    # ------------------------------------------------ misc
    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def full_name(self):
        return self._name_scope

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            mod_str = repr(sub)
            mod_str = "\n".join("  " + l for l in mod_str.split("\n"))
            lines.append(f"  ({name}): {mod_str.strip()}")
        main = self.__class__.__name__ + "("
        if extra and not lines:
            return main + extra + ")"
        if lines:
            return main + (extra + "\n" if extra else "\n") + "\n".join(lines) + "\n)"
        return main + ")"
