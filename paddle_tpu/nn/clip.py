"""Gradient clipping (reference: python/paddle/nn/clip.py —
ClipGradByGlobalNorm etc., consumed by optimizer.grad_clip)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._value, self.min, self.max), stop_gradient=True)))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g._value)))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor(g._value * scale, stop_gradient=True)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Global-norm clip. In hybrid-parallel training the optimizer wrapper
    extends the squared-norm reduction across mesh axes (reference
    HybridParallelClipGrad, fleet/meta_optimizers/dygraph_optimizer/
    hybrid_parallel_optimizer.py:42)."""

    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def _global_norm_sq(self, params_grads):
        total = None
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                continue
            s = jnp.sum(jnp.square(g._value.astype(jnp.float32)))
            total = s if total is None else total + s
        return total

    def __call__(self, params_grads):
        total = self._global_norm_sq(params_grads)
        if total is None:
            return params_grads
        gnorm = jnp.sqrt(total)
        scale = jnp.minimum(self.clip_norm / jnp.maximum(gnorm, 1e-12), 1.0)
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            out.append((p, Tensor(g._value * scale.astype(g._value.dtype), stop_gradient=True)))
        return out


GradientClipBase = ClipGradBase
GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm
