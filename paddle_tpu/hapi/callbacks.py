"""Training callbacks (reference: python/paddle/hapi/callbacks.py —
Callback base, ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler)."""
from __future__ import annotations

import os
import time

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks, model=None, params=None):
        self.callbacks = list(callbacks)
        for c in self.callbacks:
            c.set_model(model)
            c.set_params(params)

    def __getattr__(self, name):
        if not name.startswith("on_"):
            raise AttributeError(name)

        def dispatch(*args, **kwargs):
            for c in self.callbacks:
                getattr(c, name)(*args, **kwargs)

        return dispatch


def _scalar(v) -> float:
    """Format-time materialization: host scalars (python or numpy) pass
    through; device values (Tensor / jax array) take one counted host sync
    — the fit loop hands callbacks floats at its sync boundaries, so
    steady-state logging never pays this."""
    if isinstance(v, (float, int, np.floating, np.integer)):
        return float(v)
    from .metric_buffer import to_float

    return to_float(v)


class ProgBarLogger(Callback):
    """Per-epoch throughput/metric logging (reference ProgBarLogger; prints a
    summary line per log_freq steps instead of a terminal progress bar)."""

    def __init__(self, log_freq=10, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._start = time.time()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            logs = logs or {}
            msgs = [f"{k}: {_scalar(v):.4f}" for k, v in logs.items()]
            ips = (step + 1) / max(time.time() - self._start, 1e-9)
            print(f"Epoch {self.epoch}: step {step}/{self.steps} "
                  f"[{ips:.1f} step/s] " + " ".join(msgs))

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            logs = logs or {}
            msgs = [f"{k}: {_scalar(v):.4f}" for k, v in logs.items()]
            print(f"Epoch {epoch} done in {time.time() - self._start:.1f}s " + " ".join(msgs))


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "max" or (mode == "auto" and ("acc" in monitor or monitor.startswith("fmeasure"))):
            self.monitor_op = np.greater
            self.min_delta *= 1
        else:
            self.monitor_op = np.less
            self.min_delta *= -1
        self.stopped_epoch = 0

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.best = self.baseline if self.baseline is not None else (
            np.inf if self.monitor_op == np.less else -np.inf)

    def on_eval_end(self, logs=None):
        logs = logs or {}
        value = logs.get(self.monitor)
        if value is None:
            return
        value = np.asarray(value).reshape(-1)[0]
        if self.monitor_op(value - self.min_delta, self.best):
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"EarlyStopping: best {self.monitor}={self.best:.5f}")


class LRScheduler(Callback):
    """Step the optimizer's LR schedule (reference LRScheduler callback)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


def config_callbacks(callbacks, model, epochs=None, steps=None, verbose=2,
                     save_freq=1, save_dir=None, metrics=None):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(verbose=verbose))
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks.append(LRScheduler())
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    params = {"epochs": epochs, "steps": steps, "verbose": verbose, "metrics": metrics or []}
    return CallbackList(cbks, model=model, params=params)
