"""MetricBuffer: keep per-step training metrics on the device.

The classic hapi loop forced ``float(loss.numpy())`` every step — a
device→host readback that stalls the async dispatch queue exactly once per
step, which on TPU serializes H2D, program dispatch and D2H
(ISSUE 5 motivation). The buffer is the non-blocking replacement: the loop
appends raw device scalars (zero host syncs), and floats materialize only
at **sync boundaries** — every ``sync_every`` steps (log frequency) and at
the epoch flush. Materialization batches all pending scalars into one
device concatenation + a single host transfer, and converts element-wise to
python floats, so the flushed values are **bit-identical** to what the
per-step ``float(...)`` loop would have produced.

Every materialization is timed and counted in
``profiler.pipeline_stats`` (``host_sync_us`` / ``host_syncs_per_step``) —
the bench's ``extras.pipeline`` proves the steady state issues zero.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional


def to_float(value) -> float:
    """One blocking device→host scalar read, counted as a host sync.
    The sanctioned sync point for code that *must* return a python float
    (``Model.train_batch(sync=True)``, epoch summaries)."""
    import numpy as np

    from ..profiler.pipeline import pipeline_stats

    t0 = time.perf_counter()
    v = getattr(value, "_value", value)
    out = float(np.asarray(v).reshape(-1)[0])
    dt = time.perf_counter() - t0
    pipeline_stats.add_host_sync(dt)
    from ..observability.tracing import tracer

    if tracer.enabled:
        tracer.emit("host_sync", t0, dt, track="train_loop")
    return out


class MetricBuffer:
    """Per-name ring of device scalars with boundary-only materialization.

    ``sync_every=k`` → :meth:`should_sync` is True every k-th step (the
    loop materializes there, typically to feed a progress logger);
    ``sync_every=0``/``None`` → only explicit :meth:`flush` calls sync.
    """

    def __init__(self, sync_every: Optional[int] = None):
        self.sync_every = int(sync_every or 0)
        self._pending: Dict[str, List] = {}
        self._history: Dict[str, List[float]] = {}

    # ------------------------------------------------------------- appending
    def append(self, name: str, value) -> None:
        """Record one step's metric. ``value`` may be a Tensor or a raw
        device array; it is stored as-is — no host transfer happens."""
        self._pending.setdefault(name, []).append(
            getattr(value, "_value", value))

    def latest(self, name: str):
        """The most recent recorded value, still device-resident (pending)
        or the last materialized float."""
        pend = self._pending.get(name)
        if pend:
            return pend[-1]
        hist = self._history.get(name)
        return hist[-1] if hist else None

    def last_float(self, name: str):
        """The most recent MATERIALIZED value (a python float), or None
        when nothing has synced yet — never touches the device."""
        hist = self._history.get(name)
        return hist[-1] if hist else None

    def should_sync(self, step: int) -> bool:
        """True on sync boundaries: step is 0-based and with
        ``sync_every=k`` steps 0, k, 2k, ... materialize — the same
        cadence ``ProgBarLogger`` prints on (``step % log_freq == 0``),
        so the logger always receives already-materialized floats."""
        return self.sync_every > 0 and step % self.sync_every == 0

    # --------------------------------------------------------- materializing
    def materialize(self) -> Dict[str, float]:
        """Move every pending scalar to the host (one stacked transfer per
        metric), append to the history, and return the latest float per
        metric. The conversion path (f32 device scalar → python float) is
        bit-identical to a per-step ``float(np.asarray(v))``."""
        import numpy as np

        from ..profiler.pipeline import pipeline_stats

        if not self._pending:
            return {k: v[-1] for k, v in self._history.items() if v}
        import jax.numpy as jnp

        t0 = time.perf_counter()
        out = {}
        n_values = 0
        for name, vals in self._pending.items():
            stacked = np.asarray(jnp.stack([jnp.reshape(v, ()) for v in vals]))
            floats = [float(x) for x in stacked]
            n_values += len(floats)
            self._history.setdefault(name, []).extend(floats)
            out[name] = floats[-1]
        self._pending.clear()
        dt = time.perf_counter() - t0
        pipeline_stats.add_host_sync(dt)
        from ..observability.tracing import tracer

        if tracer.enabled:
            tracer.emit("metric.flush", t0, dt, track="train_loop",
                        metrics=len(out), values=n_values)
        return out

    def flush(self) -> Dict[str, dict]:
        """Epoch boundary: materialize everything and return per-metric
        ``{"last", "mean", "values"}``, then reset the history. ``mean``
        uses the same float64 accumulation over python floats as the old
        per-step loop's ``np.mean(list_of_floats)``."""
        import numpy as np

        self.materialize()
        report = {}
        for name, vals in self._history.items():
            if not vals:
                continue
            report[name] = {"last": vals[-1],
                            "mean": float(np.mean(vals)),
                            "values": list(vals)}
        self._history.clear()
        return report
