from .callbacks import (  # noqa: F401
    Callback,
    EarlyStopping,
    LRScheduler,
    ModelCheckpoint,
    ProgBarLogger,
)
from .metric_buffer import MetricBuffer  # noqa: F401
from .model import Model  # noqa: F401
