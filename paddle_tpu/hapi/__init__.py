from .callbacks import (  # noqa: F401
    Callback,
    EarlyStopping,
    LRScheduler,
    ModelCheckpoint,
    ProgBarLogger,
)
from .model import Model  # noqa: F401
