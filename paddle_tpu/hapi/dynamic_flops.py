"""paddle.flops — per-layer FLOPs/params accounting (reference:
python/paddle/hapi/dynamic_flops.py): forward-post hooks record each leaf
layer's multiply-accumulate count from its real input/output shapes, summed
over one dry forward. On TPU the number doubles as the MFU denominator —
bench.py's analytic formulas are the model-specific fast path; this is the
generic layer-walk.

The per-op formulas themselves live in ``analysis/cost_model.py``
(``linear_flops``/``conv_flops``/...): the static jaxpr walker and this
layer-hook front end share one accounting, so the two tiers cannot
drift. The hook API (``custom_ops`` mapping layer classes to
``fn(layer, x, y) -> flops``) is unchanged."""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..analysis import cost_model as _cm


def _numel(shape):
    n = 1
    for s in shape:
        n *= int(s)
    return n


def _count_conv(layer, x, y):
    return _cm.conv_flops(
        _numel(y.shape),
        int(layer.weight.shape[1]),      # per-group in-channels
        _numel(layer.weight.shape[2:]),  # kernel taps
        getattr(layer, "bias", None) is not None)


def _count_linear(layer, x, y):
    return _cm.linear_flops(_numel(y.shape), int(layer.weight.shape[0]),
                            getattr(layer, "bias", None) is not None)


def _count_norm(layer, x, y):
    return _cm.norm_flops(_numel(x.shape))


def _count_act(layer, x, y):
    return _cm.activation_flops(_numel(y.shape))


def _count_pool(layer, x, y):
    ks = getattr(layer, "kernel_size", 2)
    k = _numel(ks) if isinstance(ks, (list, tuple)) else int(ks) ** 2
    return _cm.pool_flops(_numel(y.shape), k)


_COUNTERS = {
    "Conv1D": _count_conv, "Conv2D": _count_conv, "Conv3D": _count_conv,
    "Conv1DTranspose": _count_conv, "Conv2DTranspose": _count_conv,
    "Conv3DTranspose": _count_conv,
    "Linear": _count_linear,
    "BatchNorm": _count_norm, "BatchNorm1D": _count_norm,
    "BatchNorm2D": _count_norm, "BatchNorm3D": _count_norm,
    "LayerNorm": _count_norm, "GroupNorm": _count_norm,
    "InstanceNorm2D": _count_norm,
    "ReLU": _count_act, "ReLU6": _count_act, "GELU": _count_act,
    "Sigmoid": _count_act, "Tanh": _count_act, "Silu": _count_act,
    "Softmax": _count_act, "LeakyReLU": _count_act,
    "MaxPool1D": _count_pool, "MaxPool2D": _count_pool,
    "MaxPool3D": _count_pool, "AvgPool1D": _count_pool,
    "AvgPool2D": _count_pool, "AvgPool3D": _count_pool,
}


def flops(net, input_size, custom_ops: Optional[dict] = None,
          print_detail: bool = False) -> int:
    """Total forward FLOPs of ``net`` on an ``input_size`` batch (reference
    paddle.flops). ``custom_ops`` maps layer CLASSES to
    ``fn(layer, x, y) -> flops`` counters, like the reference's contract."""
    import paddle_tpu as P

    custom = {cls.__name__: fn for cls, fn in (custom_ops or {}).items()}
    rows = []
    removes = []

    def attach(layer):
        name = type(layer).__name__
        counter = custom.get(name) or _COUNTERS.get(name)
        if counter is None or list(layer.children()):
            return

        def hook(lay, inputs, output, _counter=counter):
            x = inputs[0] if isinstance(inputs, (list, tuple)) else inputs
            y = output[0] if isinstance(output, (list, tuple)) else output
            n_params = int(sum(_numel(p.shape) for p in lay.parameters(
                include_sublayers=False)))
            rows.append((type(lay).__name__, list(np.shape(y)),
                         n_params, int(_counter(lay, x, y))))

        removes.append(layer.register_forward_post_hook(hook))

    for sub in net.sublayers(include_self=True):
        attach(sub)
    was_training = net.training
    net.eval()
    try:
        net(P.to_tensor(np.zeros(input_size, np.float32)))
    finally:
        if was_training:
            net.train()
        for r in removes:
            r.remove()
    total = sum(r[3] for r in rows)
    if print_detail:
        from ..base.log import get_logger

        log = get_logger()
        log.info("%-18s %-20s %12s %14s", "Layer", "Output shape",
                 "Params", "FLOPs")
        for name, shape, n_params, f in rows:
            log.info("%-18s %-20s %12d %14d", name, shape, n_params, f)
        log.info("Total FLOPs: %d  (~%.3f GFLOPs)", total, total / 1e9)
    return total
