"""paddle.summary — layer-by-layer model summary (reference:
python/paddle/hapi/model_summary.py): one dry forward with forward-post
hooks records each leaf layer's output shape and parameter count; returns
{'total_params', 'trainable_params'} like the reference and logs the
table."""
from __future__ import annotations

from typing import Optional

import numpy as np


def _numel(shape):
    n = 1
    for s in shape:
        n *= int(s)
    return n


def summary(net, input_size=None, dtypes=None, input=None) -> dict:
    """reference paddle.summary(net, input_size): dry-run shape/param table.

    input_size: tuple/list batch shape (or list of them for multi-input);
    input: a ready-made tensor (wins over input_size).
    """
    import paddle_tpu as P

    rows = []
    removes = []

    def attach(layer):
        if list(layer.children()):
            return

        def hook(lay, inputs, output):
            y = output[0] if isinstance(output, (list, tuple)) else output
            own = lay.parameters(include_sublayers=False)
            n_params = int(sum(_numel(p.shape) for p in own))
            n_train = int(sum(_numel(p.shape) for p in own
                              if not p.stop_gradient))
            rows.append((type(lay).__name__, list(np.shape(y)),
                         n_params, n_train))

        removes.append(layer.register_forward_post_hook(hook))

    for sub in net.sublayers(include_self=True):
        attach(sub)

    if input is None:
        if input_size is None:
            raise ValueError("summary needs input_size or input")
        sizes = (list(input_size) if isinstance(input_size[0], (list, tuple))
                 else [list(input_size)])
        np_dtypes = list(dtypes or ["float32"] * len(sizes))
        args = [P.to_tensor(np.zeros(s, np.dtype(d)))
                for s, d in zip(sizes, np_dtypes)]
    else:
        args = [input]

    was_training = net.training
    net.eval()
    try:
        net(*args)
    finally:
        if was_training:
            net.train()
        for r in removes:
            r.remove()

    total = int(sum(_numel(p.shape) for p in net.parameters()))
    trainable = int(sum(_numel(p.shape) for p in net.parameters()
                        if not p.stop_gradient))
    from ..base.log import get_logger

    log = get_logger()
    log.info("%-22s %-22s %12s", "Layer (type)", "Output Shape", "Param #")
    for name, shape, n_params, _ in rows:
        log.info("%-22s %-22s %12d", name, shape, n_params)
    log.info("Total params: %d  Trainable params: %d  Non-trainable: %d",
             total, trainable, total - trainable)
    return {"total_params": total, "trainable_params": trainable}
