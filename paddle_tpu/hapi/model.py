"""High-level Model API (reference: python/paddle/hapi/model.py —
Model.prepare/fit/evaluate/predict/save/load + summary).

TPU-native: train/eval batches run through a jit-compiled step (the
paddle_tpu.jit functionalizer), so `Model.fit` trains at whole-program XLA
speed out of the box — the reference's dygraph loop pays per-op dispatch
instead. The fit loop is async end-to-end (ISSUE 5): losses stay on the
device in a ``MetricBuffer`` and materialize only at log/epoch boundaries,
and ``device_prefetch=N`` stages upcoming batches onto the device while the
current step computes — the steady-state step issues zero blocking host
syncs.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..core.tensor import Tensor
from ..io import DataLoader, Dataset, DeviceLoader
from ..metric.metrics import Metric
from .callbacks import config_callbacks
from .metric_buffer import MetricBuffer, to_float


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self.stop_training = False
        self._preempted = False  # SIGTERM seen mid-fit (snapshot + stop)
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._train_step = None

    # ------------------------------------------------------------ prepare
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        metrics = _to_list(metrics)
        for m in metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metric must be paddle.metric.Metric, got {type(m)}")
        self._metrics = metrics
        self._train_step = None

    # ------------------------------------------------------------ stepping
    def _build_train_step(self):
        from ..jit.api import TrainStep

        model = self.network
        loss_fn = self._loss

        def fn(*batch):
            *xs, y = batch
            return loss_fn(model(*xs), y)

        self._train_step = TrainStep(model=model, optimizer=self._optimizer, loss_fn=fn)

    def train_batch(self, inputs, labels=None, update=True, sync=True):
        """One optimizer step; returns the loss (reference train_batch).

        ``sync=True`` (the reference contract) materializes a python float
        — one blocking device→host read. ``sync=False`` returns the loss
        as a device-resident Tensor so async loops (``fit``) can defer the
        readback to a ``MetricBuffer`` boundary."""
        if self._optimizer is None or self._loss is None:
            raise RuntimeError("call prepare(optimizer, loss) before training")
        self.network.train()
        if self._train_step is None:
            self._build_train_step()
        batch = _to_list(inputs) + _to_list(labels)
        loss = self._train_step(*batch)
        if sync:
            return [to_float(loss)]
        return [loss]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        outputs = self.network(*_to_list(inputs))
        losses = []
        if self._loss is not None and labels is not None:
            loss = self._loss(outputs, *_to_list(labels))
            losses = [to_float(loss)]
        metric_outs = []
        for m in self._metrics:
            computed = m.compute(outputs, *_to_list(labels))
            metric_outs.append(m.update(*_to_list(computed)))
        return losses, metric_outs

    def predict_batch(self, inputs):
        self.network.eval()
        out = self.network(*_to_list(inputs))
        return [o.numpy() if isinstance(o, Tensor) else o for o in _to_list(out)]

    # ------------------------------------------------------------ loops
    def _make_loader(self, data, batch_size, shuffle, num_workers=0,
                     device_prefetch=None):
        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers,
                              device_prefetch=device_prefetch)
        return data  # any iterable of batches

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            device_prefetch=None, sync_every=None, snapshot_dir=None,
            snapshot_every=None, snapshot_keep=None, resume=None):
        """Train over ``train_data``. The loop is non-blocking by design:
        per-step losses stay device-resident in a :class:`MetricBuffer`
        and materialize only every ``sync_every`` steps (defaults to
        ``log_freq``, or ``FLAGS_metric_sync_every`` when set) and at
        epoch boundaries; ``device_prefetch=N`` double-buffers H2D batch
        staging (``FLAGS_device_prefetch`` sets the default). Callbacks
        keep the float-valued ``logs`` contract: between boundaries they
        receive the LAST materialized loss (fresh every ``sync_every``-th
        step) rather than a device handle — only an explicit
        ``sync_every=0`` passes device values through.

        Preemption safety (ISSUE 14): ``snapshot_dir`` arms atomic
        rolling train-state snapshots (params, optimizer — zero1 shard
        pieces included — RNG key, and the epoch/batch loader cursor)
        every ``snapshot_every`` steps (``FLAGS_train_snapshot_every``)
        and on SIGTERM (the preemption signal snapshots at the next step
        boundary, then stops cleanly). ``resume=True`` (or a directory)
        restores the newest snapshot and continues mid-epoch at the
        EXACT next batch — with a deterministic loader the resumed loss
        stream is bit-identical to the uninterrupted run's, and a zero1
        job may resume onto a changed dp degree (shard re-slice).
        Elastic wiring (ISSUE 15): when ``snapshot_dir`` is armed and
        ``resume`` is left unset, a relaunched worker (the launcher
        exports ``PADDLE_RESTART_GEN > 0`` on every restart) resumes
        automatically; pass ``resume=False`` to force a fresh start."""
        from ..base.flags import get_flag
        from ..observability.anomaly import monitor

        loader = self._make_loader(train_data, batch_size, shuffle,
                                   num_workers, device_prefetch)
        if device_prefetch and loader is not None and loader is train_data:
            # caller-supplied loader/iterable (a Dataset got a fresh loader
            # above with device_prefetch wired in): wrap — never mutate the
            # caller's object — unless it already prefetches on its own
            already = (isinstance(loader, DeviceLoader)
                       or bool(getattr(loader, "device_prefetch", 0)))
            if not already:
                loader = DeviceLoader(loader, depth=int(device_prefetch))
        if sync_every is None:
            sync_every = int(get_flag("metric_sync_every")) or log_freq
        snapshotter, cursor = self._arm_snapshots(snapshot_dir, snapshot_keep,
                                                  resume)
        if snapshot_every is None:
            snapshot_every = int(get_flag("train_snapshot_every"))
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        cbks = config_callbacks(callbacks, self, epochs=epochs, steps=steps,
                                verbose=verbose, save_freq=save_freq,
                                save_dir=save_dir, metrics=self._metrics)
        self.stop_training = False
        self._preempted = False
        cbks.on_train_begin()
        logs = {}
        buf = MetricBuffer(sync_every=sync_every)
        restore_sig = self._install_sigterm(snapshotter)
        try:
            logs = self._fit_loop(loader, epochs, eval_data, eval_freq,
                                  batch_size, num_workers, cbks, buf,
                                  cursor=cursor, snapshotter=snapshotter,
                                  snapshot_every=int(snapshot_every))
        except BaseException as e:
            if monitor.enabled:
                # uncaught train-loop exception: capture the forensic
                # window (spans + metrics + step-time history) before the
                # stack unwinds and the evidence is gone
                monitor.on_exception("train.fit", e)
            raise
        finally:
            restore_sig()
        cbks.on_train_end(logs)

    # -------------------------------------------------- preemption safety
    def _arm_snapshots(self, snapshot_dir, snapshot_keep, resume):
        """Resolve the snapshotter + the resume cursor. ``resume`` may be
        True (use ``snapshot_dir``) or a directory; a resume target with
        no complete snapshot starts fresh (first boot of an elastic job)
        with a log line rather than failing the launch."""
        if resume is None and snapshot_dir:
            # elastic relaunch wiring (ISSUE 15 satellite, ROADMAP
            # leftover from PR 14): a worker the launcher RESTARTED
            # (PADDLE_RESTART_GEN > 0 — set by distributed.launch on
            # every relaunch/elastic re-form) resumes from its snapshot
            # cursor automatically instead of silently replaying the
            # epoch from step 0. First boots (gen 0) start fresh.
            import os

            try:
                gen = int(os.environ.get("PADDLE_RESTART_GEN", "0") or 0)
            except ValueError:
                gen = 0
            if gen > 0:
                from ..base.log import get_logger

                get_logger().info(
                    "fit: elastic relaunch detected (PADDLE_RESTART_GEN="
                    "%d) — resuming from the snapshot cursor under %s",
                    gen, snapshot_dir)
                resume = True
        if resume and not isinstance(resume, (str, bytes)) and not snapshot_dir:
            raise ValueError("fit(resume=True) needs snapshot_dir=")
        resume_dir = (resume if isinstance(resume, (str, bytes)) else None)
        target = snapshot_dir or resume_dir
        if target is None:
            return None, None
        from ..reliability.snapshot import TrainSnapshotter

        snapshotter = TrainSnapshotter(str(resume_dir or target),
                                       keep=snapshot_keep)
        cursor = None
        if resume:
            from ..base.log import get_logger

            if snapshotter.latest() is None:
                get_logger().info(
                    "fit(resume=...): no complete snapshot under %s — "
                    "starting fresh", snapshotter.dir)
            else:
                cursor = snapshotter.restore(self.network, self._optimizer)
                get_logger().info(
                    "fit(resume=...): restored step %d (epoch %d, next "
                    "batch %d) from %s", cursor["step"], cursor["epoch"],
                    cursor["next_batch"], snapshotter.dir)
        if snapshot_dir and resume_dir and str(snapshot_dir) != str(resume_dir):
            # resume from one dir, keep snapshotting into another
            snapshotter = TrainSnapshotter(str(snapshot_dir),
                                           keep=snapshot_keep)
        return snapshotter, cursor

    def _install_sigterm(self, snapshotter):
        """SIGTERM → snapshot-at-next-step-boundary + clean stop. Only on
        the main thread (the interpreter's signal contract); returns the
        zero-arg restore closure."""
        if snapshotter is None:
            return lambda: None
        import signal
        import threading

        if threading.current_thread() is not threading.main_thread():
            return lambda: None

        def _on_sigterm(signum, frame):
            # flag only: the snapshot (device sync + disk IO) runs at the
            # step boundary, never inside the signal frame
            self._preempted = True

        try:
            prev = signal.signal(signal.SIGTERM, _on_sigterm)
        except (ValueError, OSError):
            return lambda: None
        return lambda: signal.signal(signal.SIGTERM, prev)

    def _fit_loop(self, loader, epochs, eval_data, eval_freq, batch_size,
                  num_workers, cbks, buf, cursor=None, snapshotter=None,
                  snapshot_every=0):
        from ..observability.anomaly import monitor
        from ..observability.memory import sampler as mem_sampler
        from ..profiler.pipeline import pipeline_stats, timed

        logs = {}
        start_epoch = int(cursor["epoch"]) if cursor else 0
        resume_batch = int(cursor["next_batch"]) if cursor else 0
        global_step = int(cursor["step"]) if cursor else 0
        # epoch-pinned shuffle ONLY when the preemption-safe contract is
        # armed (snapshots or resume): the original and resumed processes
        # must draw the SAME index order for the cursor to land on the
        # exact next batch. Plain fits keep their fresh-entropy shuffle —
        # pinning every run to default_rng(epoch) would silently collapse
        # seed-ensemble training into one run
        pin_epochs = snapshotter is not None or cursor is not None
        for epoch in range(start_epoch, epochs):
            if pin_epochs and hasattr(loader, "set_epoch"):
                loader.set_epoch(epoch)
            cbks.on_epoch_begin(epoch)
            skip = resume_batch if epoch == start_epoch else 0
            for step, batch in enumerate(self._epoch_iter(loader, skip),
                                         start=skip):
                xs, ys = self._split_batch(batch)
                cbks.on_train_batch_begin(step)
                with timed(pipeline_stats.add_dispatch):
                    losses = self.train_batch(xs, ys, sync=False)
                buf.append("loss", losses[0])
                pipeline_stats.step()
                global_step += 1
                # boundary-only device-memory telemetry (sync-free: reads
                # live-array metadata + allocator counters, never a D2H)
                mem_sampler.maybe_sample("step")
                if buf.should_sync(step):
                    # log boundary (aligned with ProgBarLogger's cadence):
                    # one batched readback covering every step since the
                    # previous boundary
                    logs = dict(buf.materialize())
                    if monitor.enabled:
                        # metric-flush boundary: the flight recorder's
                        # memory-watermark detector reads the boundary
                        # sampler's last (sync-free) measurement here
                        monitor.on_flush()
                else:
                    # keep the logs contract float-valued without syncing:
                    # callbacks see the last boundary's float (step 0 is
                    # always a boundary when sync_every >= 1); only an
                    # explicit sync_every=0 hands them the device value
                    val = buf.last_float("loss")
                    logs = {"loss": val if val is not None
                            else buf.latest("loss")}
                cbks.on_train_batch_end(step, logs)
                # ONE preemption point per step, after the callbacks: a
                # SIGTERM landing anywhere inside this step (train_batch,
                # flush, callbacks) is handled HERE with a snapshot at
                # the exact boundary — never a silent epoch break that
                # would skip the tail batches
                if snapshotter is not None and (
                        self._preempted
                        or (snapshot_every > 0
                            and global_step % snapshot_every == 0)):
                    snapshotter.save(self.network, self._optimizer,
                                     step=global_step, epoch=epoch,
                                     next_batch=step + 1)
                if self._preempted:
                    from ..base.log import get_logger

                    get_logger().warning(
                        "fit: SIGTERM received — snapshot landed at step "
                        "%d; stopping cleanly (resume with fit(resume=...))",
                        global_step)
                    self.stop_training = True
                    # mid-epoch break is preemption-only: callback-driven
                    # stop_training keeps its finish-the-epoch contract
                    break
            report = buf.flush()
            if monitor.enabled:
                monitor.on_flush()
            if "loss" in report:
                logs = {"loss": report["loss"]["last"]}
            cbks.on_epoch_end(epoch, logs)
            if eval_data is not None and not self._preempted and (
                    epoch % eval_freq == 0 or epoch == epochs - 1):
                eval_logs = self.evaluate(eval_data, batch_size=batch_size,
                                          verbose=0, num_workers=num_workers)
                cbks.on_eval_end(eval_logs)
            if self.stop_training:
                break
        return logs

    @staticmethod
    def _epoch_iter(loader, skip):
        """One epoch's iterator, fast-forwarded ``skip`` batches: loaders
        with a cursor (``DataLoader.iter_from`` — index-level, zero
        replayed fetches) skip natively, anything else consumes."""
        if not skip:
            return iter(loader)
        if hasattr(loader, "iter_from"):
            return loader.iter_from(skip)
        it = iter(loader)
        for _ in range(int(skip)):
            next(it)
        return it

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        loader = self._make_loader(eval_data, batch_size, False)
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            xs, ys = self._split_batch(batch)
            batch_losses, _ = self.eval_batch(xs, ys)
            losses.extend(batch_losses)
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            res = m.accumulate()
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = res if isinstance(res, list) else [res]
            logs.update(dict(zip(names, vals)))
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = self._make_loader(test_data, batch_size, False)
        outputs = []
        for batch in loader:
            # datasets that yield (x, y) keep working for predict: the label
            # column is dropped, matching fit's input/label split
            xs, _ = self._split_batch(batch)
            outputs.append(self.predict_batch(xs))
        # transpose [steps][n_outs] -> [n_outs][steps]
        outs = list(map(list, zip(*outputs))) if outputs else []
        if stack_outputs:
            outs = [np.concatenate(o) for o in outs]
        return outs

    @staticmethod
    def _split_batch(batch, has_label=True):
        if isinstance(batch, (tuple, list)):
            if has_label and len(batch) >= 2:
                return list(batch[:-1]), [batch[-1]]
            return list(batch), []
        return [batch], []

    # ------------------------------------------------------------ persistence
    def save(self, path, training=True):
        from ..framework.io import save as fw_save

        fw_save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fw_save(self._optimizer.state_dict(), path + ".pdopt")

    def save_sharded(self, directory, overwrite=False):
        """Emit a SERVABLE sharded checkpoint of the network (ISSUE 15):
        one piece file per (tensor, shard) written straight from each
        device's shard — no host-side full-tensor gather — plus the
        manifest, under the atomic tmp+rename publish. The directory
        rolls directly into a live engine
        (``ServingEngine.swap_weights(directory)`` /
        ``Predictor.swap_weights``) because the piece names are the
        network's state_dict keys — the same keys ``jit.save`` exports.
        Returns the save report (``max_piece_bytes`` is the O(shard)
        residency accounting)."""
        from ..distributed.checkpoint.sharded import save_sharded

        return save_sharded(self.network.state_dict(), directory,
                            overwrite=overwrite)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        import os

        from ..framework.io import load as fw_load

        params = fw_load(path + ".pdparams") if not path.endswith(".pdparams") else fw_load(path)
        self.network.set_state_dict(params)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and os.path.exists(opt_path):
            self._optimizer.set_state_dict(fw_load(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        """Parameter-count summary (reference hapi/model_summary.py)."""
        rows, total = [], 0
        for name, p in self.network.named_parameters():
            n = int(np.prod(p.shape)) if p.shape else 1
            total += n
            rows.append((name, tuple(p.shape), n))
        width = max((len(r[0]) for r in rows), default=10) + 2
        lines = [f"{'Layer (param)':<{width}}{'Shape':<20}{'Param #':>12}"]
        lines += [f"{n:<{width}}{str(s):<20}{c:>12,}" for n, s, c in rows]
        lines.append(f"Total params: {total:,}")
        print("\n".join(lines))
        return {"total_params": total, "trainable_params": total}
