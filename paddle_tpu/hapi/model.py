"""High-level Model API (reference: python/paddle/hapi/model.py —
Model.prepare/fit/evaluate/predict/save/load + summary).

TPU-native: train/eval batches run through a jit-compiled step (the
paddle_tpu.jit functionalizer), so `Model.fit` trains at whole-program XLA
speed out of the box — the reference's dygraph loop pays per-op dispatch
instead. The fit loop is async end-to-end (ISSUE 5): losses stay on the
device in a ``MetricBuffer`` and materialize only at log/epoch boundaries,
and ``device_prefetch=N`` stages upcoming batches onto the device while the
current step computes — the steady-state step issues zero blocking host
syncs.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..core.tensor import Tensor
from ..io import DataLoader, Dataset, DeviceLoader
from ..metric.metrics import Metric
from .callbacks import config_callbacks
from .metric_buffer import MetricBuffer, to_float


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self.stop_training = False
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._train_step = None

    # ------------------------------------------------------------ prepare
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        metrics = _to_list(metrics)
        for m in metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metric must be paddle.metric.Metric, got {type(m)}")
        self._metrics = metrics
        self._train_step = None

    # ------------------------------------------------------------ stepping
    def _build_train_step(self):
        from ..jit.api import TrainStep

        model = self.network
        loss_fn = self._loss

        def fn(*batch):
            *xs, y = batch
            return loss_fn(model(*xs), y)

        self._train_step = TrainStep(model=model, optimizer=self._optimizer, loss_fn=fn)

    def train_batch(self, inputs, labels=None, update=True, sync=True):
        """One optimizer step; returns the loss (reference train_batch).

        ``sync=True`` (the reference contract) materializes a python float
        — one blocking device→host read. ``sync=False`` returns the loss
        as a device-resident Tensor so async loops (``fit``) can defer the
        readback to a ``MetricBuffer`` boundary."""
        if self._optimizer is None or self._loss is None:
            raise RuntimeError("call prepare(optimizer, loss) before training")
        self.network.train()
        if self._train_step is None:
            self._build_train_step()
        batch = _to_list(inputs) + _to_list(labels)
        loss = self._train_step(*batch)
        if sync:
            return [to_float(loss)]
        return [loss]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        outputs = self.network(*_to_list(inputs))
        losses = []
        if self._loss is not None and labels is not None:
            loss = self._loss(outputs, *_to_list(labels))
            losses = [to_float(loss)]
        metric_outs = []
        for m in self._metrics:
            computed = m.compute(outputs, *_to_list(labels))
            metric_outs.append(m.update(*_to_list(computed)))
        return losses, metric_outs

    def predict_batch(self, inputs):
        self.network.eval()
        out = self.network(*_to_list(inputs))
        return [o.numpy() if isinstance(o, Tensor) else o for o in _to_list(out)]

    # ------------------------------------------------------------ loops
    def _make_loader(self, data, batch_size, shuffle, num_workers=0,
                     device_prefetch=None):
        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers,
                              device_prefetch=device_prefetch)
        return data  # any iterable of batches

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            device_prefetch=None, sync_every=None):
        """Train over ``train_data``. The loop is non-blocking by design:
        per-step losses stay device-resident in a :class:`MetricBuffer`
        and materialize only every ``sync_every`` steps (defaults to
        ``log_freq``, or ``FLAGS_metric_sync_every`` when set) and at
        epoch boundaries; ``device_prefetch=N`` double-buffers H2D batch
        staging (``FLAGS_device_prefetch`` sets the default). Callbacks
        keep the float-valued ``logs`` contract: between boundaries they
        receive the LAST materialized loss (fresh every ``sync_every``-th
        step) rather than a device handle — only an explicit
        ``sync_every=0`` passes device values through."""
        from ..base.flags import get_flag
        from ..observability.anomaly import monitor

        loader = self._make_loader(train_data, batch_size, shuffle,
                                   num_workers, device_prefetch)
        if device_prefetch and loader is not None and loader is train_data:
            # caller-supplied loader/iterable (a Dataset got a fresh loader
            # above with device_prefetch wired in): wrap — never mutate the
            # caller's object — unless it already prefetches on its own
            already = (isinstance(loader, DeviceLoader)
                       or bool(getattr(loader, "device_prefetch", 0)))
            if not already:
                loader = DeviceLoader(loader, depth=int(device_prefetch))
        if sync_every is None:
            sync_every = int(get_flag("metric_sync_every")) or log_freq
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        cbks = config_callbacks(callbacks, self, epochs=epochs, steps=steps,
                                verbose=verbose, save_freq=save_freq,
                                save_dir=save_dir, metrics=self._metrics)
        self.stop_training = False
        cbks.on_train_begin()
        logs = {}
        buf = MetricBuffer(sync_every=sync_every)
        try:
            logs = self._fit_loop(loader, epochs, eval_data, eval_freq,
                                  batch_size, num_workers, cbks, buf)
        except BaseException as e:
            if monitor.enabled:
                # uncaught train-loop exception: capture the forensic
                # window (spans + metrics + step-time history) before the
                # stack unwinds and the evidence is gone
                monitor.on_exception("train.fit", e)
            raise
        cbks.on_train_end(logs)

    def _fit_loop(self, loader, epochs, eval_data, eval_freq, batch_size,
                  num_workers, cbks, buf):
        from ..observability.anomaly import monitor
        from ..observability.memory import sampler as mem_sampler
        from ..profiler.pipeline import pipeline_stats, timed

        logs = {}
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for step, batch in enumerate(loader):
                xs, ys = self._split_batch(batch)
                cbks.on_train_batch_begin(step)
                with timed(pipeline_stats.add_dispatch):
                    losses = self.train_batch(xs, ys, sync=False)
                buf.append("loss", losses[0])
                pipeline_stats.step()
                # boundary-only device-memory telemetry (sync-free: reads
                # live-array metadata + allocator counters, never a D2H)
                mem_sampler.maybe_sample("step")
                if buf.should_sync(step):
                    # log boundary (aligned with ProgBarLogger's cadence):
                    # one batched readback covering every step since the
                    # previous boundary
                    logs = dict(buf.materialize())
                    if monitor.enabled:
                        # metric-flush boundary: the flight recorder's
                        # memory-watermark detector reads the boundary
                        # sampler's last (sync-free) measurement here
                        monitor.on_flush()
                else:
                    # keep the logs contract float-valued without syncing:
                    # callbacks see the last boundary's float (step 0 is
                    # always a boundary when sync_every >= 1); only an
                    # explicit sync_every=0 hands them the device value
                    val = buf.last_float("loss")
                    logs = {"loss": val if val is not None
                            else buf.latest("loss")}
                cbks.on_train_batch_end(step, logs)
            report = buf.flush()
            if monitor.enabled:
                monitor.on_flush()
            if "loss" in report:
                logs = {"loss": report["loss"]["last"]}
            cbks.on_epoch_end(epoch, logs)
            if eval_data is not None and (epoch % eval_freq == 0 or epoch == epochs - 1):
                eval_logs = self.evaluate(eval_data, batch_size=batch_size,
                                          verbose=0, num_workers=num_workers)
                cbks.on_eval_end(eval_logs)
            if self.stop_training:
                break
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        loader = self._make_loader(eval_data, batch_size, False)
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            xs, ys = self._split_batch(batch)
            batch_losses, _ = self.eval_batch(xs, ys)
            losses.extend(batch_losses)
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            res = m.accumulate()
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = res if isinstance(res, list) else [res]
            logs.update(dict(zip(names, vals)))
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = self._make_loader(test_data, batch_size, False)
        outputs = []
        for batch in loader:
            # datasets that yield (x, y) keep working for predict: the label
            # column is dropped, matching fit's input/label split
            xs, _ = self._split_batch(batch)
            outputs.append(self.predict_batch(xs))
        # transpose [steps][n_outs] -> [n_outs][steps]
        outs = list(map(list, zip(*outputs))) if outputs else []
        if stack_outputs:
            outs = [np.concatenate(o) for o in outs]
        return outs

    @staticmethod
    def _split_batch(batch, has_label=True):
        if isinstance(batch, (tuple, list)):
            if has_label and len(batch) >= 2:
                return list(batch[:-1]), [batch[-1]]
            return list(batch), []
        return [batch], []

    # ------------------------------------------------------------ persistence
    def save(self, path, training=True):
        from ..framework.io import save as fw_save

        fw_save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fw_save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        import os

        from ..framework.io import load as fw_load

        params = fw_load(path + ".pdparams") if not path.endswith(".pdparams") else fw_load(path)
        self.network.set_state_dict(params)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and os.path.exists(opt_path):
            self._optimizer.set_state_dict(fw_load(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        """Parameter-count summary (reference hapi/model_summary.py)."""
        rows, total = [], 0
        for name, p in self.network.named_parameters():
            n = int(np.prod(p.shape)) if p.shape else 1
            total += n
            rows.append((name, tuple(p.shape), n))
        width = max((len(r[0]) for r in rows), default=10) + 2
        lines = [f"{'Layer (param)':<{width}}{'Shape':<20}{'Param #':>12}"]
        lines += [f"{n:<{width}}{str(s):<20}{c:>12,}" for n, s, c in rows]
        lines.append(f"Total params: {total:,}")
        print("\n".join(lines))
        return {"total_params": total, "trainable_params": total}
