"""Thread-safe request queue with per-tenant admission control.

The front door of the serving tier: client threads :meth:`RequestQueue.submit`
requests; the scheduler thread pops FIFO prefixes sized by the bucket
ladder (:func:`jit.bucketing.assemble_bucket`). Admission is decided AT
submit — a full queue or an over-quota tenant is told *now* (an
:class:`AdmissionError` carries which gate refused), not after its request
aged in a queue it could never clear. Quota is measured in SAMPLES, not
requests: a tenant streaming batch-32 requests spends its budget 32x
faster than one sending singletons.

Every request carries its phase timestamps (enqueue → admit → dispatch →
complete, ``time.perf_counter`` space); completion hands them to
``profiler.pipeline.serving_stats`` so the latency accounting rides the
same observability channel as the train-loop pipeline stats.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np


class AdmissionError(RuntimeError):
    """A submit the admission controller refused: ``reason`` is ``"queue"``
    (global sample cap) or ``"tenant"`` (per-tenant in-flight quota)."""

    def __init__(self, reason: str, message: str):
        super().__init__(message)
        self.reason = reason


class RejectedError(RuntimeError):
    """Raised by :meth:`Request.result` when the queue shut down before the
    request was served."""


_req_ids = itertools.count()


class Request:
    """One inference request: ``n`` samples stacked on each input's batch
    axis. The submitting thread blocks in :meth:`result`; the scheduler
    thread completes it."""

    __slots__ = ("id", "tenant", "inputs", "n", "t_enqueue", "t_admit",
                 "t_dispatch", "t_complete", "_event", "_outputs", "_error")

    def __init__(self, tenant: str, inputs: Sequence[np.ndarray], n: int):
        self.id = next(_req_ids)
        self.tenant = tenant
        self.inputs = inputs
        self.n = int(n)
        self.t_enqueue = time.perf_counter()
        self.t_admit = None
        self.t_dispatch = None
        self.t_complete = None
        self._event = threading.Event()
        self._outputs = None
        self._error = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> List[np.ndarray]:
        """Block until served; returns the output arrays (``n`` rows each)."""
        if not self._event.wait(timeout):
            raise TimeoutError(f"request {self.id} not served in {timeout}s")
        if self._error is not None:
            raise self._error
        return self._outputs

    # scheduler side ------------------------------------------------------
    def _complete(self, outputs) -> None:
        self.t_complete = time.perf_counter()
        self._outputs = outputs
        self._event.set()

    def _fail(self, error: BaseException) -> None:
        self.t_complete = time.perf_counter()
        self._error = error
        self._event.set()


class AdmissionController:
    """Two admission gates, both in samples: a global queued-sample cap
    (protects the scheduler's latency promise — a deeper queue than the
    executor can clear inside the SLO is better refused than served late)
    and a per-tenant in-flight cap (one chatty tenant cannot starve the
    rest). In-flight = admitted and not yet completed, so quota releases
    only at completion, covering execution occupancy too."""

    def __init__(self, max_queue: Optional[int] = None,
                 tenant_quota: Optional[int] = None):
        from ..base.flags import get_flag

        self.max_queue = int(get_flag("serving_max_queue")
                             if max_queue is None else max_queue)
        self.tenant_quota = int(get_flag("serving_tenant_quota")
                                if tenant_quota is None else tenant_quota)
        self._queued = 0
        self._inflight: Dict[str, int] = {}
        # own lock: try_admit runs on client threads (under the queue's
        # condition), on_complete on the scheduler thread (no queue lock) —
        # the read-modify-writes of _inflight must serialize regardless of
        # which outer lock the caller holds
        self._lock = threading.Lock()

    def try_admit(self, tenant: str, n: int) -> Optional[str]:
        """None = admitted (state charged); else the refusing gate."""
        with self._lock:
            if self.max_queue > 0 and self._queued + n > self.max_queue:
                return "queue"
            if (self.tenant_quota > 0
                    and self._inflight.get(tenant, 0) + n > self.tenant_quota):
                return "tenant"
            self._queued += n
            self._inflight[tenant] = self._inflight.get(tenant, 0) + n
            return None

    def on_dispatch(self, tenant: str, n: int) -> None:
        with self._lock:
            self._queued -= n

    def on_complete(self, tenant: str, n: int) -> None:
        with self._lock:
            left = self._inflight.get(tenant, 0) - n
            if left > 0:
                self._inflight[tenant] = left
            else:
                self._inflight.pop(tenant, None)

    def inflight(self, tenant: str) -> int:
        with self._lock:
            return self._inflight.get(tenant, 0)


class RequestQueue:
    """FIFO of admitted requests + the condition variable the scheduler
    sleeps on. ``close()`` stops new submits; the scheduler keeps taking
    until the queue is drained (graceful shutdown serves everything that
    was admitted)."""

    def __init__(self, admission: Optional[AdmissionController] = None,
                 stats=None):
        self._dq: deque = deque()
        self._cond = threading.Condition()
        self.admission = admission or AdmissionController()
        self.closed = False
        if stats is None:
            from ..profiler.pipeline import serving_stats as stats
        self.stats = stats

    def __len__(self) -> int:
        with self._cond:
            return len(self._dq)

    def depth_samples(self) -> int:
        with self._cond:
            return sum(r.n for r in self._dq)

    def submit(self, request: Request) -> Request:
        """Admit + enqueue, or raise :class:`AdmissionError` /
        ``RuntimeError`` (closed). Stamps ``t_admit`` on success."""
        with self._cond:
            if self.closed:
                raise RuntimeError("serving queue is closed")
            gate = self.admission.try_admit(request.tenant, request.n)
            if gate is not None:
                self.stats.record_rejected(tenant=request.tenant)
                refusal = (
                    f"request of {request.n} samples refused by the "
                    f"'{gate}' gate (tenant={request.tenant!r}: "
                    f"{self.admission.inflight(request.tenant)} in flight, "
                    f"queue={self.admission._queued} samples)")
            else:
                request.t_admit = time.perf_counter()
                self._dq.append(request)
                self._cond.notify()
        if gate is not None:
            from ..observability.anomaly import monitor

            # rejection-burst watcher, fed OUTSIDE the condition lock: a
            # triggered verdict writes a forensic bundle, and that disk
            # I/O must never stall other tenants' submits or take_batch
            if monitor.enabled:
                monitor.on_rejected(request.tenant)
            raise AdmissionError(gate, refusal)
        return request

    def take_batch(self, buckets, max_total: Optional[int] = None,
                   timeout: Optional[float] = None,
                   linger: float = 0.0):
        """Scheduler side: block until requests are pending (or ``timeout``),
        then pop the FIFO prefix :func:`assemble_bucket` selects. Returns
        ``(requests, bucket)`` — or ``([], None)`` on timeout/closed-empty.

        ``buckets`` may be a ladder list or a zero-arg callable returning
        one; callables are resolved AFTER the wait, at assembly time, so a
        predictor re-laddered while the scheduler slept applies to the
        very batch that wakes it. ``max_total`` defaults to the ladder top.

        ``linger`` is the continuous-batching window: once ANY request is
        pending, wait up to that long for the rung to fill before
        dispatching a padded batch (latency spent buying fill)."""
        from ..jit.bucketing import assemble_bucket

        deadline = (time.perf_counter() + timeout) if timeout else None
        with self._cond:
            while not self._dq:
                if self.closed:
                    return [], None
                rest = (deadline - time.perf_counter()) if deadline else None
                if rest is not None and rest <= 0:
                    return [], None
                self._cond.wait(rest if rest is not None else 0.1)
            ladder = list(buckets()) if callable(buckets) else list(buckets)
            cap = (min(int(max_total), int(ladder[-1])) if max_total
                   else int(ladder[-1]))
            if linger > 0 and not self.closed:
                # a rung already full dispatches immediately; otherwise give
                # late arrivals one window to ride the same program call
                linger_until = time.perf_counter() + linger
                while (sum(r.n for r in self._dq) < cap
                       and not self.closed):
                    rest = linger_until - time.perf_counter()
                    if rest <= 0:
                        break
                    self._cond.wait(rest)
                if callable(buckets):  # re-resolve: the linger also slept
                    ladder = list(buckets())
                    cap = (min(int(max_total), int(ladder[-1])) if max_total
                           else int(ladder[-1]))
            try:
                k, bucket = assemble_bucket([r.n for r in self._dq], ladder,
                                            cap)
            except ValueError as e:
                # oversized head (engine.submit gates this; a live ladder
                # shrink can still race): fail ITS request, keep serving
                bad = self._dq.popleft()
                self.admission.on_dispatch(bad.tenant, bad.n)
                self.admission.on_complete(bad.tenant, bad.n)
                bad._fail(e)
                return [], None
            taken = [self._dq.popleft() for _ in range(k)]
            for r in taken:
                self.admission.on_dispatch(r.tenant, r.n)
            return taken, bucket

    def close(self) -> None:
        with self._cond:
            self.closed = True
            self._cond.notify_all()

    def fail_pending(self, error: BaseException) -> int:
        """Complete every still-queued request with ``error`` (non-drain
        shutdown). Returns how many were failed."""
        with self._cond:
            pending = list(self._dq)
            self._dq.clear()
            for r in pending:
                self.admission.on_dispatch(r.tenant, r.n)
                self.admission.on_complete(r.tenant, r.n)
                r._fail(error)
            return len(pending)
