"""Thread-safe request queue with per-tenant admission control.

The front door of the serving tier: client threads :meth:`RequestQueue.submit`
requests; the scheduler thread pops FIFO prefixes sized by the bucket
ladder (:func:`jit.bucketing.assemble_bucket`). Admission is decided AT
submit — a full queue or an over-quota tenant is told *now* (an
:class:`AdmissionError` carries which gate refused), not after its request
aged in a queue it could never clear. Quota is measured in SAMPLES, not
requests: a tenant streaming batch-32 requests spends its budget 32x
faster than one sending singletons.

Every request carries its phase timestamps (enqueue → admit → dispatch →
complete, ``time.perf_counter`` space); completion hands them to
``profiler.pipeline.serving_stats`` so the latency accounting rides the
same observability channel as the train-loop pipeline stats.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..observability.locks import named_condition, named_lock


class AdmissionError(RuntimeError):
    """A submit the admission controller refused: ``reason`` is ``"queue"``
    (global sample cap), ``"tenant"`` (per-tenant in-flight quota),
    ``"priority"`` (bulk tier refused to protect interactive headroom),
    ``"ttl"`` (the request expired in queue before it could be served) or
    ``"circuit"`` (the tenant's circuit breaker is open — its recent
    batches kept failing, so load is shed at the door until the breaker's
    cooldown probe succeeds)."""

    def __init__(self, reason: str, message: str):
        super().__init__(message)
        self.reason = reason


class RejectedError(RuntimeError):
    """Raised by :meth:`Request.result` when the queue shut down before the
    request was served."""


_req_ids = itertools.count()


class Request:
    """One inference request: ``n`` samples stacked on each input's batch
    axis. The submitting thread blocks in :meth:`result`; the scheduler
    thread completes it."""

    __slots__ = ("id", "tenant", "inputs", "n", "seq", "t_enqueue", "t_admit",
                 "t_dispatch", "t_complete", "_event", "_outputs", "_error")

    def __init__(self, tenant: str, inputs: Sequence[np.ndarray], n: int,
                 seq: Optional[int] = None):
        self.id = next(_req_ids)
        self.tenant = tenant
        self.inputs = inputs
        self.n = int(n)
        # real length on the sequence axis (two-axis exports only): the
        # scheduler pads up to the seq rung and slices back to this
        self.seq = None if seq is None else int(seq)
        self.t_enqueue = time.perf_counter()
        self.t_admit = None
        self.t_dispatch = None
        self.t_complete = None
        self._event = threading.Event()
        self._outputs = None
        self._error = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> List[np.ndarray]:
        """Block until served; returns the output arrays (``n`` rows each)."""
        if not self._event.wait(timeout):
            raise TimeoutError(f"request {self.id} not served in {timeout}s")
        if self._error is not None:
            raise self._error
        return self._outputs

    # scheduler side ------------------------------------------------------
    # Resolution is FIRST-RESULT-WINS: a retried batch replaying its
    # completion loop (reliability.RetryPolicy around the program call)
    # or a shutdown racing a drain must never overwrite a result a
    # client thread may already be reading. A second resolution attempt
    # is counted (`serving.duplicate_resolution` — the chaos harness
    # asserts it stays 0) and dropped.
    def _resolved_already(self) -> bool:
        if not self._event.is_set():
            return False
        from ..observability.metrics import registry

        registry.counter(
            "serving.duplicate_resolution",
            "attempts to complete/fail an already-resolved request "
            "future (must stay 0: nonzero means a retry or shutdown "
            "path double-delivered)").inc()
        return True

    def _complete(self, outputs) -> None:
        if self._resolved_already():
            return
        self.t_complete = time.perf_counter()
        self._outputs = outputs
        self._event.set()

    def _fail(self, error: BaseException) -> None:
        if self._resolved_already():
            return
        self.t_complete = time.perf_counter()
        self._error = error
        self._event.set()


class DecodeRequest(Request):
    """One autoregressive generation request: a token prompt that will
    occupy one KV slot from admission to retirement. The future resolves
    to the generated token ids (``np.int32``, greedy decode, up to
    ``max_new_tokens`` or the engine's EOS). ``n`` is 1 — admission is
    denominated in slots for the decode tier."""

    __slots__ = ("prompt", "max_new_tokens", "generated", "slot", "seq_rung",
                 "pages", "temperature", "top_k", "top_p", "seed",
                 "speculate", "spec_live", "spec_proposed", "spec_accepted")

    def __init__(self, tenant: str, prompt, max_new_tokens: int,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, seed: int = 0,
                 speculate: bool = False):
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("decode request needs a non-empty prompt")
        super().__init__(tenant, [prompt], 1, seq=int(prompt.size))
        self.prompt = prompt
        self.max_new_tokens = max(int(max_new_tokens), 1)
        self.generated: List[int] = []
        self.slot = None          # KV slot, assigned at admission-to-slot
        self.seq_rung = None      # prefill seq-ladder rung (scheduler set)
        self.pages: List[int] = []  # block table (paged pools only)
        # sampling knobs ride the programs as traced DATA (never a
        # retrace); temperature 0 = greedy, the bit-exact audit mode
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = int(seed)
        # self-speculative decoding lane policy (ISSUE 20): ``speculate``
        # is the per-request opt-in; ``spec_live`` drops to False when
        # the rolling acceptance (accepted/proposed) falls below
        # FLAGS_serving_spec_min_accept — drafts for this lane are
        # wasted work, the scheduler stops speculating once every
        # opted-in lane has disabled. The committed stream is identical
        # either way (only the tokens-per-full-pass chunking changes).
        self.speculate = bool(speculate)
        self.spec_live = bool(speculate)
        self.spec_proposed = 0
        self.spec_accepted = 0

    @property
    def position(self) -> int:
        """The next KV write position: prompt rows 0..len-1 land at
        prefill; generated token ``i`` (the input of decode step ``i+1``)
        writes at ``len + i``."""
        return int(self.prompt.size) + max(len(self.generated) - 1, 0)


class AdmissionController:
    """Admission gates, all in samples: a global queued-sample cap
    (protects the scheduler's latency promise — a deeper queue than the
    executor can clear inside the SLO is better refused than served late),
    a per-tenant in-flight cap (one chatty tenant cannot starve the
    rest), and a PRIORITY gate: tenants marked ``bulk`` (:meth:`set_tier`)
    may only fill ``FLAGS_serving_bulk_queue_share`` of the global cap, so
    interactive tenants always find headroom at the door — bulk work is
    preempted at admission, not mid-execution. In-flight = admitted and
    not yet completed, so quota releases only at completion, covering
    execution occupancy too.

    The controller also owns the request TTL
    (``FLAGS_serving_request_ttl_ms`` / ``request_ttl_ms``): the queue
    expires requests whose wait exceeds it (:class:`AdmissionError`
    reason ``"ttl"``, ``serving.expired`` counter) instead of executing
    dead work whose client has long timed out."""

    #: named priority tiers (lower = more urgent); ints also accepted
    TIERS = {"interactive": 0, "bulk": 1}

    def __init__(self, max_queue: Optional[int] = None,
                 tenant_quota: Optional[int] = None,
                 request_ttl_ms: Optional[float] = None,
                 breaker_board=None):
        from ..base.flags import get_flag

        self.max_queue = int(get_flag("serving_max_queue")
                             if max_queue is None else max_queue)
        self.tenant_quota = int(get_flag("serving_tenant_quota")
                                if tenant_quota is None else tenant_quota)
        # None defers to the flag at expiry time (live-tunable)
        self._ttl_ms = request_ttl_ms
        # per-tenant circuit breakers (reliability.BreakerBoard): a
        # tenant whose batches keep failing is shed HERE, at the door,
        # instead of queueing work a broken path will fail late
        self.breaker_board = breaker_board
        self._tiers: Dict[str, int] = {}
        self._queued = 0
        self._inflight: Dict[str, int] = {}
        # own lock: try_admit runs on client threads (under the queue's
        # condition), on_complete on the scheduler thread (no queue lock) —
        # the read-modify-writes of _inflight must serialize regardless of
        # which outer lock the caller holds
        self._lock = named_lock("serving.admission")

    # ------------------------------------------------------------ tiers
    def set_tier(self, tenant: str, tier) -> None:
        """Pin ``tenant`` to a priority tier: ``"interactive"`` (0, the
        default) or ``"bulk"`` (1) — or any int, lower = more urgent."""
        with self._lock:
            self._tiers[tenant] = (self.TIERS[tier] if isinstance(tier, str)
                                   else int(tier))

    def tier_of(self, tenant: str) -> int:
        with self._lock:
            return self._tiers.get(tenant, 0)

    def ttl_s(self) -> float:
        """The live request TTL in seconds (<=0 disables)."""
        ms = self._ttl_ms
        if ms is None:
            from ..base.flags import get_flag

            ms = float(get_flag("serving_request_ttl_ms"))
        return float(ms) / 1e3

    def try_admit(self, tenant: str, n: int) -> Optional[str]:
        """None = admitted (state charged); else the refusing gate."""
        # consulted OUTSIDE self._lock: the board has its own lock and an
        # open breaker's cooldown probe must not serialize admissions
        if self.breaker_board is not None and self.breaker_board.is_open(tenant):
            return "circuit"
        with self._lock:
            if self.max_queue > 0 and self._queued + n > self.max_queue:
                return "queue"
            if self._tiers.get(tenant, 0) > 0 and self.max_queue > 0:
                from ..base.flags import get_flag

                cap = int(self.max_queue
                          * float(get_flag("serving_bulk_queue_share")))
                if self._queued + n > cap:
                    return "priority"
            if (self.tenant_quota > 0
                    and self._inflight.get(tenant, 0) + n > self.tenant_quota):
                return "tenant"
            self._queued += n
            self._inflight[tenant] = self._inflight.get(tenant, 0) + n
            return None

    def on_dispatch(self, tenant: str, n: int) -> None:
        with self._lock:
            self._queued -= n

    def on_complete(self, tenant: str, n: int) -> None:
        with self._lock:
            left = self._inflight.get(tenant, 0) - n
            if left > 0:
                self._inflight[tenant] = left
            else:
                self._inflight.pop(tenant, None)

    def inflight(self, tenant: str) -> int:
        with self._lock:
            return self._inflight.get(tenant, 0)


class RequestQueue:
    """FIFO of admitted requests + the condition variable the scheduler
    sleeps on. ``close()`` stops new submits; the scheduler keeps taking
    until the queue is drained (graceful shutdown serves everything that
    was admitted)."""

    def __init__(self, admission: Optional[AdmissionController] = None,
                 stats=None):
        self._dq: deque = deque()
        self._cond = named_condition("serving.queue")
        self.admission = admission or AdmissionController()
        self.closed = False
        if stats is None:
            from ..profiler.pipeline import serving_stats as stats
        self.stats = stats

    def __len__(self) -> int:
        with self._cond:
            return len(self._dq)

    def depth_samples(self) -> int:
        with self._cond:
            return sum(r.n for r in self._dq)

    def submit(self, request: Request) -> Request:
        """Admit + enqueue, or raise :class:`AdmissionError` /
        ``RuntimeError`` (closed). Stamps ``t_admit`` on success."""
        with self._cond:
            if self.closed:
                raise RuntimeError("serving queue is closed")
            gate = self.admission.try_admit(request.tenant, request.n)
            if gate is not None:
                self.stats.record_rejected(tenant=request.tenant)
                refusal = (
                    f"request of {request.n} samples refused by the "
                    f"'{gate}' gate (tenant={request.tenant!r}: "
                    f"{self.admission.inflight(request.tenant)} in flight, "
                    f"queue={self.admission._queued} samples)")
            else:
                request.t_admit = time.perf_counter()
                self._dq.append(request)
                self._cond.notify()
        if gate is not None:
            from ..observability.anomaly import monitor

            # rejection-burst watcher, fed OUTSIDE the condition lock: a
            # triggered verdict writes a forensic bundle, and that disk
            # I/O must never stall other tenants' submits or take_batch
            if monitor.enabled:
                monitor.on_rejected(request.tenant)
            raise AdmissionError(gate, refusal)
        return request

    def _expire_locked(self, now: float) -> None:
        """Fail every request whose queue wait exceeded the TTL (caller
        holds the condition). Requests enqueue in arrival order, so the
        overdue set is always a prefix of the deque — dead work leaves
        BEFORE batch assembly instead of occupying a program call whose
        client already timed out."""
        ttl = self.admission.ttl_s()
        if ttl <= 0:
            return
        expired = []
        while self._dq and (now - self._dq[0].t_enqueue) > ttl:
            r = self._dq.popleft()
            self.admission.on_dispatch(r.tenant, r.n)
            self.admission.on_complete(r.tenant, r.n)
            expired.append(r)
        if not expired:
            return
        from ..observability.metrics import registry

        counter = registry.counter(
            "serving.expired",
            "requests expired in queue past FLAGS_serving_request_ttl_ms "
            "(failed with AdmissionError reason='ttl', never executed)")
        for r in expired:
            wait_ms = (now - r.t_enqueue) * 1e3
            counter.inc(tenant=r.tenant)
            if hasattr(self.stats, "record_expired"):
                self.stats.record_expired(tenant=r.tenant)
            r._fail(AdmissionError(
                "ttl", f"request {r.id} expired after {wait_ms:.1f}ms in "
                       f"queue (> FLAGS_serving_request_ttl_ms = "
                       f"{self.admission.ttl_s() * 1e3:.1f}ms); dead work "
                       "is refused, not executed"))

    def take_slots(self, max_requests: int,
                   timeout: Optional[float] = None,
                   budget_fn=None) -> List[Request]:
        """Decode-scheduler side: pop up to ``max_requests`` pending
        requests in (priority tier, FIFO) order — the slot-admission path
        of the continuous-batching loop. Interactive-tier requests go
        first regardless of queue position (bulk work preempted at
        admission); within a tier FIFO order holds. TTL-overdue requests
        are expired first, never handed out. Returns ``[]`` on
        timeout/closed-empty; with ``timeout`` of 0/None it never blocks
        (the decode loop polls between steps).

        ``budget_fn(request) -> bool`` is the paged pools' admission
        gate: taking STOPS at the first request it refuses (the request
        stays queued, and nothing behind it jumps ahead — a page-budget
        wait must not become a reorder), so a request that merely has to
        wait for a retirement is never shed."""
        if max_requests <= 0:
            return []
        with self._cond:
            self._expire_locked(time.perf_counter())
            if not self._dq and timeout:
                deadline = time.perf_counter() + timeout
                while not self._dq and not self.closed:
                    rest = deadline - time.perf_counter()
                    if rest <= 0:
                        break
                    self._cond.wait(rest)
                self._expire_locked(time.perf_counter())
            if not self._dq:
                return []
            order = sorted(
                range(len(self._dq)),
                key=lambda i: (self.admission.tier_of(self._dq[i].tenant), i))
            chosen = order[:int(max_requests)]
            if budget_fn is not None:
                fits = 0
                for i in chosen:
                    if not budget_fn(self._dq[i]):
                        break
                    fits += 1
                chosen = chosen[:fits]
                if not chosen:
                    return []
            # returned in PRIORITY order (interactive lanes anchor prefill
            # grouping); the survivors keep their FIFO deque order
            taken = [self._dq[i] for i in chosen]
            chosen_set = set(chosen)
            kept = [r for i, r in enumerate(self._dq) if i not in chosen_set]
            self._dq.clear()
            self._dq.extend(kept)
            for r in taken:
                self.admission.on_dispatch(r.tenant, r.n)
            return taken

    def take_batch(self, buckets, max_total: Optional[int] = None,
                   timeout: Optional[float] = None,
                   linger: float = 0.0):
        """Scheduler side: block until requests are pending (or ``timeout``),
        then pop the FIFO prefix :func:`assemble_bucket` selects. Returns
        ``(requests, bucket)`` — or ``([], None)`` on timeout/closed-empty.

        ``buckets`` may be a ladder list or a zero-arg callable returning
        one; callables are resolved AFTER the wait, at assembly time, so a
        predictor re-laddered while the scheduler slept applies to the
        very batch that wakes it. ``max_total`` defaults to the ladder top.

        ``linger`` is the continuous-batching window: once ANY request is
        pending, wait up to that long for the rung to fill before
        dispatching a padded batch (latency spent buying fill)."""
        from ..jit.bucketing import assemble_bucket

        deadline = (time.perf_counter() + timeout) if timeout else None
        with self._cond:
            self._expire_locked(time.perf_counter())
            while not self._dq:
                if self.closed:
                    return [], None
                rest = (deadline - time.perf_counter()) if deadline else None
                if rest is not None and rest <= 0:
                    return [], None
                self._cond.wait(rest if rest is not None else 0.1)
                self._expire_locked(time.perf_counter())
            ladder = list(buckets()) if callable(buckets) else list(buckets)
            cap = (min(int(max_total), int(ladder[-1])) if max_total
                   else int(ladder[-1]))
            if linger > 0 and not self.closed:
                # a rung already full dispatches immediately; otherwise give
                # late arrivals one window to ride the same program call
                linger_until = time.perf_counter() + linger
                while (sum(r.n for r in self._dq) < cap
                       and not self.closed):
                    rest = linger_until - time.perf_counter()
                    if rest <= 0:
                        break
                    self._cond.wait(rest)
                if callable(buckets):  # re-resolve: the linger also slept
                    ladder = list(buckets())
                    cap = (min(int(max_total), int(ladder[-1])) if max_total
                           else int(ladder[-1]))
                self._expire_locked(time.perf_counter())
                if not self._dq:
                    return [], None
            try:
                k, bucket = assemble_bucket([r.n for r in self._dq], ladder,
                                            cap)
            except ValueError as e:
                # oversized head (engine.submit gates this; a live ladder
                # shrink can still race): fail ITS request, keep serving
                bad = self._dq.popleft()
                self.admission.on_dispatch(bad.tenant, bad.n)
                self.admission.on_complete(bad.tenant, bad.n)
                bad._fail(e)
                return [], None
            taken = [self._dq.popleft() for _ in range(k)]
            for r in taken:
                self.admission.on_dispatch(r.tenant, r.n)
            return taken, bucket

    def close(self) -> None:
        with self._cond:
            self.closed = True
            self._cond.notify_all()

    def fail_pending(self, error: BaseException) -> int:
        """Complete every still-queued request with ``error`` (non-drain
        shutdown). Returns how many were failed."""
        with self._cond:
            pending = list(self._dq)
            self._dq.clear()
            for r in pending:
                self.admission.on_dispatch(r.tenant, r.n)
                self.admission.on_complete(r.tenant, r.n)
                r._fail(error)
            return len(pending)
