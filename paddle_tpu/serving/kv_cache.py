"""Device-resident KV cache with slot-based alloc/release.

The memory discipline of true continuous batching: decode state lives in
ONE pair of device buffers shaped ``[layers, max_slots+1, max_seq, heads,
head_dim]``, allocated once at engine construction and never resized —
O(``FLAGS_serving_max_slots``) residency, not O(traffic) and not
O(max_batch x max_seq) per request (the O(shard)-residency discipline of
the redistribution work, PAPERS arxiv 2112.01075, applied to serving
state). Requests borrow a slot from the free list at admission, their
prompt/token K/V rows are written in place by the jitted prefill/decode
programs (functional ``lax.dynamic_update_slice`` / scatter updates under
buffer donation, so XLA aliases the output onto the input allocation —
no per-step reallocation), and the slot returns to the free list at
retirement for the next queued request.

Slot ``max_slots`` (the last one) is the *pad slot*: batch lanes that
only exist to fill a bucket rung write their garbage K/V there, so a
padded program call can scatter unconditionally without touching any
live sequence's state.

Host-side bookkeeping (free list, per-slot lengths, occupancy gauge)
stays in :class:`KVSlotPool`; the pure functions below run inside the
jitted programs and carry no python state.
"""
from __future__ import annotations

from typing import Iterable, List, Optional

import numpy as np

from ..observability.locks import named_lock

__all__ = ["KVSlotPool", "KVPagePool", "write_prompt",
           "write_prompt_batch", "append_token", "write_prompt_pages",
           "append_token_paged", "gather_pages"]


# ------------------------------------------------------ functional updates
def write_prompt(cache, slot, rows):
    """Write one prompt's K (or V) rows into one slot — the interactive
    single-request prefill path: ``rows`` is ``[layers, S, heads, dim]``,
    ``slot`` a scalar; one ``lax.dynamic_update_slice`` at (0, slot, 0,
    0, 0). Under donation XLA updates the pool buffer in place."""
    import jax.lax as lax
    import jax.numpy as jnp

    return lax.dynamic_update_slice(
        cache, rows[:, None].astype(cache.dtype),
        (jnp.zeros((), jnp.int32), jnp.asarray(slot, jnp.int32),
         jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32),
         jnp.zeros((), jnp.int32)))


def write_prompt_batch(cache, slot_ids, rows):
    """Batched prefill write: ``rows`` is ``[layers, B, S, heads, dim]``,
    ``slot_ids`` ``[B]`` — one scatter over the slot axis covering every
    layer. Rows past a lane's real prompt length carry garbage, which is
    safe by construction: decode overwrites position ``len`` before any
    step attends to it."""
    S = rows.shape[2]
    return cache.at[:, slot_ids, :S].set(rows.astype(cache.dtype))


def append_token(cache, layer, slot_ids, positions, rows):
    """One decode step's write for one layer: ``rows`` is ``[B, heads,
    dim]`` landing at ``(layer, slot_ids[b], positions[b])``. Pad lanes
    point at the pool's pad slot so the scatter needs no mask."""
    return cache.at[layer, slot_ids, positions].set(
        rows.astype(cache.dtype))


# ------------------------------------------------- paged functional updates
def write_prompt_pages(cache, tables, rows):
    """Batched paged prefill write: ``rows`` is ``[layers, B, T*ps,
    heads, dim]`` (prompt K/V padded up to whole pages), ``tables`` is
    the traced ``[B, T]`` int32 block table — one scatter over the page
    axis covering every layer. Table entries past a lane's real pages
    are 0 (the pad page), so garbage rows land in the trash page and a
    padded program call never touches live state."""
    L, B, _, H, D = rows.shape
    T = tables.shape[1]
    ps = cache.shape[2]
    paged = rows.astype(cache.dtype).reshape(L, B, T, ps, H, D)
    return cache.at[:, tables].set(paged)


def append_token_paged(cache, layer, pages, offsets, rows):
    """One decode step's paged write for one layer: ``rows`` is ``[B,
    heads, dim]`` landing at ``(layer, pages[b], offsets[b])`` where
    ``pages[b] = table[b, pos // page_size]`` and ``offsets[b] = pos %
    page_size`` — both traced. Pad lanes carry page 0."""
    return cache.at[layer, pages, offsets].set(rows.astype(cache.dtype))


def gather_pages(cache, layer, tables):
    """Materialize a batch's contiguous K (or V) view from the page
    array: ``cache[layer][tables]`` gathers ``[B, T, ps, heads, dim]``
    along the page axis and reshapes to ``[B, T*ps, heads, dim]`` — the
    traced-block-table read the decode attention indexes through. One
    compiled program serves ANY page map because the table is data."""
    B, T = tables.shape
    ps, H, D = cache.shape[2], cache.shape[3], cache.shape[4]
    return cache[layer][tables].reshape(B, T * ps, H, D)


# --------------------------------------------------------------- the pool
class KVSlotPool:
    """Free-list slot allocator over one device-resident K/V buffer pair.

    ``alloc()``/``release()`` run on the scheduler thread (a lock keeps
    them safe for engine shutdown paths); the arrays themselves are
    replaced wholesale by :meth:`commit` after each program call — the
    functional update idiom, with donation making it in-place on
    accelerators. :meth:`device_bytes` must never change after
    :meth:`mark_warm` (the JX332 audit and the bench's
    ``kv_pool_bytes_constant`` proof)."""

    def __init__(self, num_layers: int, max_slots: int, max_seq: int,
                 num_heads: int, head_dim: int, dtype="float32"):
        import jax.numpy as jnp

        if max_slots < 1:
            raise ValueError("KVSlotPool needs at least one slot")
        self.num_layers = int(num_layers)
        self.max_slots = int(max_slots)
        self.max_seq = int(max_seq)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        # +1: the pad slot — garbage writes from bucket-padding lanes
        shape = (self.num_layers, self.max_slots + 1, self.max_seq,
                 self.num_heads, self.head_dim)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        self.lengths = np.zeros(self.max_slots, np.int32)  # host-side
        self._free: List[int] = list(range(self.max_slots - 1, -1, -1))
        self._lock = named_lock("serving.kv_pool")
        self.bytes_at_warmup: Optional[int] = None
        self._gauge_occupancy()

    # ------------------------------------------------------------ slots
    @property
    def pad_slot(self) -> int:
        """The trash slot padded batch lanes write to (never allocated)."""
        return self.max_slots

    def alloc(self) -> int:
        """Borrow a free slot (its length resets to 0); raises
        ``RuntimeError`` when the pool is exhausted — the scheduler must
        gate admission on :meth:`free_count`."""
        with self._lock:
            if not self._free:
                raise RuntimeError(
                    f"KV slot pool exhausted ({self.max_slots} slots in "
                    "use); admission must wait for a retirement")
            slot = self._free.pop()
            self.lengths[slot] = 0
        self._gauge_occupancy()
        return slot

    def release(self, slot: int) -> None:
        """Return a slot to the free list (idempotence guarded)."""
        with self._lock:
            slot = int(slot)
            if not 0 <= slot < self.max_slots:
                raise ValueError(f"slot {slot} out of range")
            if slot in self._free:
                raise ValueError(f"slot {slot} is already free")
            self.lengths[slot] = 0
            self._free.append(slot)
        self._gauge_occupancy()

    def in_use(self) -> int:
        with self._lock:
            return self.max_slots - len(self._free)

    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    # ------------------------------------------------------------ buffers
    def commit(self, new_k, new_v) -> None:
        """Swap in the post-step buffers (the jitted program's functional
        outputs). Shape and dtype are pinned — a program handing back a
        different footprint is a bug the JX332 audit would otherwise
        catch after the fact. An injected ``kv.commit`` fault rejects
        the swap BEFORE any assignment: the pool keeps the previous
        buffers and the decode fault wall releases the step's slots."""
        from ..reliability.faults import fault_point

        fault_point("kv.commit")
        if (new_k.shape != self.k.shape or new_v.shape != self.v.shape
                or new_k.dtype != self.k.dtype):
            raise ValueError(
                f"KV commit changed the pool footprint: "
                f"{self.k.shape}/{self.k.dtype} -> "
                f"{new_k.shape}/{new_k.dtype}")
        self.k = new_k
        self.v = new_v
        # NaN/Inf sentinel on the committed keys (one bool read when the
        # numerics witness is dark; a poisoned decode step shows up here
        # before it contaminates every later token)
        from ..observability import numerics

        numerics.watch("serving.kv_commit", new_k)

    def device_bytes(self) -> int:
        return int(self.k.nbytes) + int(self.v.nbytes)

    def mark_warm(self) -> None:
        """Freeze the footprint baseline (end of engine warmup): any
        later :meth:`device_bytes` drift is a JX332 error."""
        self.bytes_at_warmup = self.device_bytes()

    # ------------------------------------------------------ observability
    def _gauge_occupancy(self) -> None:
        from ..observability.metrics import registry

        registry.gauge(
            "serving.kv_slots_in_use",
            "KV cache slots currently allocated to live decode sequences "
            "(capacity = FLAGS_serving_max_slots)").set(
                self.max_slots - len(self._free))


# ---------------------------------------------------------- the page pool
class KVPagePool:
    """Free-list *page* allocator over one device-resident K/V buffer
    pair shaped ``[layers, num_pages+1, page_size, heads, head_dim]``.

    The vLLM discipline applied to the slot pool above: instead of one
    full ``max_seq`` row per sequence, a request holds only the fixed-
    size pages its live tokens occupy, named by a per-request *block
    table* (a list of page ids, traced as an int32 array inside the
    decode programs). Page 0 is the pad page — bucket-padding lanes and
    table padding both point there, so scatters and gathers need no
    mask. Mixed 128–4k contexts share one pool whose residency tracks
    live tokens, not the per-request worst case.

    The host side mirrors :class:`KVSlotPool`: ``alloc``/``release`` on
    the scheduler thread under a lock, :meth:`commit` swapping in the
    jitted programs' functional outputs under donation, and
    :meth:`device_bytes` frozen after :meth:`mark_warm` (the JX332
    audit and the bench's ``kv_pool_bytes_constant`` proof duck-type
    both pools). :meth:`note_utilization` feeds the JX334
    page-fragmentation watermark."""

    def __init__(self, num_layers: int, num_pages: int, page_size: int,
                 num_heads: int, head_dim: int, dtype="float32"):
        import jax.numpy as jnp

        if num_pages < 1:
            raise ValueError("KVPagePool needs at least one page")
        if page_size < 1 or (page_size & (page_size - 1)):
            raise ValueError(
                f"page_size must be a power of two, got {page_size}")
        self.num_layers = int(num_layers)
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        # +1: page 0 is the pad page — never allocated, absorbs garbage
        shape = (self.num_layers, self.num_pages + 1, self.page_size,
                 self.num_heads, self.head_dim)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        # low page ids hand out first: pop() from the tail
        self._free: List[int] = list(range(self.num_pages, 0, -1))
        self._lock = named_lock("serving.kv_pool")
        self.bytes_at_warmup: Optional[int] = None
        self._util_sum = 0.0
        self._util_min = 1.0
        self._util_samples = 0
        self._gauge_occupancy()

    # ------------------------------------------------------------ pages
    @property
    def pad_page(self) -> int:
        """The trash page padded lanes and table padding point at."""
        return 0

    def alloc(self, n: int = 1) -> List[int]:
        """Borrow ``n`` free pages; raises ``RuntimeError`` when the
        pool cannot cover the request — the caller (scheduler) sheds
        that ONE request and releases any pages it already holds, so an
        allocation failure never leaks and never touches other lanes.
        The ``kv.page_alloc`` fault site lives here: an injected
        failure exercises exactly that shed path."""
        from ..reliability.faults import fault_point

        fault_point("kv.page_alloc")
        with self._lock:
            if len(self._free) < n:
                raise RuntimeError(
                    f"KV page pool exhausted ({self.num_pages - len(self._free)}"
                    f"/{self.num_pages} pages in use, {n} requested); "
                    "admission must wait for a retirement")
            pages = [self._free.pop() for _ in range(n)]
        self._gauge_occupancy()
        return pages

    def release(self, pages: Iterable[int]) -> None:
        """Return a request's pages to the free list (idempotence and
        range guarded per page)."""
        with self._lock:
            for page in pages:
                page = int(page)
                if not 1 <= page <= self.num_pages:
                    raise ValueError(f"page {page} out of range")
                if page in self._free:
                    raise ValueError(f"page {page} is already free")
                self._free.append(page)
        self._gauge_occupancy()

    def in_use(self) -> int:
        with self._lock:
            return self.num_pages - len(self._free)

    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    # ------------------------------------------------------------ buffers
    def commit(self, new_k, new_v) -> None:
        """Swap in the post-step buffers — same contract as
        :meth:`KVSlotPool.commit`: footprint pinned, ``kv.commit``
        fault rejects BEFORE assignment, numerics witness on keys."""
        from ..reliability.faults import fault_point

        fault_point("kv.commit")
        if (new_k.shape != self.k.shape or new_v.shape != self.v.shape
                or new_k.dtype != self.k.dtype):
            raise ValueError(
                f"KV commit changed the pool footprint: "
                f"{self.k.shape}/{self.k.dtype} -> "
                f"{new_k.shape}/{new_k.dtype}")
        self.k = new_k
        self.v = new_v
        from ..observability import numerics

        numerics.watch("serving.kv_commit", new_k)

    def device_bytes(self) -> int:
        return int(self.k.nbytes) + int(self.v.nbytes)

    def mark_warm(self) -> None:
        """Freeze the footprint baseline (end of engine warmup): any
        later :meth:`device_bytes` drift is a JX332 error."""
        self.bytes_at_warmup = self.device_bytes()

    # ------------------------------------------------------ observability
    def note_utilization(self, live_tokens: int) -> None:
        """Record one page-utilization sample: live tokens over the
        token capacity of the pages currently in use. Sampled by the
        scheduler each decode step; the running mean/min feed the JX334
        fragmentation watermark and the utilization gauge."""
        with self._lock:
            used = self.num_pages - len(self._free)
        if used <= 0:
            return
        util = min(1.0, float(live_tokens) / float(used * self.page_size))
        self._util_sum += util
        self._util_min = min(self._util_min, util)
        self._util_samples += 1
        from ..observability.metrics import registry

        registry.gauge(
            "serving.kv_page_utilization",
            "live tokens / token capacity of in-use KV pages — low "
            "values mean fragmentation (JX334)").set(util)

    def utilization_report(self) -> dict:
        n = self._util_samples
        return {
            "samples": n,
            "mean": (self._util_sum / n) if n else 1.0,
            "min": self._util_min if n else 1.0,
        }

    def _gauge_occupancy(self) -> None:
        from ..observability.metrics import registry

        registry.gauge(
            "serving.kv_pages_in_use",
            "KV cache pages currently allocated to live decode "
            "sequences (capacity = pool num_pages)").set(
                self.num_pages - len(self._free))
