"""Continuous bucketed batch assembly: requests → padded device batch → rows.

The scheduler is the piece between the queue and the warm-compiled
predictor program: it stacks a FIFO prefix of mixed-size requests along
the batch axis, pads the stack up to the bucket rung
(:func:`jit.bucketing.assemble_bucket` picked), runs ONE program call,
and scatters the output rows back to their requests. Re-batching is
continuous — assembly happens again between every pair of steps, so
requests that arrived while the previous batch computed ride the very
next program call.

Pure functions (:func:`stack_requests`, :func:`scatter_outputs`) do the
array work so they unit-test without threads; :class:`Scheduler` is the
one background thread that loops take → stack → execute → scatter.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .request_queue import Request, RequestQueue


def stack_requests(requests: Sequence[Request], bucket: int,
                   dynamic_axes: Dict[int, int],
                   n_inputs: int) -> List[np.ndarray]:
    """Concatenate each input across requests along its batch axis and
    zero-pad up to ``bucket``. Inputs without a dynamic axis (static side
    inputs of a partially dynamic export) are per-BATCH, not per-sample —
    every batched request must carry the same value, verified bit-wise
    (serving request 1's rows with request 0's side input would be a
    silent cross-tenant data leak; a loud batch failure is the contract)."""
    stacked = []
    axes = dynamic_axes or {i: 0 for i in range(n_inputs)}
    for i in range(n_inputs):
        if i not in axes:
            head = np.asarray(requests[0].inputs[i])
            for r in requests[1:]:
                if not np.array_equal(head, np.asarray(r.inputs[i])):
                    raise ValueError(
                        f"static input {i} differs across the assembled "
                        "batch (per-batch side inputs must match bit-wise "
                        "to share one program call)")
            stacked.append(head)
            continue
        ax = axes[i]
        parts = [np.asarray(r.inputs[i]) for r in requests]
        cat = parts[0] if len(parts) == 1 else np.concatenate(parts, axis=ax)
        short = bucket - cat.shape[ax]
        if short > 0:
            widths = [(0, 0)] * cat.ndim
            widths[ax] = (0, short)
            cat = np.pad(cat, widths)
        stacked.append(cat)
    return stacked


def fetch_outputs(outputs: Sequence) -> List[np.ndarray]:
    """ONE device fetch per assembled batch (ROADMAP serving leftover):
    start every output leaf's D2H copy asynchronously first, then gather —
    the transfers overlap on the wire instead of serializing one blocking
    ``np.asarray`` round-trip per leaf. Counted once per call in the
    ``serving.d2h_fetches`` observability counter (vs once per LEAF under
    the old path), which is the proof the batch readback stays batched."""
    from ..observability.metrics import registry

    leaves = list(outputs)
    for leaf in leaves:
        start = getattr(leaf, "copy_to_host_async", None)
        if start is not None:
            start()
    arrays = [np.asarray(leaf) for leaf in leaves]
    registry.counter(
        "serving.d2h_fetches",
        "device→host readback rounds issued by the serving scheduler "
        "(one per assembled batch, NOT one per output leaf)").inc()
    return arrays


def scatter_outputs(outputs: Sequence[np.ndarray],
                    requests: Sequence[Request]) -> List[List[np.ndarray]]:
    """Split each output's leading axis back into per-request row blocks
    (the padding tail is dropped). Output batch axis is 0 by the serving
    export contract."""
    per_request: List[List[np.ndarray]] = [[] for _ in requests]
    offsets = []
    pos = 0
    for r in requests:
        offsets.append(pos)
        pos += r.n
    for out in outputs:
        arr = np.asarray(out)
        for j, r in enumerate(requests):
            per_request[j].append(arr[offsets[j]: offsets[j] + r.n])
    return per_request


class Scheduler:
    """The serving tier's one executor thread: continuously drains the
    queue into bucketed batches and hands them to ``execute`` (the
    engine's predictor call). Crashes in ``execute`` fail only the batch
    that triggered them — the loop survives and keeps serving."""

    def __init__(self, queue: RequestQueue, execute: Callable,
                 buckets, *, max_batch: Optional[int] = None,
                 linger_s: float = 0.0, on_batch: Optional[Callable] = None):
        self.queue = queue
        self.execute = execute           # (requests, bucket) -> None
        # a list, or a zero-arg callable for a LIVE ladder view (the engine
        # passes the batch program's, so a re-laddered predictor takes
        # effect at the very next assembly, no scheduler restart)
        self.buckets = buckets
        self.max_batch = max_batch
        self.linger_s = float(linger_s)
        self.on_batch = on_batch         # (n_samples, bucket, depth) tap
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()

    def start(self) -> "Scheduler":
        if self._thread is not None:
            raise RuntimeError("scheduler already started")
        self._thread = threading.Thread(target=self._loop,
                                        name="paddle-serving-scheduler",
                                        daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        from ..observability.anomaly import monitor
        from ..observability.memory import sampler
        from ..observability.tracing import tracer

        while True:
            # buckets/max pass through RAW: take_batch resolves a callable
            # ladder at assembly time, after its wait — no stale snapshot
            requests, bucket = self.queue.take_batch(
                self.buckets, self.max_batch, timeout=0.05,
                linger=self.linger_s)
            if not requests:
                if self.queue.closed and len(self.queue) == 0:
                    break
                continue
            now = time.perf_counter()
            for r in requests:
                r.t_dispatch = now
            n_samples = sum(r.n for r in requests)
            if self.on_batch is not None:
                self.on_batch(n_samples, bucket, self.queue.depth_samples())
            try:
                with tracer.span("serving.batch", track="serving.scheduler",
                                 bucket=bucket, n_samples=n_samples,
                                 n_requests=len(requests)):
                    self.execute(requests, bucket)
            except BaseException as e:  # noqa: BLE001 — batch-scoped fault wall
                if monitor.enabled:
                    # serving-worker exception hook: capture the forensic
                    # window BEFORE the batch is failed away (the flight
                    # recorder is the only record once result() re-raises)
                    monitor.on_exception("serving.worker", e)
                for r in requests:
                    self.queue.admission.on_complete(r.tenant, r.n)
                    r._fail(e)
            # batch-boundary memory telemetry (sync-free by contract)
            sampler.maybe_sample("batch")
        self._stopped.set()

    def alive(self) -> bool:
        """Is the executor thread running? (the /healthz liveness probe)"""
        return self._thread is not None and self._thread.is_alive()

    def join(self, timeout: Optional[float] = None) -> bool:
        """Wait for the loop to exit (after ``queue.close()``)."""
        if self._thread is None:
            return True
        self._thread.join(timeout)
        return not self._thread.is_alive()
