"""Continuous bucketed batch assembly: requests → padded device batch → rows.

The scheduler is the piece between the queue and the warm-compiled
predictor program: it stacks a FIFO prefix of mixed-size requests along
the batch axis, pads the stack up to the bucket rung
(:func:`jit.bucketing.assemble_bucket` picked), runs ONE program call,
and scatters the output rows back to their requests. Re-batching is
continuous — assembly happens again between every pair of steps, so
requests that arrived while the previous batch computed ride the very
next program call.

Pure functions (:func:`stack_requests`, :func:`scatter_outputs`) do the
array work so they unit-test without threads; :class:`Scheduler` is the
one background thread that loops take → stack → execute → scatter.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .request_queue import Request, RequestQueue


def stack_requests(requests: Sequence[Request], bucket: int,
                   dynamic_axes: Dict[int, int],
                   n_inputs: int,
                   seq_axes: Optional[Dict[int, int]] = None,
                   seq_bucket: Optional[int] = None) -> List[np.ndarray]:
    """Concatenate each input across requests along its batch axis and
    zero-pad up to ``bucket``. On two-axis exports each request's
    sequence axis (``seq_axes``: {input_idx: axis}) is first right-padded
    up to ``seq_bucket`` so mixed-length requests stack into one (batch,
    seq) rung. Inputs without a dynamic axis (static side inputs of a
    partially dynamic export) are per-BATCH, not per-sample — every
    batched request must carry the same value, verified bit-wise (serving
    request 1's rows with request 0's side input would be a silent
    cross-tenant data leak; a loud batch failure is the contract)."""
    stacked = []
    axes = dynamic_axes or {i: 0 for i in range(n_inputs)}
    seq_axes = seq_axes or {}
    for i in range(n_inputs):
        if i not in axes:
            head = np.asarray(requests[0].inputs[i])
            for r in requests[1:]:
                if not np.array_equal(head, np.asarray(r.inputs[i])):
                    raise ValueError(
                        f"static input {i} differs across the assembled "
                        "batch (per-batch side inputs must match bit-wise "
                        "to share one program call)")
            stacked.append(head)
            continue
        ax = axes[i]
        parts = []
        for r in requests:
            a = np.asarray(r.inputs[i])
            sax = seq_axes.get(i)
            if (sax is not None and seq_bucket is not None
                    and a.shape[sax] < seq_bucket):
                widths = [(0, 0)] * a.ndim
                widths[sax] = (0, seq_bucket - a.shape[sax])
                a = np.pad(a, widths)
            parts.append(a)
        cat = parts[0] if len(parts) == 1 else np.concatenate(parts, axis=ax)
        short = bucket - cat.shape[ax]
        if short > 0:
            widths = [(0, 0)] * cat.ndim
            widths[ax] = (0, short)
            cat = np.pad(cat, widths)
        stacked.append(cat)
    return stacked


def fetch_outputs(outputs: Sequence) -> List[np.ndarray]:
    """ONE device fetch per assembled batch (ROADMAP serving leftover):
    start every output leaf's D2H copy asynchronously first, then gather —
    the transfers overlap on the wire instead of serializing one blocking
    ``np.asarray`` round-trip per leaf. Counted once per call in the
    ``serving.d2h_fetches`` observability counter (vs once per LEAF under
    the old path), which is the proof the batch readback stays batched."""
    from ..observability.metrics import registry

    leaves = list(outputs)
    for leaf in leaves:
        start = getattr(leaf, "copy_to_host_async", None)
        if start is not None:
            start()
    arrays = [np.asarray(leaf) for leaf in leaves]
    registry.counter(
        "serving.d2h_fetches",
        "device→host readback rounds issued by the serving scheduler "
        "(one per assembled batch, NOT one per output leaf)").inc()
    return arrays


def scatter_outputs(outputs: Sequence[np.ndarray],
                    requests: Sequence[Request],
                    seq_bucket: Optional[int] = None,
                    out_seq_axes: Optional[Dict[int, int]] = None
                    ) -> List[List[np.ndarray]]:
    """Split each output's leading axis back into per-request row blocks
    (the padding tail is dropped). Output batch axis is 0 by the serving
    export contract; on two-axis exports the seq pad is sliced back to
    each request's real length (``Request.seq``) on exactly the axes the
    export's out_avals mark symbolic (``out_seq_axes``: {leaf_idx: axis}
    from ``_BatchProgram`` — never a runtime shape guess, so a static
    axis that happens to equal the rung survives untouched)."""
    per_request: List[List[np.ndarray]] = [[] for _ in requests]
    offsets = []
    pos = 0
    for r in requests:
        offsets.append(pos)
        pos += r.n
    for idx, out in enumerate(outputs):
        arr = np.asarray(out)
        ax = (out_seq_axes or {}).get(idx)
        for j, r in enumerate(requests):
            rows = arr[offsets[j]: offsets[j] + r.n]
            if (ax is not None and seq_bucket is not None
                    and r.seq is not None and r.seq < seq_bucket
                    and rows.shape[ax] == seq_bucket):
                rows = np.take(rows, range(r.seq), axis=ax)
            per_request[j].append(rows)
    return per_request


class Scheduler:
    """The serving tier's one executor thread: continuously drains the
    queue into bucketed batches and hands them to ``execute`` (the
    engine's predictor call). Crashes in ``execute`` fail only the batch
    that triggered them — the loop survives and keeps serving.

    ``retry`` (a ``reliability.RetryPolicy``) replays a transiently
    failed program call before the fault wall gives the batch up;
    ``breakers`` (a ``reliability.BreakerBoard``) is fed per-tenant
    success/failure so a tenant whose batches keep dying flips to
    ``degraded`` and sheds at admission."""

    def __init__(self, queue: RequestQueue, execute: Callable,
                 buckets, *, max_batch: Optional[int] = None,
                 linger_s: float = 0.0, on_batch: Optional[Callable] = None,
                 retry=None, breakers=None):
        self.queue = queue
        self.execute = execute           # (requests, bucket) -> None
        # a list, or a zero-arg callable for a LIVE ladder view (the engine
        # passes the batch program's, so a re-laddered predictor takes
        # effect at the very next assembly, no scheduler restart)
        self.buckets = buckets
        self.max_batch = max_batch
        self.linger_s = float(linger_s)
        self.on_batch = on_batch         # (n_samples, bucket, depth) tap
        self.retry = retry
        self.breakers = breakers
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()

    def _call(self, requests, bucket) -> None:
        if self.retry is not None:
            self.retry.run(self.execute, requests, bucket)
        else:
            self.execute(requests, bucket)

    def _record(self, requests, ok: bool) -> None:
        if self.breakers is None:
            return
        for tenant in {r.tenant for r in requests}:
            (self.breakers.record_success if ok
             else self.breakers.record_failure)(tenant)

    def start(self) -> "Scheduler":
        if self._thread is not None:
            raise RuntimeError("scheduler already started")
        self._thread = threading.Thread(target=self._loop,
                                        name="paddle-serving-scheduler",
                                        daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        from ..observability.anomaly import monitor
        from ..observability.memory import sampler
        from ..observability.tracing import tracer

        while True:
            # buckets/max pass through RAW: take_batch resolves a callable
            # ladder at assembly time, after its wait — no stale snapshot
            requests, bucket = self.queue.take_batch(
                self.buckets, self.max_batch, timeout=0.05,
                linger=self.linger_s)
            if not requests:
                if self.queue.closed and len(self.queue) == 0:
                    break
                continue
            now = time.perf_counter()
            for r in requests:
                r.t_dispatch = now
            n_samples = sum(r.n for r in requests)
            if self.on_batch is not None:
                self.on_batch(n_samples, bucket, self.queue.depth_samples())
            try:
                with tracer.span("serving.batch", track="serving.scheduler",
                                 bucket=bucket, n_samples=n_samples,
                                 n_requests=len(requests)):
                    self._call(requests, bucket)
                self._record(requests, ok=True)
            except BaseException as e:  # noqa: BLE001 — batch-scoped fault wall
                if monitor.enabled:
                    # serving-worker exception hook: capture the forensic
                    # window BEFORE the batch is failed away (the flight
                    # recorder is the only record once result() re-raises)
                    monitor.on_exception("serving.worker", e)
                self._record(requests, ok=False)
                for r in requests:
                    self.queue.admission.on_complete(r.tenant, r.n)
                    r._fail(e)
            # batch-boundary memory telemetry (sync-free by contract)
            sampler.maybe_sample("batch")
        self._stopped.set()

    def alive(self) -> bool:
        """Is the executor thread running? (the /healthz liveness probe)"""
        return self._thread is not None and self._thread.is_alive()

    def join(self, timeout: Optional[float] = None) -> bool:
        """Wait for the loop to exit (after ``queue.close()``)."""
        if self._thread is None:
            return True
        self._thread.join(timeout)
        return not self._thread.is_alive()


class DecodeScheduler:
    """The decode tier's one executor thread: true continuous batching
    over a KV slot pool. Every loop iteration is ONE program call —
    prefill OR decode — and between any two calls requests JOIN (queued →
    freed slot, priority order) and LEAVE (finished → slot released,
    future resolved). No full-batch re-assembly ever happens: running
    sequences keep their device-resident KV rows and simply appear in the
    next step's gathered lane set.

    Step policy: prefill-first. A waiting prompt joins the batch at the
    very next boundary (its compute also emits its first token), then
    decode steps serve every active lane at once. Prefill groups share
    one seq rung (anchored at the OLDEST waiting request, so rung
    grouping never starves FIFO order across rungs) and are capped at
    ``prefill_max_batch`` lanes.

    Crashes in a program call fail only the lanes that rode it — their
    slots release, the loop survives and keeps serving."""

    def __init__(self, queue: RequestQueue, programs, pool, *,
                 prefill_max_batch: int, eos_id: Optional[int] = None,
                 stats=None, on_step: Optional[Callable] = None,
                 retry=None, breakers=None):
        self.queue = queue
        self.programs = programs
        self.pool = pool
        self.prefill_max_batch = max(int(prefill_max_batch), 1)
        self.eos_id = eos_id
        self.stats = stats
        self.on_step = on_step           # (kind, lanes, rung, emitted) tap
        self.retry = retry               # replays a transient program call
        self.breakers = breakers         # per-tenant degraded accounting
        self._active: Dict[int, object] = {}    # slot -> DecodeRequest
        self._pending: List[object] = []        # slot held, prefill due
        self._step_lanes: List[object] = []     # lanes riding the current call
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "DecodeScheduler":
        if self._thread is not None:
            raise RuntimeError("decode scheduler already started")
        self._thread = threading.Thread(target=self._loop,
                                        name="paddle-serving-decode",
                                        daemon=True)
        self._thread.start()
        return self

    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def join(self, timeout: Optional[float] = None) -> bool:
        if self._thread is None:
            return True
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def active_count(self) -> int:
        """Sequences holding a slot right now (active, awaiting prefill,
        or riding the in-flight program call)."""
        seen = {id(r) for r in self._active.values()}
        seen.update(id(r) for r in self._pending)
        seen.update(id(r) for r in self._step_lanes)
        return len(seen)

    # ------------------------------------------------------------ the loop
    def _loop(self) -> None:
        from ..observability.anomaly import monitor
        from ..observability.memory import sampler

        while True:
            stepped = self._admit_and_step(monitor)
            if not stepped:
                if (self.queue.closed and len(self.queue) == 0
                        and not self._active and not self._pending):
                    break
            else:
                # step-boundary memory telemetry (sync-free by contract)
                sampler.maybe_sample("batch")
        self._stopped.set()

    def _admit_and_step(self, monitor) -> bool:
        """One scheduler beat: admit queued requests into free slots,
        then run one prefill-or-decode call. Returns False when fully
        idle (nothing admitted, nothing to step)."""
        free = self.pool.free_count()
        if free > 0:
            idle = not self._active and not self._pending
            taken = self.queue.take_slots(
                free, timeout=0.05 if idle else 0.0)
            now = time.perf_counter()
            for r in taken:
                r.slot = self.pool.alloc()
                r.seq_rung = self._seq_rung(r)
                r.t_dispatch = now
                self._pending.append(r)
        if self._pending:
            self._guarded(self._prefill_step, monitor)
            return True
        if self._active:
            self._guarded(self._decode_step, monitor)
            return True
        return False

    def _seq_rung(self, r) -> int:
        from ..jit.bucketing import bucket_for

        return bucket_for(int(r.prompt.size), self.programs.seq_ladder)

    def _guarded(self, step, monitor) -> None:
        """Batch-scoped fault wall: a crashed program call fails exactly
        the lanes it carried (``_step_lanes``, set by the step before its
        program call) and frees their slots; pending prefills and active
        lanes that did NOT ride the call keep serving. Transient program
        faults are absorbed by the retry policy INSIDE the step (around
        the program call only — admission/absorb bookkeeping never
        replays); only a give-up reaches this wall."""
        try:
            step()
        except BaseException as e:  # noqa: BLE001 — batch-scoped fault wall
            if monitor.enabled:
                monitor.on_exception("serving.decode_worker", e)
            involved, self._step_lanes = self._step_lanes, []
            if self.breakers is not None:
                for tenant in {r.tenant for r in involved}:
                    self.breakers.record_failure(tenant)
            for r in involved:
                self._free_lane(r)
                self.queue.admission.on_complete(r.tenant, r.n)
                r._fail(e)

    def _free_lane(self, r) -> None:
        """Detach one request from its KV residency — the single cleanup
        path the fault wall and retirement share (slot pools release the
        slot; paged pools release the block table's pages)."""
        if r.slot is not None:
            self._active.pop(r.slot, None)
            self.pool.release(r.slot)
            r.slot = None

    def _program_call(self, fn):
        """One prefill/decode program call through the fault point and
        (when armed) the retry policy — the only part of a step that is
        safe to replay: it reads pool/request state and returns fresh
        buffers, mutating nothing until ``commit``/``_absorb``.

        EXCEPT under buffer donation (accelerators donate the KV pool
        args so XLA aliases in place): a failed-after-dispatch attempt
        may already have invalidated ``pool.k``/``pool.v``, and a replay
        would read deleted arrays — worse, the pool would stay poisoned
        for every later step. Donating programs therefore skip retry and
        fail straight to the fault wall (lanes fail, slots release, the
        pool keeps its last committed buffers)."""
        from ..reliability.faults import fault_point

        def attempt():
            fault_point("serving.decode_step")
            return fn()

        donates = bool(getattr(self.programs, "_donate", ()))
        if self.retry is not None and not donates:
            return self.retry.run(attempt)
        return attempt()

    # ------------------------------------------------------------- steps
    def _prefill_step(self) -> None:
        from ..jit.bucketing import bucket_for
        from ..observability.tracing import tracer

        rung = self._pending[0].seq_rung  # oldest request anchors the rung
        group = [r for r in self._pending
                 if r.seq_rung == rung][: self.prefill_max_batch]
        for r in group:
            self._pending.remove(r)
        self._step_lanes = list(group)  # the fault wall's blast radius
        b_rung = bucket_for(len(group), self.programs.prefill_batch_rungs)
        pad = self.pool.pad_slot
        tokens = np.zeros((b_rung, rung), np.int32)
        lengths = np.ones(b_rung, np.int32)
        slots = np.full(b_rung, pad, np.int32)
        for i, r in enumerate(group):
            L = int(r.prompt.size)
            tokens[i, :L] = r.prompt
            lengths[i] = L
            slots[i] = r.slot
        t0 = time.perf_counter()
        with tracer.span("serving.decode", track="serving.scheduler",
                         kind="prefill", rung=(b_rung, rung),
                         lanes=len(group)):
            ck, cv, toks = self._program_call(lambda: self.programs.prefill(
                self.pool.k, self.pool.v, tokens, lengths, slots))
            self.pool.commit(ck, cv)
            toks = np.asarray(toks)
        self._absorb(group, toks, kind="prefill",
                     seconds=time.perf_counter() - t0, rung=(b_rung, rung))

    def _decode_step(self) -> None:
        from ..jit.bucketing import bucket_for
        from ..observability.tracing import tracer

        lanes = sorted(self._active.values(), key=lambda r: r.id)
        self._step_lanes = list(lanes)  # the fault wall's blast radius
        b_rung = bucket_for(len(lanes), self.programs.decode_rungs)
        pad = self.pool.pad_slot
        tokens = np.zeros(b_rung, np.int32)
        slots = np.full(b_rung, pad, np.int32)
        positions = np.zeros(b_rung, np.int32)
        for i, r in enumerate(lanes):
            tokens[i] = r.generated[-1]
            slots[i] = r.slot
            positions[i] = r.position
        t0 = time.perf_counter()
        with tracer.span("serving.decode", track="serving.scheduler",
                         kind="decode", rung=b_rung, lanes=len(lanes)):
            ck, cv, toks = self._program_call(lambda: self.programs.decode(
                self.pool.k, self.pool.v, tokens, slots, positions))
            self.pool.commit(ck, cv)
            toks = np.asarray(toks)
        self._absorb(lanes, toks, kind="decode",
                     seconds=time.perf_counter() - t0, rung=b_rung)

    def _absorb(self, lanes, toks, *, kind: str, seconds: float,
                rung) -> None:
        """Scatter one step's emitted tokens back to their requests,
        retire finished sequences (slot released, future resolved), keep
        the rest active for the next step."""
        self._step_lanes = []  # the call succeeded: nothing to fail
        if self.breakers is not None:
            for tenant in {r.tenant for r in lanes}:
                self.breakers.record_success(tenant)
        for i, r in enumerate(lanes):
            tok = int(toks[i])
            r.generated.append(tok)
            self.pool.lengths[r.slot] = r.position
            done = (len(r.generated) >= r.max_new_tokens
                    or (self.eos_id is not None and tok == self.eos_id)
                    or r.position >= self.pool.max_seq)
            if done:
                self._retire(r)
            else:
                self._active[r.slot] = r
        if self.stats is not None:
            self.stats.record_decode_step(kind, seconds, len(lanes),
                                          len(lanes))
            self.stats.record_slot_occupancy(self.pool.in_use(),
                                             self.pool.max_slots)
        if self.on_step is not None:
            self.on_step(kind, len(lanes), rung, len(lanes))

    def _retire(self, r) -> None:
        from ..observability.anomaly import monitor

        self._free_lane(r)
        self.queue.admission.on_complete(r.tenant, r.n)
        r._complete(np.asarray(r.generated, np.int32))
        if self.stats is not None:
            self.stats.record_request(r.t_enqueue, r.t_admit, r.t_dispatch,
                                      r.t_complete, r.n, tenant=r.tenant)
        if monitor.enabled:
            monitor.on_serving_request(
                r.t_complete - r.t_enqueue, r.t_dispatch - r.t_admit,
                tenant=r.tenant)

class PagedDecodeScheduler(DecodeScheduler):
    """The decode loop over a :class:`~.kv_cache.KVPagePool`.

    Same one-program-call-per-beat shape as the slot scheduler; what
    changes is the residency model:

    - admission is gated on LANES (the batch ladder's width) and on the
      page budget — a taken request allocates ``ceil(prompt/page_size)``
      pages up front, and an allocation failure (pool pressure or an
      injected ``kv.page_alloc`` fault) sheds exactly that request with
      ``AdmissionError(reason="kv_pages")``: its pages release, every
      other lane keeps serving.
    - before each decode step, lanes crossing a page boundary grow
      their block table by one page (:meth:`_ensure_pages`) through the
      same fault site and the same single-request shed path.
    - the program call carries the batch's block tables as ONE traced
      int32 array padded to the (batch × table) rung — page maps are
      data, so churn never retraces — plus the per-lane sampling
      arguments (temperature/top-k/top-p/PRNG key pair).
    - retirement releases the request's pages; the pool's utilization
      watermark (JX334) samples live tokens against in-use pages each
      step.
    """

    def __init__(self, queue: RequestQueue, programs, pool, *,
                 max_lanes: int, prefill_max_batch: int,
                 eos_id: Optional[int] = None, stats=None,
                 on_step: Optional[Callable] = None, retry=None,
                 breakers=None, speculate_k: int = 0,
                 spec_min_accept: Optional[float] = None):
        from ..base.flags import get_flag

        super().__init__(queue, programs, pool,
                         prefill_max_batch=prefill_max_batch,
                         eos_id=eos_id, stats=stats, on_step=on_step,
                         retry=retry, breakers=breakers)
        self.max_lanes = max(int(max_lanes), 1)
        self.max_seq = int(programs.max_seq)
        # self-speculation lane policy (ISSUE 20): a beat runs one
        # draft+verify round instead of one decode step whenever the
        # master toggle is on AND any lane still speculates — opted-out
        # lanes ride the round anyway (their committed tokens come from
        # the same full-model verify pass, so their stream is identical;
        # only the chunking differs)
        self.speculate_k = max(int(speculate_k), 0)
        self.spec_min_accept = float(
            get_flag("serving_spec_min_accept")
            if spec_min_accept is None else spec_min_accept)
        self.spec_enabled = self.speculate_k > 0
        # _active is keyed by request id here (no slot identity exists)
        self.shed_count = 0
        self._starved = set()  # lane ids waiting on a page (gate admission)

    # ---------------------------------------------------------- admission
    def _admit_and_step(self, monitor) -> bool:
        free = self.max_lanes - self.active_count()
        # starved active lanes get first claim on freed pages: admitting
        # new prompts while a running lane waits for growth would steal
        # its pages and starve it forever
        if free > 0 and self.pool.free_count() > 0 and not self._starved:
            idle = not self._active and not self._pending
            # page-budget admission gate: a request is taken only when
            # its PROMPT pages fit the free list right now — one that
            # merely has to wait for a retirement stays queued (FIFO,
            # never shed); growth past the prompt is overcommitted by
            # design and sheds only on true mid-flight exhaustion
            budget = [self.pool.free_count()]

            def fits(r):
                need = -(-int(r.prompt.size) // self.pool.page_size)
                if need > budget[0]:
                    return False
                budget[0] -= need
                return True

            taken = self.queue.take_slots(
                free, timeout=0.05 if idle else 0.0, budget_fn=fits)
            now = time.perf_counter()
            for r in taken:
                r.seq_rung = self._seq_rung(r)
                r.t_dispatch = now
                need = -(-int(r.prompt.size) // self.pool.page_size)
                try:
                    r.pages = self.pool.alloc(need)
                except Exception as e:  # noqa: BLE001 — shed, don't crash
                    self._shed(r, e)
                    continue
                self._pending.append(r)
        if self._pending:
            self._guarded(self._prefill_step, monitor)
            return True
        if self._active:
            self._guarded(self._decode_step, monitor)
            return True
        return False

    def _shed(self, r, cause) -> None:
        """Page-allocation failure sheds ONE request: its pages return
        to the pool (no leak — the JX333 audit stays clean), its future
        fails with ``AdmissionError(reason="kv_pages")``, and every
        other lane keeps decoding."""
        from .request_queue import AdmissionError

        self._free_lane(r)
        self.queue.admission.on_complete(r.tenant, r.n)
        if self.breakers is not None:
            self.breakers.record_failure(r.tenant)
        self.shed_count += 1
        try:
            from ..observability.metrics import registry

            registry.counter(
                "serving.kv_page_shed",
                "decode requests shed because a KV page allocation "
                "failed (pool pressure or injected kv.page_alloc "
                "fault)").inc()
        except Exception:
            pass
        r._fail(AdmissionError(
            "kv_pages",
            f"request {r.id} shed: KV page allocation failed ({cause})"))

    def _free_lane(self, r) -> None:
        self._active.pop(r.id, None)
        if r.pages:
            self.pool.release(r.pages)
            r.pages = []

    def _ensure_pages(self, lanes, lookahead: int = 0):
        """Grow each lane's block table to cover its next write position
        (plus ``lookahead`` speculative positions — a draft+verify round
        writes up to k positions past the committed one, and those rows
        must land in lane-owned pages; the uncommitted suffix rolls back
        via the free-list after acceptance). The lookahead is capped at
        the last legal position — overflow writes spill to the pad page
        inside the bounded programs, never into a live page.
        Returns the lanes ready to step. An INJECTED ``kv.page_alloc``
        fault sheds its lane (the chaos contract: prove the shed path).
        Natural exhaustion is gentler: the starved lane simply sits out
        this step — it keeps its pages and retries next beat, by which
        time a retirement has usually freed some. Only when EVERY active
        lane is starved (no retirement can ever come) does the deadlock
        breaker shed the youngest starved lane, freeing its pages for
        the older ones — guaranteed progress, FIFO-fair."""
        from ..reliability.faults import FaultInjection

        ready, starved = [], []
        for r in lanes:
            last = min(int(r.position) + lookahead, self.max_seq - 1)
            need = last // self.pool.page_size + 1
            try:
                while len(r.pages) < need:
                    r.pages.extend(self.pool.alloc(1))
            except FaultInjection as e:
                self._shed(r, e)
                continue
            except Exception:  # noqa: BLE001 — natural pressure: wait
                starved.append(r)
                continue
            ready.append(r)
        if not ready and starved and not self._pending:
            victim = max(starved, key=lambda r: r.id)
            starved.remove(victim)
            self._shed(victim, RuntimeError(
                "page pool deadlocked: every active lane needs a page "
                "and none can retire"))
        self._starved = {r.id for r in starved}
        return ready

    # -------------------------------------------------------------- steps
    def _sample_args(self, lanes, b_rung: int):
        """The per-lane sampling arguments of one program call. The PRNG
        key is ``[request_seed, generated_token_index]`` — a pure
        function of the request, never of batch composition, so sampled
        streams are deterministic per seed under any join/leave order.
        Pad lanes carry temperature 0 (the cheap greedy branch)."""
        temps = np.zeros(b_rung, np.float32)
        top_ks = np.zeros(b_rung, np.int32)
        top_ps = np.ones(b_rung, np.float32)
        rkeys = np.zeros((b_rung, 2), np.uint32)
        for i, r in enumerate(lanes):
            temps[i] = r.temperature
            top_ks[i] = r.top_k
            top_ps[i] = r.top_p
            rkeys[i] = (np.uint32(r.seed & 0xFFFFFFFF),
                        np.uint32(len(r.generated)))
        return temps, top_ks, top_ps, rkeys

    def _prefill_step(self) -> None:
        from ..jit.bucketing import bucket_for
        from ..observability.tracing import tracer

        rung = self._pending[0].seq_rung  # oldest request anchors the rung
        group = [r for r in self._pending
                 if r.seq_rung == rung][: self.prefill_max_batch]
        for r in group:
            self._pending.remove(r)
        self._step_lanes = list(group)  # the fault wall's blast radius
        b_rung = bucket_for(len(group), self.programs.prefill_batch_rungs)
        t_cols = self.programs._prefill_table_cols(rung)
        tokens = np.zeros((b_rung, rung), np.int32)
        lengths = np.ones(b_rung, np.int32)
        tables = np.zeros((b_rung, t_cols), np.int32)  # 0 = pad page
        for i, r in enumerate(group):
            L = int(r.prompt.size)
            tokens[i, :L] = r.prompt
            lengths[i] = L
            tables[i, :len(r.pages)] = r.pages
        t0 = time.perf_counter()
        with tracer.span("serving.decode", track="serving.scheduler",
                         kind="prefill", rung=(b_rung, rung),
                         lanes=len(group)):
            ck, cv, toks = self._program_call(lambda: self.programs.prefill(
                self.pool.k, self.pool.v, tokens, lengths, tables,
                *self._sample_args(group, b_rung)))
            self.pool.commit(ck, cv)
            toks = np.asarray(toks)
        self._absorb(group, toks, kind="prefill",
                     seconds=time.perf_counter() - t0, rung=(b_rung, rung))

    def _decode_step(self) -> None:
        from ..jit.bucketing import bucket_for
        from ..observability.tracing import tracer

        if (self.speculate_k > 0 and self.spec_enabled
                and any(r.spec_live for r in self._active.values())):
            self._spec_round()
            return
        lanes = sorted(self._active.values(), key=lambda r: r.id)
        lanes = self._ensure_pages(lanes)
        if not lanes:
            return
        self._step_lanes = list(lanes)  # the fault wall's blast radius
        b_rung = bucket_for(len(lanes), self.programs.decode_rungs)
        t_rung = bucket_for(max(len(r.pages) for r in lanes),
                            self.programs.table_rungs)
        tokens = np.zeros(b_rung, np.int32)
        tables = np.zeros((b_rung, t_rung), np.int32)  # 0 = pad page
        positions = np.zeros(b_rung, np.int32)
        for i, r in enumerate(lanes):
            tokens[i] = r.generated[-1]
            tables[i, :len(r.pages)] = r.pages
            positions[i] = r.position
        t0 = time.perf_counter()
        with tracer.span("serving.decode", track="serving.scheduler",
                         kind="decode", rung=(b_rung, t_rung),
                         lanes=len(lanes)):
            ck, cv, toks = self._program_call(lambda: self.programs.decode(
                self.pool.k, self.pool.v, tokens, tables, positions,
                *self._sample_args(lanes, b_rung)))
            self.pool.commit(ck, cv)
            toks = np.asarray(toks)
        self._absorb(lanes, toks, kind="decode",
                     seconds=time.perf_counter() - t0, rung=(b_rung, t_rung))

    def _spec_round(self) -> None:
        """One self-speculation round (ISSUE 20): ONE draft dispatch
        proposes k tokens per lane through the truncated-layer program,
        ONE verify dispatch scores all k+1 positions with the full
        model, then the host commits each lane's longest accepted prefix
        plus the verify pass's own next token — ≥ 1 token per round,
        up to k+1, always bitwise the tokens the plain decode loop
        would have produced. Pages grown for the speculative suffix
        roll back through the pool free-list in ``_absorb_spec``."""
        from ..jit.bucketing import bucket_for
        from ..observability.tracing import tracer

        k = self.speculate_k
        lanes = sorted(self._active.values(), key=lambda r: r.id)
        lanes = self._ensure_pages(lanes, lookahead=k)
        if not lanes:
            return
        self._step_lanes = list(lanes)  # the fault wall's blast radius
        b_rung = bucket_for(len(lanes), self.programs.decode_rungs)
        t_rung = bucket_for(max(len(r.pages) for r in lanes),
                            self.programs.table_rungs)
        tokens = np.zeros(b_rung, np.int32)
        tables = np.zeros((b_rung, t_rung), np.int32)  # 0 = pad page
        positions = np.zeros(b_rung, np.int32)
        for i, r in enumerate(lanes):
            tokens[i] = r.generated[-1]
            tables[i, :len(r.pages)] = r.pages
            positions[i] = r.position
        sample = self._sample_args(lanes, b_rung)
        with tracer.span("serving.decode", track="serving.scheduler",
                         kind="speculate", rung=(b_rung, t_rung),
                         lanes=len(lanes), k=k):
            t0 = time.perf_counter()
            ck, cv, drafts = self._program_call(lambda: self.programs.draft(
                self.pool.k, self.pool.v, tokens, tables, positions,
                *sample))
            self.pool.commit(ck, cv)
            drafts = np.asarray(drafts)       # [b_rung, k] proposals
            t_draft = time.perf_counter() - t0
            vin = np.zeros((b_rung, k + 1), np.int32)
            vin[:, 0] = tokens                # last committed token at p
            vin[:, 1:] = drafts               # proposals at p+1..p+k
            t1 = time.perf_counter()
            ck, cv, vtoks = self._program_call(lambda: self.programs.verify(
                self.pool.k, self.pool.v, vin, tables, positions, *sample))
            self.pool.commit(ck, cv)
            vtoks = np.asarray(vtoks)         # [b_rung, k+1] true tokens
            t_verify = time.perf_counter() - t1
        self._absorb_spec(lanes, drafts, vtoks, t_draft=t_draft,
                          t_verify=t_verify, rung=(b_rung, t_rung))

    def _absorb_spec(self, lanes, drafts, vtoks, *, t_draft: float,
                     t_verify: float, rung) -> None:
        """Acceptance + commit + rollback for one speculation round.
        Lane i's accepted prefix length m is the longest run of draft
        proposals the verify pass reproduced; verify tokens 0..m commit
        (the tokens the plain loop would emit, in order, under the same
        per-index sampling keys), stopping early at eos/max_new/max_seq
        exactly like ``_absorb``. Block-table pages past the new write
        position — grown for the speculative suffix — release back to
        the free-list: the rollback contract."""
        self._step_lanes = []  # the calls succeeded: nothing to fail
        if self.breakers is not None:
            for tenant in {r.tenant for r in lanes}:
                self.breakers.record_success(tenant)
        k = self.speculate_k
        proposed = accepted = committed = 0
        for i, r in enumerate(lanes):
            m = 0
            while m < k and int(drafts[i, m]) == int(vtoks[i, m]):
                m += 1
            r.spec_proposed += k
            r.spec_accepted += m
            proposed += k
            accepted += m
            done = False
            for j in range(m + 1):
                tok = int(vtoks[i, j])
                r.generated.append(tok)
                committed += 1
                done = (len(r.generated) >= r.max_new_tokens
                        or (self.eos_id is not None and tok == self.eos_id)
                        or r.position >= self.max_seq)
                if done:
                    break
            # rolling-acceptance lane policy: once a request has seen a
            # fair window (two full rounds' worth of proposals) and its
            # acceptance rate sits under the floor, drafting for it costs
            # more than it saves — the lane opts itself out; the batch
            # falls back to plain decode when every lane has
            if (r.spec_live and r.spec_proposed >= 2 * k
                    and r.spec_accepted
                    < self.spec_min_accept * r.spec_proposed):
                r.spec_live = False
            if done:
                self._retire(r)
            else:
                keep = int(r.position) // self.pool.page_size + 1
                if len(r.pages) > keep:  # speculative-suffix rollback
                    self.pool.release(r.pages[keep:])
                    del r.pages[keep:]
                self._active[r.id] = r
        live_tokens = sum(int(r.prompt.size) + len(r.generated)
                          for r in self._active.values())
        self.pool.note_utilization(live_tokens)
        if self.stats is not None:
            self.stats.record_decode_step("draft", t_draft, len(lanes), 0)
            self.stats.record_decode_step("verify", t_verify, len(lanes),
                                          committed)
            self.stats.record_spec_round(proposed, accepted, committed)
            self.stats.record_slot_occupancy(self.active_count(),
                                             self.max_lanes)
        try:
            from ..observability.metrics import registry

            registry.counter(
                "serving.spec_rounds",
                "self-speculation rounds (one draft + one verify "
                "dispatch each) run by the decode scheduler").inc()
            registry.counter(
                "serving.spec_tokens_proposed",
                "draft tokens proposed by self-speculation "
                "rounds").inc(proposed)
            registry.counter(
                "serving.spec_tokens_accepted",
                "draft tokens the full-model verify pass accepted "
                "(the rest rolled back)").inc(accepted)
        except Exception:
            pass
        if self.on_step is not None:
            self.on_step("speculate", len(lanes), rung, committed)

    def _absorb(self, lanes, toks, *, kind: str, seconds: float,
                rung) -> None:
        self._step_lanes = []  # the call succeeded: nothing to fail
        if self.breakers is not None:
            for tenant in {r.tenant for r in lanes}:
                self.breakers.record_success(tenant)
        for i, r in enumerate(lanes):
            tok = int(toks[i])
            r.generated.append(tok)
            done = (len(r.generated) >= r.max_new_tokens
                    or (self.eos_id is not None and tok == self.eos_id)
                    or r.position >= self.max_seq)
            if done:
                self._retire(r)
            else:
                self._active[r.id] = r
        live_tokens = sum(int(r.prompt.size) + len(r.generated)
                          for r in self._active.values())
        self.pool.note_utilization(live_tokens)
        if self.stats is not None:
            self.stats.record_decode_step(kind, seconds, len(lanes),
                                          len(lanes))
            self.stats.record_slot_occupancy(self.active_count(),
                                             self.max_lanes)
        if self.on_step is not None:
            self.on_step(kind, len(lanes), rung, len(lanes))
