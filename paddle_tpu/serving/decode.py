"""True continuous batching for GPT decode (serving phase 2).

The batch tier (``ServingEngine``) batches at assembly time: stack, run
once, scatter — so autoregressive decode would degenerate into
batch-per-token re-assembly, and one long request holds every
co-batched one hostage. This module serves decode the TPU-native way:

- :class:`DecodePrograms` — functional prefill and decode-step programs
  built straight from a ``models.gpt.GPTForCausalLM``'s parameters
  (plain jnp math, no Tensor dispatch), operating against the
  device-resident :class:`~.kv_cache.KVSlotPool`. One compiled
  specialization per bucket rung — ``(batch, seq)`` pairs for prefill,
  batch rungs for decode — all AOT-warmed through the persistent compile
  cache (a warm-disk replica restores the WHOLE program set with zero
  traces).
- :class:`DecodeEngine` — the serving front door
  (:class:`~.engine.EngineBase`): admission control with priority tiers
  and TTL, per-tenant stats lanes, telemetry egress, and a
  :class:`~.scheduler.DecodeScheduler` thread running the join/leave
  loop: requests enter a running batch the step after a slot frees and
  leave the step they finish — no full re-assembly, ever.

Decoding is greedy (argmax), which makes the bit-exactness contract
testable: the tokens a request receives are identical whether it decoded
alone or joined a full batch mid-flight (per-lane math touches only the
lane's own slot; masked pad columns contribute exact zeros).
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..base.flags import get_flag
from ..observability.locks import named_lock
from ..profiler.pipeline import serving_stats
from . import kv_cache as kvc
from .engine import EngineBase
from .kv_cache import KVPagePool, KVSlotPool
from .request_queue import DecodeRequest
from .scheduler import DecodeScheduler, PagedDecodeScheduler

__all__ = ["DecodeEngine", "DecodePrograms", "PagedDecodePrograms"]


def _extract_gpt(model):
    """The model's parameters as a plain pytree (shared device arrays,
    zero-copy) plus its config. Only the single-device GPT path serves
    here — parallel layouts keep their training-side machinery."""
    cfg = model.config
    if (cfg.tensor_parallel or cfg.pipeline_parallel
            or cfg.sequence_parallel or cfg.context_parallel):
        raise ValueError(
            "decode serving builds single-device programs; export the "
            "model unsharded (tensor/pipeline/sequence/context-parallel "
            "configs are training layouts)")

    def val(p):
        return p._value

    blocks = []
    for blk in model.gpt.h:
        a, m = blk.attn, blk.mlp
        blocks.append({
            "ln1_w": val(blk.ln_1.weight), "ln1_b": val(blk.ln_1.bias),
            "qkv_w": val(a.qkv_proj.weight), "qkv_b": val(a.qkv_proj.bias),
            "out_w": val(a.out_proj.weight), "out_b": val(a.out_proj.bias),
            "ln2_w": val(blk.ln_2.weight), "ln2_b": val(blk.ln_2.bias),
            "fc1_w": val(m.fc1.weight), "fc1_b": val(m.fc1.bias),
            "fc2_w": val(m.fc2.weight), "fc2_b": val(m.fc2.bias),
        })
    params = {
        "wte": val(model.gpt.embeddings.word_embeddings.weight),
        "wpe": val(model.gpt.embeddings.position_embeddings.weight),
        "lnf_w": val(model.gpt.ln_f.weight),
        "lnf_b": val(model.gpt.ln_f.bias),
        "blocks": blocks,
    }
    if not cfg.tie_word_embeddings:
        params["head_w"] = val(model.lm_head.weight)
    return params, cfg


def _ln(x, w, b, eps):
    import jax.numpy as jnp
    from jax import lax

    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) * lax.rsqrt(var + eps) * w + b


class DecodePrograms:
    """The decode tier's compiled program set over one GPT's weights.

    Two program families, each specialized per bucket rung:

    - ``prefill``: ``[B, S]`` prompt tokens → per-layer K/V written into
      the pool's slots (``lax.dynamic_update_slice`` on the B=1
      interactive path, one all-layer scatter otherwise) + the first
      generated token per lane (greedy, from each lane's last real
      position);
    - ``decode``: ``[B]`` last tokens → one attention step per lane over
      its own slot's cached rows (cols ≤ position), K/V appended at the
      lane's write position, next token per lane.

    Both take and return the pool buffers functionally; KV args are
    donated on accelerators so XLA aliases output onto input — zero
    per-step reallocation. ``traces`` ticks inside the traced bodies
    (the zero-retrace proof); warmup arms every rung through the
    persistent compile cache (``restored`` rungs paid zero traces).
    """

    def __init__(self, model, pool: KVSlotPool, *,
                 seq_ladder: Sequence[int],
                 prefill_batch_rungs: Sequence[int],
                 decode_rungs: Sequence[int]):
        import jax

        params, cfg = _extract_gpt(model)
        self.params = jax.device_put(params)
        self.pool = pool
        self.seq_ladder = sorted(int(s) for s in seq_ladder)
        self.prefill_batch_rungs = sorted(int(b) for b in prefill_batch_rungs)
        self.decode_rungs = sorted(int(b) for b in decode_rungs)
        self._heads = cfg.num_attention_heads
        self._head_dim = cfg.head_dim
        self._hidden = cfg.hidden_size
        self._max_pos = int(cfg.max_position_embeddings)
        self._eps = float(cfg.layer_norm_epsilon)
        self._tied = bool(cfg.tie_word_embeddings)
        self._scale = 1.0 / math.sqrt(cfg.head_dim)
        self.traces = 0
        self.warmed: List[tuple] = []
        self.restored: List[tuple] = []
        self._aot: Dict[tuple, object] = {}
        self._lock = named_lock("serving.decode.programs")
        try:
            backend = jax.devices()[0].platform
        except Exception:
            backend = "cpu"
        # serving-step donation idiom: the pool buffers are dead after the
        # call (the scheduler commits the outputs), so donate them and XLA
        # updates the KV cache in place. CPU ignores donation — skip the
        # warning noise there; the footprint proof holds either way
        # (commit() pins shape/dtype, device_bytes stays constant).
        self._donate = (1, 2) if backend != "cpu" else ()
        # executables are parameter-VALUE independent (params are runtime
        # args), so the cache key needs only the structural identity —
        # which includes every compile-time CONSTANT baked into the traced
        # bodies (eps is one; miss it and two models differing only in
        # layer_norm_epsilon would share executables)
        self._model_key = (
            int(cfg.vocab_size), int(cfg.hidden_size),
            int(cfg.num_hidden_layers), int(cfg.num_attention_heads),
            int(cfg.max_position_embeddings), self._tied, self._eps,
            tuple(int(d) for d in pool.k.shape), str(pool.k.dtype),
            tuple(self._donate))
        self._jit_prefill = jax.jit(self._prefill_fn,
                                    donate_argnums=self._donate)
        self._jit_decode = jax.jit(self._decode_fn,
                                   donate_argnums=self._donate)

    # ------------------------------------------------------------ programs
    def _logits_head(self, params, x):
        import jax.numpy as jnp

        w = params["wte"].T if self._tied else params["head_w"]
        return x @ w

    def _prefill_trunk(self, params, tokens, lengths):
        """The prefill transformer body shared by the slot and paged
        program families: ``[B, S]`` prompt tokens → per-lane head
        logits at the last real position plus the stacked per-layer K/V
        rows ``[layers, B, S, heads, head_dim]``. Pure function of the
        prompt — cache writing is the caller's (pool-specific) job."""
        import jax
        import jax.numpy as jnp

        self.traces += 1  # runs under trace only: the recompile proof
        B, S = tokens.shape
        eps = self._eps
        x = params["wte"][tokens] + params["wpe"][:S][None, :, :]
        ks, vs = [], []
        for blk in params["blocks"]:
            h = _ln(x, blk["ln1_w"], blk["ln1_b"], eps)
            qkv = (h @ blk["qkv_w"] + blk["qkv_b"]).reshape(
                B, S, self._heads, 3, self._head_dim)
            q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
            ks.append(k)
            vs.append(v)
            logits = jnp.einsum("bshd,bthd->bhst", q, k) * self._scale
            causal = jnp.tril(jnp.ones((S, S), bool))
            logits = jnp.where(causal[None, None], logits, -1e30)
            probs = jax.nn.softmax(logits.astype(jnp.float32),
                                   axis=-1).astype(x.dtype)
            att = jnp.einsum("bhst,bthd->bshd", probs, v).reshape(
                B, S, self._hidden)
            x = x + att @ blk["out_w"] + blk["out_b"]
            h2 = _ln(x, blk["ln2_w"], blk["ln2_b"], eps)
            x = x + jax.nn.gelu(h2 @ blk["fc1_w"] + blk["fc1_b"],
                                approximate=True) @ blk["fc2_w"] + blk["fc2_b"]
        # each lane's next token comes from its LAST REAL position (rows
        # past the prompt are garbage, never attended by real rows)
        idx = (lengths - 1).astype(jnp.int32)
        x_last = jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0]
        hfin = _ln(x_last, params["lnf_w"], params["lnf_b"], eps)
        head = self._logits_head(params, hfin)
        krows = jnp.stack(ks)  # [layers, B, S, heads, head_dim]
        vrows = jnp.stack(vs)
        return head, krows, vrows

    def _prefill_fn(self, params, ck, cv, tokens, lengths, slot_ids):
        import jax.numpy as jnp

        B = tokens.shape[0]
        head, krows, vrows = self._prefill_trunk(params, tokens, lengths)
        next_tok = jnp.argmax(head, axis=-1).astype(jnp.int32)
        if B == 1:
            # interactive path: one dynamic_update_slice per buffer
            ck = kvc.write_prompt(ck, slot_ids[0], krows[:, 0])
            cv = kvc.write_prompt(cv, slot_ids[0], vrows[:, 0])
        else:
            ck = kvc.write_prompt_batch(ck, slot_ids, krows)
            cv = kvc.write_prompt_batch(cv, slot_ids, vrows)
        return ck, cv, next_tok

    def _decode_fn(self, params, ck, cv, tokens, slot_ids, positions):
        import jax
        import jax.numpy as jnp

        self.traces += 1
        B = tokens.shape[0]
        eps = self._eps
        x = params["wte"][tokens] + params["wpe"][positions]
        col = jnp.arange(self.pool.max_seq)
        for li, blk in enumerate(params["blocks"]):
            h = _ln(x, blk["ln1_w"], blk["ln1_b"], eps)
            qkv = (h @ blk["qkv_w"] + blk["qkv_b"]).reshape(
                B, self._heads, 3, self._head_dim)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            # write this token's K/V at (layer, slot, position), then
            # attend over the slot's rows 0..position inclusive
            ck = kvc.append_token(ck, li, slot_ids, positions, k)
            cv = kvc.append_token(cv, li, slot_ids, positions, v)
            keys = ck[li, slot_ids]    # [B, max_seq, heads, head_dim]
            vals = cv[li, slot_ids]
            logits = jnp.einsum("bhd,bthd->bht", q, keys) * self._scale
            mask = col[None, None, :] <= positions[:, None, None]
            logits = jnp.where(mask, logits, -1e30)
            probs = jax.nn.softmax(logits.astype(jnp.float32),
                                   axis=-1).astype(x.dtype)
            att = jnp.einsum("bht,bthd->bhd", probs, vals).reshape(
                B, self._hidden)
            x = x + att @ blk["out_w"] + blk["out_b"]
            h2 = _ln(x, blk["ln2_w"], blk["ln2_b"], eps)
            x = x + jax.nn.gelu(h2 @ blk["fc1_w"] + blk["fc1_b"],
                                approximate=True) @ blk["fc2_w"] + blk["fc2_b"]
        hfin = _ln(x, params["lnf_w"], params["lnf_b"], eps)
        next_tok = jnp.argmax(self._logits_head(params, hfin),
                              axis=-1).astype(jnp.int32)
        return ck, cv, next_tok

    # ------------------------------------------------------------- rungs
    @property
    def rungs(self) -> List[tuple]:
        """Every specialization warmup arms: ``("decode", b)`` per batch
        rung plus ``("prefill", b, s)`` over the (batch x seq) grid."""
        out = [("decode", b) for b in self.decode_rungs]
        out += [("prefill", b, s) for b in self.prefill_batch_rungs
                for s in self.seq_ladder]
        return out

    def _zero_args(self, key):
        pad = self.pool.pad_slot
        if key[0] == "decode":
            b = key[1]
            return (np.zeros(b, np.int32), np.full(b, pad, np.int32),
                    np.zeros(b, np.int32))
        _, b, s = key
        return (np.zeros((b, s), np.int32), np.ones(b, np.int32),
                np.full(b, pad, np.int32))

    def _jitted(self, key):
        return self._jit_decode if key[0] == "decode" else self._jit_prefill

    def warmup(self) -> List[tuple]:
        """Arm every rung: restored from the persistent compile cache
        (zero traces) or AOT compile-and-publish (one trace — the same
        one an in-memory warm call pays). Idempotent per rung."""
        with self._lock:
            for key in self.rungs:
                if key in self.warmed:
                    continue
                self._warm(key)
                self.warmed.append(key)
        return list(self.warmed)

    def _digest(self, key):
        from .. import compile_cache as cc

        return cc.derive_digest(
            "serving.decode", ("serving.decode", self._model_key, key))

    def _warm(self, key) -> None:
        from .. import compile_cache as cc

        args = self._zero_args(key)
        if cc.enabled():
            digest = self._digest(key)
            compiled = cc.load_executable(
                digest, site=f"serving.decode:{key[0]}{key[1:]}")
            if compiled is not None:
                self._aot[key] = compiled
                self.restored.append(key)
                return
            lowered = self._jitted(key).lower(
                self._call_params(key), self.pool.k, self.pool.v,
                *args)  # traces += 1
            compiled = lowered.compile()
            cc.store_executable(
                digest, compiled,
                key_meta={"site": "serving.decode", "rung": repr(key)})
            self._aot[key] = compiled
            return
        # in-memory warm: one traced call against the pad slot (harmless
        # writes land in the trash slot); outputs are committed so a
        # donation backend keeps the pool buffers alive
        k, v, _ = self._jitted(key)(self._call_params(key), self.pool.k,
                                    self.pool.v, *args)
        self.pool.commit(k, v)

    def _call_params(self, key) -> dict:
        """The parameter pytree rung ``key`` runs against. The base
        families serve everything from ``self.params``; the paged family
        routes its draft rungs through the truncated-layer view."""
        return self.params

    def _flip_params(self, staged) -> None:
        """The one reference assignment a hot swap commits (caller holds
        the programs lock). Subclasses with DERIVED parameter views — the
        paged family's truncated-layer draft tier — extend this so every
        tier flips under the same lock acquisition: a draft program can
        never observe pre-swap weights once ``swap_params`` returns."""
        self.params = staged

    def swap_params(self, model) -> int:
        """Zero-downtime weight hot-swap for the decode tier: re-extract
        ``model``'s parameters (zero-copy of its live device arrays) and
        flip the program-set's parameter reference. The model must share
        the serving model's structural identity (config + KV layout) —
        validated leaf by leaf (structure/shape/dtype), so every warmed
        prefill/decode executable keeps replaying: ``traces`` cannot
        move across a swap.

        The flip is one reference assignment; each prefill/decode call
        reads ``self.params`` once at its start, so the swap lands
        exactly BETWEEN decode steps — running lanes keep their KV slots
        and simply attend with the new weights from the next step on.
        Returns the number of parameter leaves swapped."""
        import jax

        new_params, _cfg = _extract_gpt(model)
        old_leaves, old_def = jax.tree_util.tree_flatten(self.params)
        new_leaves, new_def = jax.tree_util.tree_flatten(new_params)
        if old_def != new_def:
            raise ValueError(
                "swap_params: the new model's parameter tree differs "
                "structurally from the serving one — a decode hot swap "
                "must carry the same architecture")
        for i, (o, n) in enumerate(zip(old_leaves, new_leaves)):
            if tuple(o.shape) != tuple(n.shape) or o.dtype != n.dtype:
                raise ValueError(
                    f"swap_params: leaf {i} is {tuple(n.shape)}/{n.dtype}, "
                    f"decode executables expect {tuple(o.shape)}/{o.dtype}")
        # stage the transfer BEFORE taking the lock (CX1002: a device
        # transfer under a held lock serializes every other swapper
        # behind device latency); the flip itself is one reference
        # assignment under the lock
        staged = jax.device_put(new_params)
        with self._lock:
            self._flip_params(staged)
        return len(new_leaves)

    # -------------------------------------------------------------- calls
    def prefill(self, ck, cv, tokens, lengths, slot_ids):
        key = ("prefill", int(tokens.shape[0]), int(tokens.shape[1]))
        ex = self._aot.get(key)
        if ex is not None:
            return ex(self.params, ck, cv, tokens, lengths, slot_ids)
        return self._jit_prefill(self.params, ck, cv, tokens, lengths,
                                 slot_ids)

    def decode(self, ck, cv, tokens, slot_ids, positions):
        key = ("decode", int(tokens.shape[0]))
        ex = self._aot.get(key)
        if ex is not None:
            return ex(self.params, ck, cv, tokens, slot_ids, positions)
        return self._jit_decode(self.params, ck, cv, tokens, slot_ids,
                                positions)


class PagedDecodePrograms(DecodePrograms):
    """The decode program set over a :class:`~.kv_cache.KVPagePool`.

    Same warmup/compile-cache/donation/hot-swap machinery as the slot
    family; the cache layout and the rung key change:

    - K/V is indexed through a per-request *block table* — a traced
      ``[B, T]`` int32 array naming each lane's pages in order. The
      table is DATA: one compiled program serves any page map, so page
      churn (alloc on growth, reclaim on retire, reuse by the next
      request) costs zero retraces.
    - decode rungs key on (batch rung × table rung): ``("decode", b,
      t)`` where ``t`` walks :func:`~..jit.bucketing.table_ladder` —
      a short context pays a short gather, a 4k one a long gather, and
      both replay warm.
    - sampling rides as traced per-lane arguments (temperature / top-k
      / top-p / raw uint32 PRNG key pair): sampling is data too, never
      a retrace. ``temp == 0`` lanes take the argmax branch bit-exactly
      — the greedy audit mode the slot oracle is compared against.

    With ``speculate_k > 0`` two more program families join the same
    (batch rung × table rung) grid — self-speculative decoding over the
    page pool (ISSUE 20):

    - ``draft``: ``speculate_k`` UNROLLED decode steps through a
      truncated-layer prefix of the SAME weights (``draft_layers``
      blocks, shared zero-copy — no second model, no extra weight
      memory). One dispatch proposes k tokens, writing the draft
      layers' K/V along the way.
    - ``verify``: one batched FULL-model pass over all ``k + 1``
      positions (last committed token + the k proposals), rewriting
      every layer's K/V at those positions with true-token inputs and
      choosing a token at each position with the request's canonical
      ``[seed, token_index]`` key. Committed tokens always come from
      the verify pass, so both the greedy and the sampled stream equal
      the non-speculative stream token for token; the draft only
      decides HOW MANY commit per round.

    Both families bake ``k`` and ``draft_layers`` into ``_model_key``
    (compile-time constants) and warm with everything else, so flipping
    speculation on or off mid-flight never traces.
    """

    def __init__(self, model, pool: KVPagePool, *,
                 seq_ladder: Sequence[int],
                 prefill_batch_rungs: Sequence[int],
                 decode_rungs: Sequence[int],
                 max_seq: int,
                 speculate_k: int = 0,
                 draft_layers: Optional[int] = None):
        import jax

        from ..jit.bucketing import table_ladder

        self.max_seq = int(max_seq)
        self.speculate_k = max(int(speculate_k), 0)
        n_layers = int(model.config.num_hidden_layers)
        dl = int(get_flag("serving_spec_draft_layers")
                 if draft_layers is None else draft_layers)
        # clamp, never reject: a 1-layer demo model drafts with its one
        # block — a degenerate full-depth draft that accepts 100% and
        # still wins on dispatch count (2 calls commit up to k+1 tokens)
        self.draft_layers = max(1, min(dl, n_layers))
        # super() derives _model_key from pool.k.shape (already the page
        # layout) and jits self._prefill_fn/_decode_fn — the overrides
        # below, bound through normal method resolution
        super().__init__(model, pool,
                         seq_ladder=seq_ladder,
                         prefill_batch_rungs=prefill_batch_rungs,
                         decode_rungs=decode_rungs)
        self.table_rungs = table_ladder(self.max_seq, pool.page_size)
        # disambiguate from a slot pool that happens to share shapes,
        # and cover the table ladder (it shapes the warmed rung set)
        # plus the speculation constants unrolled into draft/verify
        self._model_key = self._model_key + (
            "paged", int(pool.page_size), tuple(self.table_rungs),
            "spec", self.speculate_k, self.draft_layers)
        self.draft_params = (self._draft_view(self.params)
                             if self.speculate_k else None)
        if self.speculate_k:
            self._jit_draft = jax.jit(self._draft_fn,
                                      donate_argnums=self._donate)
            self._jit_verify = jax.jit(self._verify_fn,
                                       donate_argnums=self._donate)

    # -------------------------------------------------------- draft params
    def _draft_view(self, params: dict) -> dict:
        """The draft tier's parameter view: the first ``draft_layers``
        transformer blocks plus the shared embedding / final-LN / head
        leaves. Every leaf IS the full tree's leaf (no copy, no device
        memory) — truncation drops the TOP of the stack, so the draft's
        per-layer K/V is bitwise what the full model computes for those
        layers, and verify can overwrite it in place."""
        view = {k: v for k, v in params.items() if k != "blocks"}
        view["blocks"] = list(params["blocks"][:self.draft_layers])
        return view

    def _flip_params(self, staged) -> None:
        # one lock acquisition flips BOTH tiers: the draft view is
        # re-derived from the staged tree, so a mid-speculation hot swap
        # can never leave the draft proposing with stale weights
        super()._flip_params(staged)
        if self.speculate_k:
            self.draft_params = self._draft_view(staged)

    def _call_params(self, key) -> dict:
        return self.draft_params if key[0] == "draft" else self.params

    # ----------------------------------------------------------- sampling
    def _choose_tokens(self, head, temps, top_ks, top_ps, rkeys):
        """Per-lane next-token choice from head logits ``[B, V]``.

        All sampling parameters are traced data. A lane with ``temp ==
        0`` returns plain argmax — the SAME op the slot programs run,
        so greedy mode stays bit-exact. Otherwise: temperature-scale,
        keep the top-k / top-p prefix of the descending sort, and draw
        with ``jax.random.categorical`` from the lane's own raw uint32
        key pair — the key is ``[request_seed, token_index]`` on the
        host, so a request's stream never depends on batch composition.
        """
        import jax
        import jax.numpy as jnp

        greedy = jnp.argmax(head, axis=-1).astype(jnp.int32)
        V = head.shape[-1]

        def lane(lg, temp, tk, tp, key):
            lg = lg.astype(jnp.float32)
            scaled = lg / jnp.where(temp > 0, temp, 1.0)
            srt = jnp.sort(scaled)[::-1]  # descending
            rank = jnp.arange(V)
            k_eff = jnp.clip(jnp.where(tk > 0, tk, V), 1, V)
            probs = jax.nn.softmax(srt)
            p_eff = jnp.where((tp > 0.0) & (tp < 1.0), tp, 1.0)
            # both filters are prefixes of the sort: kept set = prefix,
            # cutoff = the smallest kept value (rank 0 is always kept)
            keep = (rank < k_eff) & (jnp.cumsum(probs) - probs < p_eff)
            cutoff = jnp.min(jnp.where(keep, srt, jnp.inf))
            filtered = jnp.where(scaled >= cutoff, scaled, -jnp.inf)
            return jax.random.categorical(key, filtered).astype(jnp.int32)

        sampled = jax.vmap(lane)(head, temps, top_ks, top_ps, rkeys)
        return jnp.where(temps > 0, sampled, greedy)

    # ----------------------------------------------------------- programs
    def _prefill_fn(self, params, ck, cv, tokens, lengths, tables,
                    temps, top_ks, top_ps, rkeys):
        import jax.numpy as jnp

        head, krows, vrows = self._prefill_trunk(params, tokens, lengths)
        next_tok = self._choose_tokens(head, temps, top_ks, top_ps, rkeys)
        # pad the prompt rows up to whole pages; the surplus rows route
        # through table entries past the lane's real pages (pad page 0)
        S = krows.shape[2]
        want = tables.shape[1] * self.pool.page_size
        if want > S:
            padw = ((0, 0), (0, 0), (0, want - S), (0, 0), (0, 0))
            krows = jnp.pad(krows, padw)
            vrows = jnp.pad(vrows, padw)
        ck = kvc.write_prompt_pages(ck, tables, krows)
        cv = kvc.write_prompt_pages(cv, tables, vrows)
        return ck, cv, next_tok

    def _paged_step_trunk(self, params, ck, cv, tokens, tables, positions,
                          *, bounded=False):
        """One paged decode step's transformer body: ``[B]`` tokens at
        ``[B]`` positions → (ck, cv, head logits ``[B, V]``), K/V
        appended through the block tables. Shared verbatim by the plain
        decode program (``bounded=False`` — the PR 18 trace, byte for
        byte) and the draft program's unrolled steps.

        ``bounded=True`` adds the speculative overflow clamps: a lane
        whose draft position runs past ``max_seq`` (or the model's
        position table) must not corrupt a LIVE page through index
        clamping, so out-of-range writes are redirected to the pool's
        pad page 0 and the wpe lookup is clamped. Such a lane's
        proposals are garbage, but its verify tokens past the boundary
        are never committed — the scheduler retires it at ``max_seq``.
        """
        import jax
        import jax.numpy as jnp

        B, T = tables.shape
        ps = self.pool.page_size
        eps = self._eps
        if bounded:
            x = (params["wte"][tokens]
                 + params["wpe"][jnp.minimum(positions, self._max_pos - 1)])
        else:
            x = params["wte"][tokens] + params["wpe"][positions]
        # the traced table maps token position -> page: column j of the
        # gathered view IS position j, so the slot program's mask and
        # softmax carry over unchanged (bit-exact greedy contract)
        col = jnp.arange(T * ps)
        page_idx = (positions // ps).astype(jnp.int32)
        if bounded:
            page_idx = jnp.minimum(page_idx, T - 1)
        pages = jnp.take_along_axis(tables, page_idx[:, None], axis=1)[:, 0]
        if bounded:
            pages = jnp.where(positions < self.max_seq, pages, 0)
        offsets = (positions % ps).astype(jnp.int32)
        for li, blk in enumerate(params["blocks"]):
            h = _ln(x, blk["ln1_w"], blk["ln1_b"], eps)
            qkv = (h @ blk["qkv_w"] + blk["qkv_b"]).reshape(
                B, self._heads, 3, self._head_dim)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            ck = kvc.append_token_paged(ck, li, pages, offsets, k)
            cv = kvc.append_token_paged(cv, li, pages, offsets, v)
            keys = kvc.gather_pages(ck, li, tables)  # [B, T*ps, h, d]
            vals = kvc.gather_pages(cv, li, tables)
            logits = jnp.einsum("bhd,bthd->bht", q, keys) * self._scale
            mask = col[None, None, :] <= positions[:, None, None]
            logits = jnp.where(mask, logits, -1e30)
            probs = jax.nn.softmax(logits.astype(jnp.float32),
                                   axis=-1).astype(x.dtype)
            att = jnp.einsum("bht,bthd->bhd", probs, vals).reshape(
                B, self._hidden)
            x = x + att @ blk["out_w"] + blk["out_b"]
            h2 = _ln(x, blk["ln2_w"], blk["ln2_b"], eps)
            x = x + jax.nn.gelu(h2 @ blk["fc1_w"] + blk["fc1_b"],
                                approximate=True) @ blk["fc2_w"] + blk["fc2_b"]
        hfin = _ln(x, params["lnf_w"], params["lnf_b"], eps)
        return ck, cv, self._logits_head(params, hfin)

    def _decode_fn(self, params, ck, cv, tokens, tables, positions,
                   temps, top_ks, top_ps, rkeys):
        self.traces += 1
        ck, cv, head = self._paged_step_trunk(params, ck, cv, tokens,
                                              tables, positions)
        next_tok = self._choose_tokens(head, temps, top_ks, top_ps, rkeys)
        return ck, cv, next_tok

    @staticmethod
    def _shift_keys(rkeys, j):
        """The request's canonical sampling key for the j-th token of a
        speculation round: host keys are ``[seed, len(generated)]`` at
        round start, so offsetting the counter lane by j reproduces
        EXACTLY the key the non-speculative stream would use for that
        token index — per-seed determinism survives speculation."""
        import jax.numpy as jnp

        if j == 0:
            return rkeys
        return rkeys + jnp.asarray([0, j], jnp.uint32)[None, :]

    def _draft_fn(self, params, ck, cv, tokens, tables, positions,
                  temps, top_ks, top_ps, rkeys):
        """``speculate_k`` decode steps through the truncated-layer
        params, unrolled into ONE program — a speculation round costs
        two dispatches (draft + verify) instead of k+1. Writes the
        draft layers' K/V (verify rewrites the accepted positions with
        full-model values anyway) and returns the proposals ``[B, k]``.
        """
        import jax.numpy as jnp

        self.traces += 1
        tok, pos, drafts = tokens, positions, []
        for j in range(self.speculate_k):
            ck, cv, head = self._paged_step_trunk(
                params, ck, cv, tok, tables, pos, bounded=True)
            tok = self._choose_tokens(head, temps, top_ks, top_ps,
                                      self._shift_keys(rkeys, j))
            drafts.append(tok)
            pos = pos + 1
        return ck, cv, jnp.stack(drafts, axis=1)

    def _verify_fn(self, params, ck, cv, tokens, tables, positions,
                   temps, top_ks, top_ps, rkeys):
        """One batched full-model pass over all ``k + 1`` positions:
        ``tokens[:, 0]`` is each lane's last committed token at its
        write position p, ``tokens[:, 1:]`` the draft proposals at
        p+1..p+k. Every layer's K/V is appended at ALL k+1 positions
        before the gather, masked causally per query column, and a
        token is chosen at each position with the canonical shifted
        key — the j-th verify token is bitwise the token the plain
        decode program would emit after committing tokens 0..j-1, which
        is the whole bit-exactness contract."""
        import jax
        import jax.numpy as jnp

        self.traces += 1
        B, K1 = tokens.shape
        T = tables.shape[1]
        ps = self.pool.page_size
        eps = self._eps
        pos = positions[:, None] + jnp.arange(K1, dtype=jnp.int32)[None, :]
        x = (params["wte"][tokens]
             + params["wpe"][jnp.minimum(pos, self._max_pos - 1)])
        col = jnp.arange(T * ps)
        page_idx = jnp.minimum((pos // ps).astype(jnp.int32), T - 1)
        pages = jnp.take_along_axis(tables, page_idx, axis=1)
        pages = jnp.where(pos < self.max_seq, pages, 0)  # pad-page spill
        offsets = (pos % ps).astype(jnp.int32)
        # [B, heads, K1 queries, T*ps cols]: query j sees cols <= p+j
        mask = col[None, None, None, :] <= pos[:, None, :, None]
        for li, blk in enumerate(params["blocks"]):
            h = _ln(x, blk["ln1_w"], blk["ln1_b"], eps)
            qkv = (h @ blk["qkv_w"] + blk["qkv_b"]).reshape(
                B, K1, self._heads, 3, self._head_dim)
            q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
            ck = kvc.append_token_paged(ck, li, pages, offsets, k)
            cv = kvc.append_token_paged(cv, li, pages, offsets, v)
            keys = kvc.gather_pages(ck, li, tables)  # [B, T*ps, h, d]
            vals = kvc.gather_pages(cv, li, tables)
            logits = jnp.einsum("bshd,bthd->bhst", q, keys) * self._scale
            logits = jnp.where(mask, logits, -1e30)
            probs = jax.nn.softmax(logits.astype(jnp.float32),
                                   axis=-1).astype(x.dtype)
            att = jnp.einsum("bhst,bthd->bshd", probs, vals).reshape(
                B, K1, self._hidden)
            x = x + att @ blk["out_w"] + blk["out_b"]
            h2 = _ln(x, blk["ln2_w"], blk["ln2_b"], eps)
            x = x + jax.nn.gelu(h2 @ blk["fc1_w"] + blk["fc1_b"],
                                approximate=True) @ blk["fc2_w"] + blk["fc2_b"]
        hfin = _ln(x, params["lnf_w"], params["lnf_b"], eps)
        head = self._logits_head(params, hfin)  # [B, K1, V]
        vtoks = [self._choose_tokens(head[:, j], temps, top_ks, top_ps,
                                     self._shift_keys(rkeys, j))
                 for j in range(K1)]
        return ck, cv, jnp.stack(vtoks, axis=1)

    # -------------------------------------------------------------- rungs
    def _prefill_table_cols(self, seq_rung: int) -> int:
        return -(-int(seq_rung) // self.pool.page_size)

    @property
    def rungs(self) -> List[tuple]:
        """``("decode", b, t)`` over (batch × table) rungs plus
        ``("prefill", b, s)`` over the (batch × seq) grid — the prefill
        table width is a function of the seq rung, not a third axis.
        With speculation enabled, ``("draft", b, t)`` and ``("verify",
        b, t)`` join over the SAME (batch × table) grid — every batch
        shape a plain decode step can take, a speculation round can
        take too, so toggling speculation mid-flight never meets a cold
        rung (JX335 audits the parity)."""
        out = [("decode", b, t) for b in self.decode_rungs
               for t in self.table_rungs]
        if self.speculate_k:
            out += [("draft", b, t) for b in self.decode_rungs
                    for t in self.table_rungs]
            out += [("verify", b, t) for b in self.decode_rungs
                    for t in self.table_rungs]
        out += [("prefill", b, s) for b in self.prefill_batch_rungs
                for s in self.seq_ladder]
        return out

    def _zero_args(self, key):
        def sample_args(b):
            return (np.zeros(b, np.float32), np.zeros(b, np.int32),
                    np.ones(b, np.float32), np.zeros((b, 2), np.uint32))

        if key[0] in ("decode", "draft"):
            _, b, t = key
            return (np.zeros(b, np.int32),          # tokens
                    np.zeros((b, t), np.int32),     # tables -> pad page
                    np.zeros(b, np.int32),          # positions
                    *sample_args(b))
        if key[0] == "verify":
            _, b, t = key
            return (np.zeros((b, self.speculate_k + 1), np.int32),
                    np.zeros((b, t), np.int32),
                    np.zeros(b, np.int32),
                    *sample_args(b))
        _, b, s = key
        t = self._prefill_table_cols(s)
        return (np.zeros((b, s), np.int32), np.ones(b, np.int32),
                np.zeros((b, t), np.int32), *sample_args(b))

    def _jitted(self, key):
        if key[0] == "draft":
            return self._jit_draft
        if key[0] == "verify":
            return self._jit_verify
        return super()._jitted(key)

    # -------------------------------------------------------------- calls
    def prefill(self, ck, cv, tokens, lengths, tables,
                temps, top_ks, top_ps, rkeys):
        key = ("prefill", int(tokens.shape[0]), int(tokens.shape[1]))
        args = (tokens, lengths, tables, temps, top_ks, top_ps, rkeys)
        ex = self._aot.get(key)
        if ex is not None:
            return ex(self.params, ck, cv, *args)
        return self._jit_prefill(self.params, ck, cv, *args)

    def decode(self, ck, cv, tokens, tables, positions,
               temps, top_ks, top_ps, rkeys):
        key = ("decode", int(tokens.shape[0]), int(tables.shape[1]))
        args = (tokens, tables, positions, temps, top_ks, top_ps, rkeys)
        ex = self._aot.get(key)
        if ex is not None:
            return ex(self.params, ck, cv, *args)
        return self._jit_decode(self.params, ck, cv, *args)

    def draft(self, ck, cv, tokens, tables, positions,
              temps, top_ks, top_ps, rkeys):
        """One draft dispatch: k truncated-layer steps, proposals [B, k]."""
        key = ("draft", int(tokens.shape[0]), int(tables.shape[1]))
        args = (tokens, tables, positions, temps, top_ks, top_ps, rkeys)
        ex = self._aot.get(key)
        if ex is not None:
            return ex(self.draft_params, ck, cv, *args)
        return self._jit_draft(self.draft_params, ck, cv, *args)

    def verify(self, ck, cv, tokens, tables, positions,
               temps, top_ks, top_ps, rkeys):
        """One verify dispatch: full-model scores at all k+1 positions."""
        key = ("verify", int(tokens.shape[0]), int(tables.shape[1]))
        args = (tokens, tables, positions, temps, top_ks, top_ps, rkeys)
        ex = self._aot.get(key)
        if ex is not None:
            return ex(self.params, ck, cv, *args)
        return self._jit_verify(self.params, ck, cv, *args)


class DecodeEngine(EngineBase):
    """GPT decode serving with true continuous batching.

    ``model`` is a live ``models.gpt.GPTForCausalLM`` (eval mode; its
    device weights are shared zero-copy with training/export users).
    Requests (:meth:`submit`) join the running batch at the next step
    boundary and leave the step they finish — the scheduler runs ONE
    prefill-or-decode program call per step against the warmed rung
    set, so ``compiles_after_warmup == 0`` holds under any mix of
    prefill and decode traffic (JX330), the KV pool footprint never
    moves after warmup (JX332), and greedy tokens are bit-exact with a
    single-request decode of the same prompt.

    Two KV residency modes (``kv_mode``):

    - ``"paged"`` (default, ISSUE 18): a :class:`~.kv_cache.KVPagePool`
      holds fixed-size pages; each request owns only the pages its live
      tokens fill, named by a per-request block table that rides the
      compiled programs as TRACED int32 data — one executable per
      (batch rung × table rung), any page map. Mixed 128–4k contexts
      stop stranding worst-case rows, admission waits for pages instead
      of shedding, and sampled decoding (``temperature``/``top_k``/
      ``top_p``/``seed`` on :meth:`submit`) draws from a per-request
      PRNG stream that is deterministic per seed and independent of
      batch composition.
    - ``"slots"`` (PR 13): one full ``max_seq`` row per request — the
      greedy bit-exact oracle the paged mode is audited against.
    """

    def __init__(self, model, *,
                 max_slots: Optional[int] = None,
                 max_seq: Optional[int] = None,
                 seq_buckets: Optional[Sequence[int]] = None,
                 prefill_max_batch: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 kv_dtype: str = "float32",
                 kv_mode: str = "paged",
                 page_size: Optional[int] = None,
                 pool_pages: Optional[int] = None,
                 speculate_k: Optional[int] = None,
                 spec_draft_layers: Optional[int] = None,
                 spec_min_accept: Optional[float] = None,
                 max_queue: Optional[int] = None,
                 tenant_quota: Optional[int] = None,
                 request_ttl_ms: Optional[float] = None,
                 serve_telemetry_port: Optional[int] = None,
                 stats=serving_stats):
        from ..jit.bucketing import powers_of_two_buckets

        super().__init__(max_queue=max_queue, tenant_quota=tenant_quota,
                         request_ttl_ms=request_ttl_ms,
                         serve_telemetry_port=serve_telemetry_port,
                         stats=stats)
        if kv_mode not in ("paged", "slots"):
            raise ValueError(f"kv_mode must be 'paged' or 'slots', "
                             f"got {kv_mode!r}")
        cfg = model.config
        max_slots = int(get_flag("serving_max_slots")
                        if max_slots is None else max_slots)
        flag_seq = int(get_flag("serving_max_seq"))
        max_seq = int(max_seq if max_seq is not None
                      else (flag_seq or cfg.max_position_embeddings))
        if max_seq > cfg.max_position_embeddings:
            raise ValueError(
                f"max_seq {max_seq} exceeds the model's position table "
                f"({cfg.max_position_embeddings})")
        prefill_max = int(get_flag("serving_prefill_max_batch")
                          if prefill_max_batch is None else prefill_max_batch)
        prefill_max = min(prefill_max, max_slots)
        if seq_buckets is None:
            seq_min = min(int(get_flag("serving_seq_bucket_min")), max_seq)
            # clamp the top rung: the power-of-two ladder rounds UP past a
            # non-power-of-two max_seq, but a slot can't hold more rows
            seq_buckets = sorted({min(s, max_seq) for s in
                                  powers_of_two_buckets(seq_min, max_seq)})
        seq_buckets = sorted(int(s) for s in seq_buckets)
        if seq_buckets[-1] > max_seq:
            raise ValueError(f"seq bucket {seq_buckets[-1]} exceeds "
                             f"max_seq {max_seq}")
        spec_k = int(get_flag("serving_spec_k")
                     if speculate_k is None else speculate_k)
        spec_k = max(spec_k, 0)
        if kv_mode == "slots" and spec_k > 0:
            raise ValueError(
                "self-speculative decoding rides the paged block tables; "
                "the slots-mode engine is the greedy oracle — use "
                "kv_mode='paged' for speculate_k > 0")
        self.kv_mode = kv_mode
        self.max_slots = max_slots  # max concurrent lanes in either mode
        self.eos_id = eos_id
        self.speculate_k = spec_k
        self._model = model  # the weight source swap_weights re-extracts
        from ..reliability.policy import RetryPolicy

        retry = RetryPolicy("serving.decode_step")
        if kv_mode == "slots":
            self.kv_pool = KVSlotPool(
                cfg.num_hidden_layers, max_slots, max_seq,
                cfg.num_attention_heads, cfg.head_dim, dtype=kv_dtype)
            self.programs = DecodePrograms(
                model, self.kv_pool,
                seq_ladder=seq_buckets,
                prefill_batch_rungs=powers_of_two_buckets(1, prefill_max),
                decode_rungs=powers_of_two_buckets(1, max_slots))
            self._scheduler = DecodeScheduler(
                self.queue, self.programs, self.kv_pool,
                prefill_max_batch=prefill_max, eos_id=eos_id, stats=stats,
                retry=retry, breakers=self.breakers)
        else:
            ps = int(get_flag("serving_page_size")
                     if page_size is None else page_size)
            n_pages = int(get_flag("serving_pool_pages")
                          if pool_pages is None else pool_pages)
            if n_pages <= 0:
                # equal-bytes default: the token capacity the slot pool
                # this replaces would have held (max_slots full rows)
                n_pages = -(-max_slots * max_seq // ps)
            self.kv_pool = KVPagePool(
                cfg.num_hidden_layers, n_pages, ps,
                cfg.num_attention_heads, cfg.head_dim, dtype=kv_dtype)
            self.programs = PagedDecodePrograms(
                model, self.kv_pool,
                seq_ladder=seq_buckets,
                prefill_batch_rungs=powers_of_two_buckets(1, prefill_max),
                decode_rungs=powers_of_two_buckets(1, max_slots),
                max_seq=max_seq,
                speculate_k=spec_k,
                draft_layers=spec_draft_layers)
            self._scheduler = PagedDecodeScheduler(
                self.queue, self.programs, self.kv_pool,
                max_lanes=max_slots, prefill_max_batch=prefill_max,
                eos_id=eos_id, stats=stats, retry=retry,
                breakers=self.breakers,
                speculate_k=spec_k,
                spec_min_accept=spec_min_accept)

    # ------------------------------------------------------------ lifecycle
    def warmup(self) -> "DecodeEngine":
        """Arm every prefill/decode rung (compile-cache restore or AOT
        compile), freeze the KV pool footprint baseline, start the decode
        loop."""
        self.programs.warmup()
        self.kv_pool.mark_warm()
        self._start_serving()
        return self

    # ------------------------------------------------------------- serving
    def submit(self, tenant: str, prompt, max_new_tokens: int = 16,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0, seed: int = 0,
               speculate: Optional[bool] = None) -> DecodeRequest:
        """Enqueue one generation request; returns the future. The prompt
        must fit the seq ladder; generation stops at ``max_new_tokens``,
        the engine's ``eos_id``, or the ``max_seq`` capacity — whichever
        comes first.

        ``temperature == 0`` (default) decodes greedily — the bit-exact
        audit mode. A positive temperature samples with optional top-k /
        top-p truncation from the request's own PRNG stream (``seed``):
        deterministic per seed, independent of batch composition. The
        sampling knobs ride the compiled programs as traced data (paged
        engines); a slots-mode engine serves greedy only.

        ``speculate`` opts the request in or out of self-speculative
        decoding (``None`` = the engine default: on iff the engine was
        built with ``speculate_k > 0``). Speculation never changes the
        token stream — committed tokens always come from the full-model
        verify pass — only how many commit per full-model call."""
        if self.kv_mode == "slots" and temperature > 0:
            raise ValueError("sampled decoding needs kv_mode='paged'; "
                             "the slot-pool engine is the greedy oracle")
        if speculate and not self.speculate_k:
            raise ValueError(
                "speculate=True needs an engine built with speculate_k > 0 "
                "(or FLAGS_serving_spec_k) — the draft/verify programs are "
                "compile-time families, not a per-request switch")
        if not self._started:
            raise RuntimeError("engine not started: call warmup() first")
        spec = bool(self.speculate_k) if speculate is None else bool(speculate)
        req = DecodeRequest(tenant, prompt, max_new_tokens,
                            temperature=temperature, top_k=top_k,
                            top_p=top_p, seed=seed, speculate=spec)
        top = self.programs.seq_ladder[-1]
        if req.prompt.size > top:
            raise ValueError(
                f"prompt of {req.prompt.size} tokens exceeds the largest "
                f"seq bucket ({top}); raise FLAGS_serving_max_seq or the "
                "seq ladder")
        if self.kv_mode == "paged":
            need = -(-int(req.prompt.size) // self.kv_pool.page_size)
            if need > self.kv_pool.num_pages:
                raise ValueError(
                    f"prompt needs {need} KV pages but the pool holds "
                    f"{self.kv_pool.num_pages} total; it could never be "
                    "admitted — raise FLAGS_serving_pool_pages")
        self.tenant(tenant)
        return self.queue.submit(req)

    def generate(self, tenant: str, prompt, max_new_tokens: int = 16,
                 timeout: Optional[float] = 120.0) -> np.ndarray:
        """submit + block: returns the generated token ids."""
        return self.submit(tenant, prompt, max_new_tokens).result(timeout)

    def active_requests(self) -> int:
        """Sequences currently holding a slot (decoding or awaiting
        prefill) — the JX333 slot-leak audit's liveness source."""
        return self._scheduler.active_count()

    def set_speculation(self, enabled: bool) -> bool:
        """Master toggle for self-speculative decoding, safe mid-flight:
        the scheduler picks the plain-decode or draft+verify path per
        step, and both program families were warmed together, so flipping
        this under live traffic costs zero retraces (the churn test's
        contract). Requires an engine built with ``speculate_k > 0``.
        Returns the previous setting."""
        if not self.speculate_k:
            raise ValueError("engine was built without speculation "
                             "(speculate_k == 0); nothing to toggle")
        prev = self._scheduler.spec_enabled
        self._scheduler.spec_enabled = bool(enabled)
        return prev

    # ------------------------------------------------------------ hot swap
    def swap_weights(self, source) -> dict:
        """Roll new weights into the live decode loop between two decode
        steps — KV slots intact, zero retraces, zero dropped requests
        (ISSUE 15). ``source`` is a sharded checkpoint directory (its
        tensor names must match the serving model's state_dict keys;
        values restore onto each parameter's current placement/dtype via
        the dtype-converting load, landing device-side NEXT TO the old
        weights) or a live ``GPTForCausalLM`` twin of the serving model.

        Running lanes keep their slots: tokens already cached attend
        unchanged, tokens emitted after the flip use the new weights —
        exactly the semantics of a served model picking up a mid-stream
        deploy. Requests wanting one-model generations should drain
        first; the engine itself never fails one over a swap.

        The source is never mutated: a checkpoint's values are staged
        through the serving model's tensors only long enough to
        re-extract the params pytree, then the original values are
        restored — the model object handed to the constructor keeps
        the weights its owner left in it. A live-model source becomes
        the engine's weight source for later dir-based swaps."""
        import os as _os
        import time as _time

        t0 = _time.perf_counter()
        if isinstance(source, (str, _os.PathLike)):
            from ..distributed.checkpoint.sharded import load_sharded_like

            model = self._model
            flat = dict(model.state_dict())
            new = load_sharded_like(str(source), flat)
            saved = {k: t._value for k, t in flat.items()}
            try:
                for k, t in flat.items():
                    t._value = new[k]
                n_leaves = self.programs.swap_params(model)
            finally:
                for k, t in flat.items():
                    t._value = saved[k]
        else:
            n_leaves = self.programs.swap_params(source)
            self._model = source
        try:
            from ..observability.metrics import registry

            registry.counter(
                "serving.weight_swaps",
                "zero-downtime weight hot-swaps committed into live "
                "predictors/engines").inc()
        except Exception:
            pass
        return {
            "n_leaves": n_leaves,
            "seconds": round(_time.perf_counter() - t0, 4),
            "compiles_after_warmup": self.compiles_after_warmup,
            "kv_slots_in_use": self.kv_pool.in_use(),
        }

    # ---------------------------------------------------------- accounting
    @property
    def compile_count(self) -> int:
        return self.programs.traces

    def telemetry_health(self) -> dict:
        health = super().telemetry_health()
        health.update(
            kv_slots=self.max_slots,
            active_requests=self.active_requests(),
        )
        if self.kv_mode == "paged":
            health.update(
                kv_mode="paged",
                kv_pages=self.kv_pool.num_pages,
                kv_page_size=self.kv_pool.page_size,
                kv_pages_in_use=self.kv_pool.in_use(),
            )
        else:
            health.update(kv_mode="slots",
                          kv_slots_in_use=self.kv_pool.in_use())
        return health

    def serving_report(self) -> dict:
        """Stats summary + the decode tier's contractual proofs."""
        report = self.stats.summary()
        report.update(
            n_tenants=len(self._tenants),
            seq_buckets=list(self.programs.seq_ladder),
            decode_rungs=list(self.programs.decode_rungs),
            prefill_batch_rungs=list(self.programs.prefill_batch_rungs),
            compiled_rungs=len(self.programs.warmed),
            restored_rungs=len(self.programs.restored),
            compiles_after_warmup=self.compiles_after_warmup,
            kv_pool_bytes=self.kv_pool.device_bytes(),
            kv_pool_bytes_constant=(
                self.kv_pool.bytes_at_warmup is None
                or self.kv_pool.device_bytes() == self.kv_pool.bytes_at_warmup),
            kv_slots=self.max_slots,
            kv_mode=self.kv_mode,
        )
        if self.kv_mode == "paged":
            util = self.kv_pool.utilization_report()
            report.update(
                table_rungs=list(self.programs.table_rungs),
                kv_pages=self.kv_pool.num_pages,
                kv_page_size=self.kv_pool.page_size,
                kv_pages_in_use=self.kv_pool.in_use(),
                kv_pool_utilization=round(util["mean"], 4),
                kv_shed_requests=self._scheduler.shed_count,
            )
            if self.speculate_k:
                report.update(
                    speculate_k=self.speculate_k,
                    spec_draft_layers=self.programs.draft_layers,
                    spec_enabled=self._scheduler.spec_enabled,
                )
        return report
