"""ServingEngine: multi-tenant continuous batching over a warm Predictor.

One engine = one exported model serving many tenants:

- every tenant gets a zero-copy ``Predictor.clone()`` — the device
  weights and the warm-compiled bucket ladder are shared process-wide,
  only the IO handles are per-tenant;
- client threads ``submit()`` and block on ``Request.result()``;
  admission control answers at the door (queue cap + tenant quota +
  priority tiers + request TTL);
- one scheduler thread continuously assembles mixed-size requests into
  bucketed batches (``jit.bucketing`` ladder) and replays the shared
  compiled specialization for the rung — ZERO retraces after
  ``warmup()``, which ``compiles_after_warmup`` proves and the
  ``analysis`` JX330 serving audit gates;
- per-request enqueue→admit→dispatch→complete latency and queue depth
  flow through ``profiler.pipeline.serving_stats``.

:class:`EngineBase` factors the tier's shared lifecycle (queue +
admission, tenant registry with mid-traffic churn, telemetry egress
server, the zero-retrace accounting) so the decode tier
(:class:`serving.decode.DecodeEngine` — device-resident KV cache,
slot-based join/leave) serves through the same front door.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from ..base.flags import get_flag
from ..inference import Config, Predictor
from ..observability.locks import named_lock
from ..observability.tracing import tracer
from ..profiler.pipeline import serving_stats
from ..reliability.faults import fault_point
from ..reliability.policy import BreakerBoard, RetryPolicy
from .request_queue import AdmissionController, Request, RequestQueue
from .scheduler import (Scheduler, fetch_outputs, scatter_outputs,
                        stack_requests)


class EngineBase:
    """Shared serving-engine chassis: request queue + admission control,
    per-tenant registry (live add/drop), the engine-owned telemetry
    exporter, and the ``compiles_after_warmup`` zero-retrace accounting.

    Subclasses provide: ``compile_count`` (their program's trace
    counter), ``_scheduler`` (an object with ``start``/``alive``/``join``),
    and their own ``warmup``/``submit`` shapes."""

    def __init__(self, *, max_queue: Optional[int] = None,
                 tenant_quota: Optional[int] = None,
                 request_ttl_ms: Optional[float] = None,
                 serve_telemetry_port: Optional[int] = None,
                 stats=serving_stats):
        self.stats = stats
        self._tenants: Dict[str, object] = {}
        self._tenant_lock = named_lock("serving.engine.tenants")
        # per-tenant circuit breakers (ISSUE 14): the scheduler feeds
        # success/failure per served tenant; an open breaker flips the
        # tenant to degraded — /healthz reflects it and admission sheds
        # its load at the door (AdmissionError reason="circuit")
        self.breakers = BreakerBoard()
        self.queue = RequestQueue(AdmissionController(
            max_queue=max_queue, tenant_quota=tenant_quota,
            request_ttl_ms=request_ttl_ms,
            breaker_board=self.breakers), stats=stats)
        self._compiles_at_warmup: Optional[int] = None
        self._started = False
        self._scheduler = None
        # telemetry egress (ISSUE 8): the engine owns one exporter thread.
        # None defers to FLAGS_telemetry_port (0 there = disabled); an
        # EXPLICIT integer always serves (0 = pick an ephemeral port, the
        # test/bench path). Started at warmup, stopped at shutdown.
        if serve_telemetry_port is None:
            flag_port = int(get_flag("telemetry_port"))
            self._telemetry_port = flag_port if flag_port > 0 else None
        else:
            self._telemetry_port = int(serve_telemetry_port)
        self._telemetry_port_explicit = serve_telemetry_port is not None
        self._telemetry_server = None

    # ------------------------------------------------------------ lifecycle
    def _start_serving(self) -> None:
        """Snapshot the compile counter, bind the exporter, start the
        scheduler thread — the tail of every subclass's ``warmup()``."""
        self._compiles_at_warmup = self.compile_count
        # bind the exporter port BEFORE the scheduler thread: an explicit
        # serve_telemetry_port that fails to bind raises with no stray
        # worker running, instead of leaving a half-started engine nobody
        # will shut down. A FLAGS_telemetry_port bind failure only degrades
        # (telemetry must never take down serving): every engine in the
        # process resolves the same flag port, so the second one would
        # always lose the race.
        if self._telemetry_port is not None and self._telemetry_server is None:
            from ..observability.export import TelemetryServer

            try:
                self._telemetry_server = TelemetryServer(
                    port=self._telemetry_port,
                    health_fn=self.telemetry_health).start()
            except OSError as e:
                if self._telemetry_port_explicit:
                    raise
                from ..base.log import get_logger
                get_logger().warning(
                    "telemetry exporter port %d unavailable (%s); "
                    "serving continues without egress", self._telemetry_port, e)
        if not self._started:
            self._scheduler.start()
            self._started = True

    def shutdown(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop admitting; with ``drain`` serve everything already
        admitted before the scheduler exits, otherwise fail pending
        requests with :class:`RejectedError`."""
        from .request_queue import RejectedError

        self.queue.close()
        if not drain:
            self.queue.fail_pending(RejectedError("serving engine shut down"))
        try:
            if self._started:
                if not self._scheduler.join(timeout):
                    raise TimeoutError("serving scheduler did not drain in "
                                       f"{timeout}s")
                self._started = False
        finally:
            if self._telemetry_server is not None:
                self._telemetry_server.stop()
                self._telemetry_server = None

    def __enter__(self):
        return self.warmup()

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=exc == (None, None, None))

    # ------------------------------------------------------------ tenants
    def tenant(self, name: str):
        """Register (or fetch) a tenant lane. The batch engine overrides
        this to materialize a Predictor clone; the decode tier only needs
        the stats lane and the admission identity."""
        with self._tenant_lock:
            if name not in self._tenants:
                self._tenants[name] = None
            return self._tenants[name]

    @property
    def tenants(self) -> List[str]:
        with self._tenant_lock:
            return sorted(self._tenants)

    def drop_tenant(self, name: str) -> bool:
        """Retire a tenant mid-traffic: its clone/lane is forgotten and
        its stats ring retired. Requests already admitted still complete
        (their futures are never dropped); only NEW identity is released.
        Returns whether the tenant existed."""
        with self._tenant_lock:
            existed = name in self._tenants
            self._tenants.pop(name, None)
        if hasattr(self.stats, "retire_tenant"):
            self.stats.retire_tenant(name)
        return existed

    def set_tenant_tier(self, name: str, tier) -> None:
        """Pin a tenant's admission priority: ``"interactive"`` (default)
        or ``"bulk"`` — bulk tenants yield queue headroom and scheduling
        order to interactive ones (preemption at admission)."""
        self.queue.admission.set_tier(name, tier)

    # ------------------------------------------------------------ telemetry
    @property
    def telemetry_url(self) -> Optional[str]:
        """The engine-owned exporter's base URL (None when not serving)."""
        srv = self._telemetry_server
        return srv.url if srv is not None else None

    def telemetry_health(self) -> dict:
        """The ``/healthz`` payload: scheduler-worker liveness (the one
        thread whose death silently strands every queued request), queue
        depth and the zero-retrace proof. ``ok`` follows worker liveness
        while the engine is supposed to be serving."""
        alive = self._scheduler.alive() if self._scheduler else False
        open_circuits = self.breakers.open_keys()
        return {
            # degraded ≠ dead: open circuits shed their own tenants while
            # the rest keep serving, so "ok" stays worker-liveness
            "ok": bool(alive) if self._started else True,
            "health": "degraded" if open_circuits else "ok",
            "open_circuits": open_circuits,
            "worker_alive": bool(alive),
            "started": self._started,
            "queue_depth_requests": len(self.queue),
            "queue_depth_samples": self.queue.depth_samples(),
            "compiles_after_warmup": self.compiles_after_warmup,
            "tenants": len(self._tenants),
        }

    # ------------------------------------------------------------ accounting
    @property
    def compile_count(self) -> int:  # subclass contract
        raise NotImplementedError

    @property
    def compiles_after_warmup(self) -> Optional[int]:
        """The zero-retrace proof: compiled specializations added SINCE
        warmup (None before warmup). Steady state must hold this at 0;
        the JX330 serving audit errors otherwise."""
        if self._compiles_at_warmup is None:
            return None
        return self.compile_count - self._compiles_at_warmup


class ServingEngine(EngineBase):
    """Continuous bucketed batching over one warm-compiled model.

    ``model``: a path prefix (as given to ``jit.save``) or a ready
    :class:`inference.Predictor`. ``buckets`` overrides the batch ladder
    (default: powers of two up to ``FLAGS_serving_max_batch``). Models
    exported with a second (sequence) symbolic dim serve from the
    two-axis (batch x seq) bucket grid: assembly pads both axes and the
    warmed grid covers every pair."""

    def __init__(self, model: Union[str, Predictor], *,
                 buckets: Optional[Sequence[int]] = None,
                 seq_buckets: Optional[Sequence[int]] = None,
                 max_queue: Optional[int] = None,
                 tenant_quota: Optional[int] = None,
                 request_ttl_ms: Optional[float] = None,
                 linger_ms: Optional[float] = None,
                 serve_telemetry_port: Optional[int] = None,
                 stats=serving_stats):
        super().__init__(max_queue=max_queue, tenant_quota=tenant_quota,
                         request_ttl_ms=request_ttl_ms,
                         serve_telemetry_port=serve_telemetry_port,
                         stats=stats)
        self.predictor = (model if isinstance(model, Predictor)
                          else Predictor(Config(model)))
        if buckets is not None:
            self.predictor.set_batch_ladder(buckets)
        if seq_buckets is not None:
            self.predictor.set_seq_ladder(seq_buckets)
        linger = (float(get_flag("serving_batch_timeout_ms"))
                  if linger_ms is None else float(linger_ms)) / 1e3
        prog = self.predictor._ensure_batch_program()
        self._n_inputs = len(self.predictor.get_input_names())
        self._dynamic_axes = dict(prog.dynamic_axes)
        # the second bucket axis: {input_idx: seq_axis} of rank-1 dims
        self._seq_axes = {i: ax for (i, ax), r in prog.dynamic_ranks.items()
                          if r == 1}
        # bounded-retry program calls (ISSUE 14): a transiently failed
        # batch replays through the SAME _execute before the fault wall
        # gives it up — _complete/_fail are first-result-wins, so a
        # replay can never double-resolve a future
        self._scheduler = Scheduler(
            self.queue, self._execute, lambda: prog.ladder,
            linger_s=linger, on_batch=self._on_batch,
            retry=RetryPolicy("serving.execute"), breakers=self.breakers)

    # ------------------------------------------------------------ lifecycle
    def warmup(self) -> "ServingEngine":
        """AOT-compile the whole bucket ladder (the full two-axis grid on
        seq-dynamic exports), snapshot the compile counter (the
        steady-state zero-retrace baseline), start the scheduler thread."""
        self.predictor.warmup_ladder()
        self._start_serving()
        return self

    # ------------------------------------------------------------ tenants
    def tenant(self, name: str) -> Predictor:
        """The tenant's own Predictor clone (weights + compiled ladder
        shared zero-copy with every other tenant; IO handles private) —
        reference ``AnalysisPredictor::Clone`` multi-tenant idiom."""
        with self._tenant_lock:
            pred = self._tenants.get(name)
            if pred is None:
                pred = self._tenants[name] = self.predictor.clone()
            return pred

    # ------------------------------------------------------------ serving
    def submit(self, tenant: str, inputs, n: Optional[int] = None) -> Request:
        """Enqueue ``n`` samples for ``tenant``; returns the
        :class:`Request` future. ``inputs``: one array or a list matching
        the model's inputs, each with ``n`` rows on its batch axis.
        Raises :class:`AdmissionError` when a gate refuses."""
        if not self._started:
            raise RuntimeError("engine not started: call warmup() first")
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        arrays = [np.asarray(a) for a in inputs]
        if n is None:
            idx0, ax0 = next(iter(self._dynamic_axes.items())) \
                if self._dynamic_axes else (0, 0)
            n = int(arrays[idx0].shape[ax0])
        max_batch = self.predictor.batch_ladder[-1]
        if n > max_batch:
            raise ValueError(
                f"request of {n} samples exceeds the largest bucket "
                f"({max_batch}); split it or raise FLAGS_serving_max_batch")
        seq = None
        if self._seq_axes:
            seq = max(int(arrays[i].shape[ax])
                      for i, ax in self._seq_axes.items())
            top = self.predictor.seq_ladder[-1]
            if seq > top:
                raise ValueError(
                    f"request sequence length {seq} exceeds the largest "
                    f"seq bucket ({top}); split it or raise the seq ladder")
        self.tenant(tenant)  # materialize the clone on first contact
        return self.queue.submit(Request(tenant, arrays, n, seq=seq))

    def run(self, tenant: str, inputs, n: Optional[int] = None,
            timeout: Optional[float] = 60.0) -> List[np.ndarray]:
        """submit + block: the synchronous convenience path."""
        return self.submit(tenant, inputs, n).result(timeout)

    def _execute(self, requests: List[Request], bucket: int) -> None:
        """One program call for one assembled batch (scheduler thread)."""
        prog = self.predictor._ensure_batch_program()
        seq_bucket = None
        if self._seq_axes:
            from ..jit.bucketing import bucket_for

            seq_bucket = bucket_for(max(r.seq or 1 for r in requests),
                                    prog.seq_ladder)
        stacked = stack_requests(requests, bucket, self._dynamic_axes,
                                 self._n_inputs, seq_axes=self._seq_axes,
                                 seq_bucket=seq_bucket)
        import jax

        fault_point("serving.execute")
        out = prog(stacked,
                   (bucket, seq_bucket) if seq_bucket is not None else bucket)
        # one batched D2H round per assembled batch, not one per leaf
        leaves = fetch_outputs(jax.tree_util.tree_leaves(
            out, is_leaf=lambda x: hasattr(x, "shape")))
        rows = scatter_outputs(leaves, requests, seq_bucket=seq_bucket,
                               out_seq_axes=prog.out_seq_axes)
        from ..observability.anomaly import monitor

        for r, outs in zip(requests, rows):
            self.queue.admission.on_complete(r.tenant, r.n)
            r._complete(outs)
            self.stats.record_request(r.t_enqueue, r.t_admit, r.t_dispatch,
                                      r.t_complete, r.n, tenant=r.tenant)
            if tracer.enabled:
                # the per-request lifecycle, emitted retroactively from the
                # Request's own perf_counter stamps onto a per-tenant lane
                # (track count = tenant count, bounded by admission): the
                # enqueue→complete span with its phase breakdown in args,
                # time-correlated with the serving.batch span that served it
                tracer.emit(
                    "serving.request", r.t_enqueue,
                    r.t_complete - r.t_enqueue,
                    track=f"serving.requests.{r.tenant}",
                    request_id=r.id, n=r.n, bucket=bucket,
                    queue_wait_ms=round((r.t_dispatch - r.t_admit) * 1e3, 3),
                    execute_ms=round((r.t_complete - r.t_dispatch) * 1e3, 3))
        if monitor.enabled:
            # serving batch close: the SLO-breach watcher sees every
            # completed request's latency + queue-wait share. Fed AFTER the
            # completion loop — a triggered forensic dump is disk I/O on
            # the scheduler thread and must not delay co-batched requests'
            # futures (the cooldown bounds it to one dump per kind window)
            for r in requests:
                monitor.on_serving_request(
                    r.t_complete - r.t_enqueue, r.t_dispatch - r.t_admit,
                    tenant=r.tenant)

    def _on_batch(self, n_samples: int, bucket: int, depth: int) -> None:
        self.stats.record_batch(n_samples, bucket)
        self.stats.record_queue_depth(depth)

    # ------------------------------------------------------------ hot swap
    def swap_weights(self, source) -> dict:
        """Roll a new checkpoint into this live engine under traffic —
        zero dropped requests, zero retraces (ISSUE 15). ``source`` is a
        sharded checkpoint directory or a ``{name: array}`` dict; the
        new weights load device-side next to the old ones (dtype- and
        placement-converting per tensor), then the shared batch
        program's parameter reference flips between two program calls.
        In-flight batches finish on the weights they started with; the
        next assembled batch serves the new ones. Every tenant clone
        shares the flip (one weight set process-wide by construction).

        Returns the :meth:`inference.Predictor.swap_weights` report plus
        ``compiles_after_warmup`` — which a swap can never move (same
        shapes + dtypes ⇒ same ladder executables)."""
        with tracer.span("serving.swap_weights", track="serving.scheduler",
                         source=str(source)[:120]):
            report = self.predictor.swap_weights(source)
        report["compiles_after_warmup"] = self.compiles_after_warmup
        return report

    # ------------------------------------------------------------ accounting
    @property
    def compile_count(self) -> int:
        return self.predictor.compile_count

    def serving_report(self) -> dict:
        """Stats summary + the recompile proof, one dict (bench payload)."""
        report = self.stats.summary()
        report.update(
            buckets=list(self.predictor.batch_ladder),
            seq_buckets=self.predictor.seq_ladder,
            # count under its own key: summary()["tenants"] is the
            # per-tenant latency breakdown and must survive the merge
            n_tenants=len(self._tenants),
            compiled_rungs=self.predictor.compile_count,
            compiles_after_warmup=self.compiles_after_warmup,
        )
        return report
