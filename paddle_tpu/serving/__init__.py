"""Multi-tenant serving tier: continuous bucketed batching over
warm-compiled predictors (the ROADMAP "millions of users" workload).

Layers (each its own module, composable and separately testable):

- :mod:`request_queue` — the front door: :class:`Request` futures,
  per-tenant :class:`AdmissionController` (sample-denominated queue cap +
  tenant quota, refusal at submit), the FIFO the scheduler drains;
- :mod:`scheduler`     — continuous batch assembly: FIFO prefix →
  ``jit.bucketing`` rung → ONE padded program call → rows scattered back;
  re-assembly between every pair of steps picks up what arrived mid-step;
- :mod:`engine`        — :class:`ServingEngine`: warm-compiles the bucket
  ladder through ``inference.Predictor.run_many``'s shared
  ``_BatchProgram``, clones the predictor per tenant (zero-copy weight
  sharing), runs the scheduler thread, and proves zero steady-state
  retraces (``compiles_after_warmup == 0``, audited by JX330).

Serving phase 2 (ISSUE 13) adds TRUE continuous batching for GPT decode:

- :mod:`kv_cache`      — :class:`KVSlotPool`: ONE device-resident K/V
  buffer pair ([layers, slots+1, seq, heads, dim], allocated once),
  free-list slot alloc/release, functional in-place row updates under
  donation;
- :mod:`decode`        — :class:`DecodePrograms` (functional GPT
  prefill/decode programs, one warm specialization per bucket rung,
  whole set restorable from the persistent compile cache) and
  :class:`DecodeEngine` (the decode front door: priority tiers, TTL,
  per-tenant lanes);
- :class:`DecodeScheduler` (in :mod:`scheduler`) — one
  prefill-or-decode program call per step; requests join freed slots
  mid-flight and leave the step they finish — no batch re-assembly.

Latency accounting (enqueue→admit→dispatch→complete, queue depth,
p50/p99, requests/sec at FLAGS_serving_slo_ms, the prefill-vs-decode
step split and decode tokens/sec) flows through
``profiler.pipeline.serving_stats``; ``bench.py`` publishes it as
``extras.serving``.

    engine = serving.ServingEngine("ckpt/model", buckets=[1, 2, 4, 8])
    engine.warmup()
    out, = engine.run("tenant-a", batch_of_3)       # blocks, 3 rows back
    req = engine.submit("tenant-b", batch_of_5)     # future
    ...
    req.result()
    engine.shutdown(drain=True)
"""
from .decode import DecodeEngine, DecodePrograms
from .engine import EngineBase, ServingEngine
from .kv_cache import KVSlotPool
from .request_queue import (AdmissionController, AdmissionError,
                            DecodeRequest, RejectedError, Request,
                            RequestQueue)
from .scheduler import (DecodeScheduler, Scheduler, scatter_outputs,
                        stack_requests)

__all__ = [
    "AdmissionController", "AdmissionError", "DecodeEngine",
    "DecodePrograms", "DecodeRequest", "DecodeScheduler", "EngineBase",
    "KVSlotPool", "RejectedError", "Request", "RequestQueue", "Scheduler",
    "ServingEngine", "scatter_outputs", "stack_requests",
]
