"""Optimizer base (reference: python/paddle/optimizer/optimizer.py:127 —
accumulator system, step :1897, minimize :1806).

TPU-native: `step()` updates parameter payloads functionally (async XLA
dispatch in eager; tracer writes under jit so the functionalizer captures
parameter/accumulator updates inside one compiled program). The per-parameter
update rule `_apply_one` is pure, so the same code serves eager and compiled
paths, and accumulators are state cells for distributed sharding (ZeRO stages
shard them over the mesh, paddle_tpu/distributed/sharding.py)."""
from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional

import jax.numpy as jnp

from ..base.enforce import enforce
from ..core.tensor import Tensor
from .lr import LRScheduler


class Optimizer:
    _accum_names: List[str] = []

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None, grad_clip=None, name=None):
        enforce(parameters is not None, "parameters must be provided (pass model.parameters())")
        if parameters and isinstance(parameters[0], dict):
            self._param_groups = []
            flat = []
            for group in parameters:
                g = dict(group)
                flat.extend(g["params"])
                self._param_groups.append(g)
            self._parameter_list = flat
        else:
            self._parameter_list = list(parameters)
            self._param_groups = [{"params": self._parameter_list}]
        self._learning_rate = learning_rate
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._accumulators: Dict[str, Dict[int, Tensor]] = defaultdict(dict)
        self._aux_state: Dict[str, Tensor] = {}
        # step counter lives in a Tensor cell so Adam-style bias correction is
        # traced state, not a python constant baked into compiled programs
        self._step_tensor = Tensor(jnp.asarray(0, jnp.int32), name="opt_step")
        self._lr_override = None  # traced LR injected by jit.TrainStep
        # zero1 plumbing (distributed/sharding/zero1.py): the per-step
        # engagement override injected by TrainStep(sharding=...) and the
        # strategy attached by group_sharded_parallel
        self._sharding_override = None
        self._zero1_strategy = None

    # ------------------------------------------------ lr
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        enforce(
            not isinstance(self._learning_rate, LRScheduler),
            "cannot set_lr when using an LRScheduler",
        )
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # ------------------------------------------------ accumulators
    def _get_accumulator(self, name: str, param: Tensor, fill=0.0, dtype=None) -> Tensor:
        store = self._accumulators[name]
        if id(param) not in store:
            v = jnp.full(param._value.shape, fill, dtype or jnp.float32)
            store[id(param)] = Tensor(v, stop_gradient=True, name=f"{param.name}_{name}")
        return store[id(param)]

    def _get_aux(self, name: str, init) -> Tensor:
        if name not in self._aux_state:
            self._aux_state[name] = Tensor(jnp.asarray(init), stop_gradient=True, name=name)
        return self._aux_state[name]

    # ------------------------------------------------ core
    def _collect_params_grads(self):
        out = []
        for group in self._param_groups:
            for p in group["params"]:
                if p.stop_gradient:
                    continue
                out.append((p, p._grad, group))
        return out

    def step(self):
        pgs = self._collect_params_grads()
        pg_for_clip = [(p, g) for p, g, _ in pgs if g is not None]
        if self._grad_clip is not None:
            clipped = self._grad_clip(pg_for_clip)
        else:
            clipped = pg_for_clip
        clip_map = {id(p): g for p, g in clipped}
        self._step_tensor._replace_value(self._step_tensor._value + 1)
        lr = self._lr_override if self._lr_override is not None else self.get_lr()
        # zero1 sharded weight update: when engaged (TrainStep override /
        # FLAGS_sharding_stage / group_sharded_parallel) every eligible
        # parameter's update runs in its 1/dp shard space — grad clipping
        # above stays on the full gradients, so clip semantics are
        # identical across tiers
        from ..distributed.sharding import zero1 as _zero1

        spec = _zero1.step_spec(self)
        strategy = _zero1.ensure_strategy(self) if spec is not None else None
        for p, _, group in pgs:
            g = clip_map.get(id(p))
            if g is None:
                continue
            group_lr = lr * p.optimize_attr.get("learning_rate", 1.0) * group.get("learning_rate", 1.0)
            wd = group.get("weight_decay", self._weight_decay)
            if strategy is not None:
                strategy.apply_one(self, p, g, group_lr, wd, spec)
            else:
                self._apply_one(p, g, group_lr, wd)

    def _apply_one(self, p: Tensor, g: Tensor, lr, weight_decay):
        raise NotImplementedError

    def _step_value(self):
        """Current step as a (possibly traced) array for update-rule math."""
        return self._step_tensor._value.astype(jnp.float32)

    @property
    def _step_count(self):
        import numpy as np

        v = self._step_tensor._value
        try:
            return int(np.asarray(v))
        except Exception:
            return v

    @_step_count.setter
    def _step_count(self, v):
        self._step_tensor._replace_value(jnp.asarray(int(v), jnp.int32))

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, [(p, p._grad) for p in self._parameter_list]

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.clear_grad()

    clear_gradients = clear_grad

    # ------------------------------------------------ regularization helper
    @staticmethod
    def _decayed_grad(p, g, weight_decay):
        """L2Decay-style regularization folded into the gradient (reference
        regularizer.py applied at optimize time)."""
        if weight_decay is None:
            return g._value
        coeff = getattr(weight_decay, "coeff", weight_decay)
        if p.regularizer is not None:
            coeff = getattr(p.regularizer, "coeff", coeff)
        return g._value + float(coeff) * p._value

    # ------------------------------------------------ state dict
    def _lookup_cell(self, store, p):
        """An accumulator cell for ``p``: the zero1 shard-space proxy's
        when the sharded update owns one, else the param's own."""
        if self._zero1_strategy is not None:
            return self._zero1_strategy.cell_for(store, p)
        return store.get(id(p))

    def state_dict(self):
        out = {}
        for name, store in self._accumulators.items():
            for p in self._parameter_list:
                cell = self._lookup_cell(store, p)
                if cell is not None:
                    out[f"{p.name}_{name}"] = cell
        if self._zero1_strategy is not None:
            for m in self._zero1_strategy.extra_state_cells():
                out[m.name] = m
        for k, v in self._aux_state.items():
            out[k] = v
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        out["@step"] = self._step_count
        return out

    def _prime_target(self, p):
        """The cell owner accumulator priming targets for ``p``: the
        zero1 shard-space proxy (pre-shaped + sharded) when the sharded
        update is engaged, else the param itself — primed cells must be
        the SAME cells the first step will update, or the GradScaler's
        overflow rollback snapshots dead state."""
        from ..distributed.sharding import zero1 as _zero1

        spec = _zero1.step_spec(self)
        if spec is None:
            return p
        return _zero1.ensure_strategy(self).prime_proxy(p, spec)

    def _prime_accumulators(self):
        """Eagerly create every accumulator (GradScaler snapshots and the jit
        functionalizer need the full cell set before the first step)."""
        for p in self._parameter_list:
            if p.stop_gradient:
                continue
            target = self._prime_target(p)
            for name in self._accum_names:
                self._get_accumulator(name, target)

    def set_state_dict(self, state):
        import numpy as np

        for p in self._parameter_list:
            for name in self._accum_names:
                key = f"{p.name}_{name}"
                if key in state:
                    src = state[key]
                    arr = src.numpy() if isinstance(src, Tensor) else np.asarray(src)
                    existing = self._lookup_cell(self._accumulators[name], p)
                    if existing is not None:
                        existing.set_value(arr)
                    else:
                        self._get_accumulator(name, p).set_value(arr)
        strategy = self._zero1_strategy
        if strategy is None and any(k.endswith("_zero1_master")
                                    for k in state):
            # a fresh optimizer restoring a master-carrying state: attach
            # the strategy so the masters land instead of being dropped
            from ..distributed.sharding import zero1 as _zero1

            if _zero1.step_spec(self, explicit="zero1") is not None:
                strategy = _zero1.ensure_strategy(self)
        if strategy is not None:
            strategy.restore_masters(self, state)
        for k in list(self._aux_state):
            if k in state:
                src = state[k]
                arr = src.numpy() if isinstance(src, Tensor) else np.asarray(src)
                self._aux_state[k].set_value(arr)
        if "LR_Scheduler" in state and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state["LR_Scheduler"])
        if "@step" in state:
            self._step_count = int(state["@step"])

    # ------------------------------------------------ introspection for jit/sharding
    def _state_cells(self):
        """All mutable Tensors owned by the optimizer (jit functionalizer +
        ZeRO sharding enumerate these)."""
        cells = []
        for store in self._accumulators.values():
            cells.extend(store.values())
        cells.extend(self._aux_state.values())
        if self._zero1_strategy is not None:
            cells.extend(self._zero1_strategy.extra_state_cells())
        return cells
